"""mxsum256 — keyed linear bitrot checksum as one int8 MXU matmul.

The production device-side bitrot hash, fused into the same launch as the
erasure codec (the role HighwayHash-256 plays host-side in the reference:
every shard chunk hashed while hot, cmd/bitrot-streaming.go:46). Where
ops/mxhash.py chains GF(2) compressions (a Merkle-Damgard walk, ~4k int
ops/byte), mxsum is a single linear map — the cheapest construction the MXU
can evaluate (~16 ops/byte) and the only one whose cost is independent of
chunk length *per compiled program*:

    digest_c = sum_i data_i * K[i, c]  +  sum_j len_le[j] * L[j, c]   (mod 2^32)

with c = 0..7 int32 columns (32-byte digest), K an unbounded keyed stream of
int8 rows derived from BITROT_KEY (PCG64), and L a fixed int8 length key.

Zero padding is free: padded tail bytes contribute 0, so a chunk of any
length s <= cap hashes identically under any cap — one compiled program
serves every chunk length (the length rides in as *data*, not shape), and
ragged final chunks join the same batched launch as full chunks. This is
what makes the hash fusable into the serving PutObject/GetObject paths
without compile-cache blowups.

Detection model (bitrot = random corruption, not an auth boundary — same
threat model as the reference's fixed magicHighwayHash256Key,
cmd/bitrot.go:31): a corruption e != 0 escapes iff e . K[:, c] == 0 mod 2^32
for all 8 columns simultaneously. A single flipped byte always perturbs
column c unless K[i, c] == 0 (each |e * K[i,c]| < 2^16, no wrap), so
single-byte rot escapes only at the ~2^-64 chance that all 8 key bytes for
that position are zero; a random multi-byte corruption escapes with
probability ~2^-256 (the kernel fraction of a full-rank map into Z_2^32^8).
Truncation/extension is caught by the L term.

Host fallback is pure numpy (exact int64 accumulation then mod 2^32 —
bit-identical to the device's wrapping int32 accumulation); tests and CPU
backends use it, device backends verify in batches on-device.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

DIGEST_LEN = 32
COLS = 8  # int32 words per digest

_KEY_CHUNK = 1 << 16  # K-stream generation granularity (rows)
_key_lock = threading.Lock()
_key_i8 = np.zeros((0, COLS), dtype=np.int8)
_key_i64 = np.zeros((0, COLS), dtype=np.int64)


def _grow_key(n_rows: int) -> None:
    global _key_i8, _key_i64
    from minio_tpu.ops.bitrot import BITROT_KEY

    seed = int.from_bytes(BITROT_KEY[8:16], "little") ^ 0x6D78_73756D  # "mxsum"
    with _key_lock:
        have = _key_i8.shape[0]
        if have >= n_rows:
            return
        n_chunks = -(-n_rows // _KEY_CHUNK)
        parts = [_key_i8]
        for ci in range(have // _KEY_CHUNK, n_chunks):
            rng = np.random.Generator(np.random.PCG64(seed + ci))
            parts.append(rng.integers(-128, 128, (_KEY_CHUNK, COLS), dtype=np.int8))
        _key_i8 = np.concatenate(parts, axis=0)
        _key_i64 = _key_i8.astype(np.int64)


def _key_rows(n_rows: int) -> np.ndarray:
    """First n_rows of the keyed int8 stream K, shape [n_rows, 8]. K[:a] is
    always a prefix of K[:b] — a chunk's digest must not depend on the cap
    it was hashed under."""
    if _key_i8.shape[0] < n_rows:
        _grow_key(n_rows)
    return _key_i8[:n_rows]


def _key_rows_i64(n_rows: int) -> np.ndarray:
    if _key_i64.shape[0] < n_rows:
        _grow_key(n_rows)
    return _key_i64[:n_rows]


@functools.lru_cache(maxsize=1)
def _len_key() -> np.ndarray:
    from minio_tpu.ops.bitrot import BITROT_KEY

    seed = int.from_bytes(BITROT_KEY[16:24], "little") ^ 0x6C656E
    rng = np.random.Generator(np.random.PCG64(seed))
    return rng.integers(-128, 128, (8, COLS), dtype=np.int8)


def digest_np(data: bytes | np.ndarray) -> bytes:
    """Host digest of one chunk (numpy, exact)."""
    arr = (np.frombuffer(data, dtype=np.uint8)
           if isinstance(data, (bytes, bytearray, memoryview)) else data)
    s = arr.size
    if s:
        acc = arr.astype(np.int8).astype(np.int64) @ _key_rows_i64(s)
    else:
        acc = np.zeros(COLS, np.int64)
    lrow = np.frombuffer(np.uint64(s).tobytes(), dtype=np.uint8)
    acc = acc + lrow.astype(np.int8).astype(np.int64) @ _len_key().astype(np.int64)
    return (acc & 0xFFFFFFFF).astype("<u4").tobytes()


def digest_batch_np(chunks: np.ndarray, lengths) -> np.ndarray:
    """Host batched digest: chunks [B, S] u8 (each row zero-padded beyond
    its length), lengths [B]. Returns [B, 32] u8."""
    b, s = chunks.shape
    if s:
        acc = chunks.astype(np.int8).astype(np.int64) @ _key_rows_i64(s)
    else:
        acc = np.zeros((b, COLS), np.int64)
    lrows = np.ascontiguousarray(
        np.asarray(lengths, dtype=np.uint64)).view(np.uint8).reshape(b, 8)
    acc = acc + lrows.astype(np.int8).astype(np.int64) @ _len_key().astype(np.int64)
    return (acc & 0xFFFFFFFF).astype("<u4").view(np.uint8).reshape(b, DIGEST_LEN)


# --- device path -------------------------------------------------------------


def len_term_device(lengths):
    """Device length-key contribution: lengths [B] (< 2^32) -> [B, 8] i32.
    Only the low 4 LE bytes are nonzero (no uint64 on device; the host's
    key rows 4-7 multiply zeros), so L[:4] suffices."""
    import jax
    import jax.numpy as jnp

    lengths = lengths.astype(jnp.uint32)
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    lrows = ((lengths[:, None] >> shifts) & jnp.uint32(0xFF)).astype(jnp.int8)
    return jax.lax.dot_general(
        lrows, jnp.asarray(_len_key()[:4]),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def pack_words_device(acc):
    """Device digest framing: acc [B, 8] i32 -> [B, 32] u8 (LE words)."""
    import jax.numpy as jnp

    w = acc.astype(jnp.uint32)
    bshift = jnp.arange(4, dtype=jnp.uint32) * 8
    by = (w[:, :, None] >> bshift) & jnp.uint32(0xFF)          # [B, 8, 4]
    return by.reshape(w.shape[0], DIGEST_LEN).astype(jnp.uint8)


def digest_device(chunks, lengths):
    """Device batched digest: chunks [B, S] u8 (zero-padded beyond each
    row's length), lengths [B] int32/uint32 (< 2^32). Returns [B, 32] u8.

    jnp-traceable — call inside jit (the fused codec launches). One int8
    MXU contraction + a tiny length term; int32 accumulation wraps mod 2^32
    exactly like the host's int64-then-mask path.
    """
    import jax
    import jax.numpy as jnp

    b, s = chunks.shape
    acc = jnp.zeros((b, COLS), dtype=jnp.int32)
    if s:
        k = jnp.asarray(_key_rows(s))                          # [S, 8] i8
        acc = jax.lax.dot_general(
            chunks.astype(jnp.int8), k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)                  # [B, 8]
    return pack_words_device(acc + len_term_device(lengths))


class MXSum256:
    """Bitrot registry adapter (ops/bitrot.py register_algorithm)."""

    digest_len = DIGEST_LEN

    @staticmethod
    def digest(data: bytes) -> bytes:
        return digest_np(data)


def register() -> None:
    from minio_tpu.ops import bitrot

    bitrot.register_algorithm("mxsum256", MXSum256)
