"""GF(2^8) arithmetic, Reed-Solomon matrices, and the bit-matrix transform.

This is the host-side (numpy) foundation of the erasure codec. The reference
wraps klauspost/reedsolomon (cmd/erasure-coding.go:23,56), whose hot loops are
AVX2/AVX512 Galois multiply tables. On TPU there is no per-byte table-lookup
SIMD, so we use a different — and MXU-friendly — formulation:

    GF(2^8) is an 8-dimensional vector space over GF(2). Multiplication by a
    *constant* c is a linear map, i.e. an 8x8 bit-matrix B_c. A Reed-Solomon
    encode  parity[j] = XOR_i  M[j,i] * data[i]  therefore becomes one big
    GF(2) matrix product:

        out_bits[S, m*8] = in_bits[S, k*8] @ W[k*8, m*8]   (mod 2)

    with S = byte positions in a shard. Bits are materialized as {0,1}
    integers, the contraction runs on the MXU (bf16/int8 matmul is exact for
    sums < 2^8), and "mod 2" is a cheap elementwise epilogue. This mirrors
    what Intel GFNI (gf2p8affineqb) does in hardware, and is how the codec
    reaches matmul-unit throughput instead of gather throughput.

Everything in this file is pure numpy and runs at setup time (matrix
construction, inversion, bit-expansion) or in tests (bit-exact reference
encode). The device kernels live in rs_xla.py / rs_pallas.py.

Field: the standard Reed-Solomon GF(2^8) with reducing polynomial
x^8+x^4+x^3+x^2+1 (0x11D), generator 2 — same field as klauspost/reedsolomon,
so encodings are interoperable at the math level.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD_SIZE = 256


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 GF(2^8) multiplication table (64 KiB)."""
    a = np.arange(256)
    t = np.zeros((256, 256), dtype=np.uint8)
    la = GF_LOG[a[1:, None]]
    lb = GF_LOG[a[None, 1:]]
    t[1:, 1:] = GF_EXP[(la + lb) % 255]
    t.setflags(write=False)
    return t


def gf_mul(a, b):
    """Elementwise GF(2^8) multiply (numpy, any broadcastable shapes)."""
    return mul_table()[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]


def gf_pow(a: int, n: int) -> int:
    """a**n in GF(2^8); 0**0 == 1 (matches klauspost galExp)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by 0")
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) - int(GF_LOG[b])) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): (mul = table, add = xor)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # products[i, j, l] = a[i, l] * b[l, j]
    prod = mul_table()[a[:, None, :], b.T[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=2)


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination.

    Raises ValueError if singular (caller treats that as "too many shards
    lost" — the reference returns reedsolomon.ErrTooFewShards).
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError(f"not square: {m.shape}")
    aug = np.concatenate([m.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    mt = mul_table()
    for col in range(n):
        pivot = -1
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = mt[aug[col], inv_p]
        mask = aug[:, col].copy()
        mask[col] = 0
        # row_i ^= mask_i * row_col  (no-op where mask_i == 0)
        aug ^= mt[mask[:, None], aug[col][None, :]]
    return aug[:, n:]


# ---------------------------------------------------------------------------
# Reed-Solomon generator matrices
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def rs_generator_matrix(k: int, n: int) -> np.ndarray:
    """Systematic [n, k] Vandermonde generator matrix.

    Same construction as klauspost/reedsolomon buildMatrix (vendored by the
    reference via cmd/erasure-coding.go:56): take the n x k Vandermonde
    matrix V[r, c] = r**c (element exponent, 0**0 = 1), then right-multiply
    by the inverse of its top k x k block so the first k rows become the
    identity (data shards pass through unchanged, last n-k rows generate
    parity). Any k rows of the result are linearly independent (MDS).
    """
    if not (0 < k <= n <= FIELD_SIZE):
        raise ValueError(f"invalid RS shape k={k} n={n}")
    vm = np.zeros((n, k), dtype=np.uint8)
    for r in range(n):
        for c in range(k):
            vm[r, c] = gf_pow(r, c)
    top_inv = gf_mat_inv(vm[:k])
    g = gf_matmul(vm, top_inv)
    # Systematic by construction.
    assert np.array_equal(g[:k], np.eye(k, dtype=np.uint8))
    g.setflags(write=False)  # cached: callers must not mutate
    return g


def parity_matrix(k: int, m: int) -> np.ndarray:
    """[m, k] parity rows of the systematic generator (fresh copy)."""
    return rs_generator_matrix(k, k + m)[k:].copy()


def decode_matrix(k: int, n: int, survivors: tuple[int, ...], targets: tuple[int, ...]) -> np.ndarray:
    """[len(targets), k] matrix reconstructing `targets` shards from `survivors`.

    survivors: exactly k shard indices (0..n-1) that are intact.
    targets:   shard indices to (re)compute — missing data and/or parity.

    With G the systematic generator, surviving shards s_S = G[S] d, so
    d = inv(G[S]) s_S and s_T = G[T] inv(G[S]) s_S. The reference reaches the
    same math through reedsolomon.ReconstructData (cmd/erasure-coding.go:89).
    There are only C(n, <=m) failure patterns, so callers cache per-pattern
    matrices (this function is lru-cached at the bit-matrix level).
    """
    if len(survivors) != k:
        raise ValueError(f"need exactly k={k} survivors, got {len(survivors)}")
    g = rs_generator_matrix(k, n)
    sub = g[list(survivors)]
    inv = gf_mat_inv(sub)
    return gf_matmul(g[list(targets)], inv)


# ---------------------------------------------------------------------------
# Bit-matrix transform: GF(2^8) matrix -> GF(2) matrix for the MXU
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _const_mul_bitmatrices() -> np.ndarray:
    """[256, 8, 8] bit-matrix of multiply-by-c for every constant c.

    B[c, j, i] = bit j of (c * x^i): column i is the GF(2^8) product of c
    with the basis element x^i, decomposed into bits.
    """
    c = np.arange(256, dtype=np.uint8)
    basis = (1 << np.arange(8)).astype(np.uint8)          # x^i
    prod = mul_table()[c[:, None], basis[None, :]]         # [256, 8] : c * x^i
    bits = (prod[:, None, :] >> np.arange(8)[None, :, None]) & 1  # [256, j, i]
    bits = bits.astype(np.uint8)
    bits.setflags(write=False)
    return bits


def expand_to_bitmatrix(m: np.ndarray) -> np.ndarray:
    """Lift a GF(2^8) matrix [r, c] to a GF(2) matrix [c*8, r*8].

    Returned layout is (input_bits, output_bits), ready for
    out_bits[S, r*8] = in_bits[S, c*8] @ W (mod 2): W[ci*8 + bi, ro*8 + bo]
    = B[m[ro, ci]][bo, bi].
    """
    m = np.asarray(m, dtype=np.uint8)
    r, c = m.shape
    b = _const_mul_bitmatrices()[m]          # [r, c, 8(out), 8(in)]
    w = b.transpose(1, 3, 0, 2)              # [c, 8(in), r, 8(out)]
    w = np.ascontiguousarray(w.reshape(c * 8, r * 8))
    w.setflags(write=False)  # lru-cached by encode/decode_bitmatrix
    return w


@functools.lru_cache(maxsize=256)
def encode_bitmatrix(k: int, m: int) -> np.ndarray:
    """[k*8, m*8] GF(2) weights computing all m parity shards at once."""
    return expand_to_bitmatrix(parity_matrix(k, m))


@functools.lru_cache(maxsize=4096)
def decode_bitmatrix(
    k: int, n: int, survivors: tuple[int, ...], targets: tuple[int, ...]
) -> np.ndarray:
    """[k*8, t*8] GF(2) weights reconstructing `targets` from `survivors`."""
    return expand_to_bitmatrix(decode_matrix(k, n, survivors, targets))


# ---------------------------------------------------------------------------
# Bit-exact numpy reference codec (the ground truth for kernel tests)
# ---------------------------------------------------------------------------


def encode_ref(data: np.ndarray, m: int) -> np.ndarray:
    """Reference encode: data [k, S] u8 -> parity [m, S] u8 (table lookups)."""
    data = np.asarray(data, dtype=np.uint8)
    k = data.shape[0]
    pm = parity_matrix(k, m)                               # [m, k]
    prod = mul_table()[pm[:, :, None], data[None, :, :]]   # [m, k, S]
    return np.bitwise_xor.reduce(prod, axis=1)


def reconstruct_ref(
    shards: np.ndarray, k: int, survivors: tuple[int, ...], targets: tuple[int, ...]
) -> np.ndarray:
    """Reference reconstruct: shards [n, S] (rows outside survivors ignored)."""
    shards = np.asarray(shards, dtype=np.uint8)
    n = shards.shape[0]
    dm = decode_matrix(k, n, survivors, targets)           # [t, k]
    surv = shards[list(survivors)]                         # [k, S]
    prod = mul_table()[dm[:, :, None], surv[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=1)
