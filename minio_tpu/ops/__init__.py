"""TPU compute kernels: GF(2^8) erasure coding, bitrot hashing.

The reference delegates these to hand-written AVX2/AVX512 assembly
(klauspost/reedsolomon, minio/highwayhash — SURVEY.md §2.3). Here they are
batched TPU kernels built on a bit-matrix formulation of GF(2^8) arithmetic.
"""

from minio_tpu.ops import gf  # noqa: F401
