"""Pallas TPU kernel for the batched GF(2) Reed-Solomon encode.

Same math as ops/rs_xla.py (bit-lift → int8 MXU contraction → mod-2 →
byte-pack) hand-tiled as one Pallas kernel so the whole epilogue stays in
VMEM with the matmul: the unpack/pack never round-trips to HBM, which is
what bounds the XLA version at large batch. Grid = (batch, S/TILE); the
[k*8, m*8] weight block is resident in VMEM for every step.

The kernel is numerically identical to rs_xla.encode — tests assert
bit-exactness in interpreter mode; on hardware `use_pallas()` flips the
bench path (MTPU_USE_PALLAS=1, default on TPU backends).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from minio_tpu.ops import gf

TILE = 512  # lanes per grid step (last-dim multiple of 128)


def use_pallas() -> bool:
    env = os.environ.get("MTPU_USE_PALLAS", "")
    if env in ("0", "off"):
        return False
    if env in ("1", "on"):
        return True
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # noqa: BLE001
        return False


def _encode_kernel(k: int, m: int, ts: int, wt_ref, x_ref, o_ref):
    """One (batch, tile) step: x [k, ts] u8 → o [m, ts] u8.

    Everything stays in [rows, lanes] orientation — no transposes (Mosaic
    rejects narrow-type transposes); the weight arrives pre-transposed as
    [m*8, k*8] so the contraction directly yields [m*8, ts]."""
    x = x_ref[:].astype(jnp.int32)                          # [k, ts]
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, ts), 1)
    bits = ((x[:, None, :] >> shifts) & 1)                  # [k, 8, ts]
    bits = bits.reshape(k * 8, ts).astype(jnp.int8)
    y = jax.lax.dot_general(
        wt_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                   # [m*8, ts]
    y = y.reshape(m, 8, ts)
    pshift = jax.lax.broadcasted_iota(jnp.int32, (m, 8, ts), 1)
    # Parity bit of y placed at position i in one step: (y << i) & (1 << i).
    # (Masking with 1 first makes Mosaic narrow the vector to i1, which its
    # casts reject — mask after the shift instead.)
    masked = (y << pshift) & (jnp.int32(1) << pshift)
    # Sum == OR here (disjoint bit positions); Mosaic keeps additions wide
    # where it narrows OR-trees to i1.
    packed = jnp.sum(masked, axis=1, dtype=jnp.int32)       # [m, ts]
    o_ref[:] = packed.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "interpret"))
def encode(data: jax.Array, k: int, m: int,
           interpret: bool = False) -> jax.Array:
    """data [B, k, S] u8 -> parity [B, m, S] u8. S must divide by TILE
    (the streaming engine pads erasure blocks to lane multiples already;
    callers with ragged S use rs_xla)."""
    b, kk, s = data.shape
    assert kk == k and s % TILE == 0, (kk, s)
    w = jnp.asarray(gf.encode_bitmatrix(k, m).T.copy(), dtype=jnp.int8)
    kernel = functools.partial(_encode_kernel, k, m, TILE)
    return pl.pallas_call(
        kernel,
        grid=(b, s // TILE),
        in_specs=[
            pl.BlockSpec((m * 8, k * 8), lambda i, j: (0, 0)),
            pl.BlockSpec((None, k, TILE), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, m, TILE), lambda i, j: (i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((b, m, s), jnp.uint8),
        interpret=interpret,
    )(w, data)
