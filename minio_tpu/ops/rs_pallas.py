"""Pallas TPU kernels for the batched GF(2) Reed-Solomon codec.

Same math as ops/rs_xla.py (bit-lift → int8 MXU contraction → mod-2 →
byte-pack) hand-tiled as one Pallas kernel so the whole epilogue stays in
VMEM with the matmul: the unpack/pack never round-trips to HBM, which is
what bounds the XLA version at large batch. Grid = (batch, S/TILE); the
[t*8, k*8] weight block is resident in VMEM for every step.

One kernel serves encode AND reconstruct — both are GF(2) bit-matrix
contractions, only the weight differs (encode_bitmatrix vs the cached
per-failure-pattern decode_bitmatrix), mirroring the symmetry rs_xla
exploits (cmd/erasure-coding.go:70,89).

The kernels are numerically identical to rs_xla — tests assert
bit-exactness in interpreter mode; on hardware `use_pallas()` flips the
serving/bench path (MTPU_USE_PALLAS=1, default on TPU backends). Callers
with ragged S pad to TILE (ops/fused.py does; parity columns never mix so
padding is free) or fall back to rs_xla.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from minio_tpu.ops import gf

TILE = 512  # lanes per grid step (last-dim multiple of 128)


def use_pallas() -> bool:
    env = os.environ.get("MTPU_USE_PALLAS", "")
    if env in ("0", "off"):
        return False
    if env in ("1", "on"):
        return True
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # noqa: BLE001
        return False


def _gf2_kernel(kin: int, tout: int, ts: int, wt_ref, x_ref, o_ref):
    """One (batch, tile) step: x [kin, ts] u8 → o [tout, ts] u8.

    Everything stays in [rows, lanes] orientation — no transposes (Mosaic
    rejects narrow-type transposes); the weight arrives pre-transposed as
    [tout*8, kin*8] so the contraction directly yields [tout*8, ts]."""
    x = x_ref[:].astype(jnp.int32)                          # [kin, ts]
    shifts = jax.lax.broadcasted_iota(jnp.int32, (kin, 8, ts), 1)
    bits = ((x[:, None, :] >> shifts) & 1)                  # [kin, 8, ts]
    bits = bits.reshape(kin * 8, ts).astype(jnp.int8)
    y = jax.lax.dot_general(
        wt_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                   # [tout*8, ts]
    y = y.reshape(tout, 8, ts)
    pshift = jax.lax.broadcasted_iota(jnp.int32, (tout, 8, ts), 1)
    # Parity bit of y placed at position i in one step: (y << i) & (1 << i).
    # (Masking with 1 first makes Mosaic narrow the vector to i1, which its
    # casts reject — mask after the shift instead.)
    masked = (y << pshift) & (jnp.int32(1) << pshift)
    # Sum == OR here (disjoint bit positions); Mosaic keeps additions wide
    # where it narrows OR-trees to i1.
    packed = jnp.sum(masked, axis=1, dtype=jnp.int32)       # [tout, ts]
    o_ref[:] = packed.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("out_shards", "interpret"))
def gf2_matmul_with_weights(x: jax.Array, w_t: jax.Array, out_shards: int,
                            interpret: bool = False) -> jax.Array:
    """Raw tiled contraction: x [B, kin, S] u8, w_t [out*8, kin*8] i8
    (pre-transposed) -> [B, out, S] u8. S must divide by TILE."""
    b, kin, s = x.shape
    assert s % TILE == 0, s
    kernel = functools.partial(_gf2_kernel, kin, out_shards, TILE)
    return pl.pallas_call(
        kernel,
        grid=(b, s // TILE),
        in_specs=[
            pl.BlockSpec((out_shards * 8, kin * 8), lambda i, j: (0, 0)),
            pl.BlockSpec((None, kin, TILE), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, out_shards, TILE), lambda i, j: (i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((b, out_shards, s), jnp.uint8),
        interpret=interpret,
    )(w_t, x)


@functools.lru_cache(maxsize=256)
def _encode_weights_t(k: int, m: int) -> np.ndarray:
    return np.ascontiguousarray(gf.encode_bitmatrix(k, m).T, dtype=np.int8)


@functools.lru_cache(maxsize=4096)
def _decode_weights_t(k: int, n: int, survivors: tuple[int, ...],
                      targets: tuple[int, ...]) -> np.ndarray:
    return np.ascontiguousarray(
        gf.decode_bitmatrix(k, n, survivors, targets).T, dtype=np.int8)


def encode(data: jax.Array, k: int, m: int,
           interpret: bool = False) -> jax.Array:
    """data [B, k, S] u8 -> parity [B, m, S] u8. S must divide by TILE."""
    w_t = jnp.asarray(_encode_weights_t(k, m))
    return gf2_matmul_with_weights(data, w_t, m, interpret=interpret)


def reconstruct(shards: jax.Array, k: int, n: int,
                survivors: tuple[int, ...], targets: tuple[int, ...],
                interpret: bool = False) -> jax.Array:
    """Rebuild `targets` from any-k `survivors` (the heal/decode kernel —
    the other half of the north star, cmd/erasure-healing.go:401-461).

    shards [B, n, S] u8 with survivor rows meaningful; S % TILE == 0."""
    surv = shards[:, list(survivors[:k]), :]
    w_t = jnp.asarray(_decode_weights_t(k, n, tuple(survivors[:k]), tuple(targets)))
    return gf2_matmul_with_weights(surv, w_t, len(targets), interpret=interpret)
