"""Per-drive WAL journal format + replay fold (docs/METAPLANE.md).

One append-only file per drive at `<root>/.mtpu.sys/wal/journal.wal`:

    MAGIC "MTPUWAL1"
    record*   [u32 payload_len][u32 crc32(payload)][payload]
    payload = [u8 type][f64 mt][u16 vol_len][u16 path_len][u32 raw_len]
              [vol utf-8][path utf-8][raw journal bytes]

Types: COMMIT (full serialized journal for the key — the whole
`meta.mp` those bytes would become) and REMOVE (journal deletion; `mt`
is the wall clock at append, used only as a replay tiebreak against
state written by an unarmed process). Because *every* journal mutation
on an armed drive rides the WAL, the last record per key in file order
is the key's authoritative post-crash state.

Durability contract: a record counts only once the WAL fsync covering
it returns — `scan()` stops at the first short/corrupt frame, so a torn
tail (SIGKILL between append and fsync) cleanly truncates to the last
durable record; the write it carried was never acknowledged and is
legally lost.

Append is zero-copy: headers are packed once, CRC folds over the parts
sequentially (zlib.crc32 chaining), and the frame reaches the kernel as
an `os.writev` gather list — payload bytes are never joined or sliced
into fresh buffers on the hot path. The scan side is cold (mount-time
replay) and trades copies for simplicity.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, NamedTuple

MAGIC = b"MTPUWAL1"
REC_COMMIT = 1
REC_REMOVE = 2
# Prefix tombstone: an out-of-band recursive delete (session/tmp
# rmtree, volume force-delete) destroyed every journal under
# (volume, path-prefix); replay must drop all EARLIER records there.
REC_REMOVE_PREFIX = 3
# Blob records: raw sys files (multipart part journals, scanner
# checkpoints, sys-config docs) group-committed through the same WAL —
# `path` is the FILE path (not a journal key) and materialization is a
# tmp+rename write of the raw bytes with no per-file fsync. The frame
# format is identical; only the apply side dispatches differently.
REC_BLOB = 4
REC_BLOB_REMOVE = 5
# Replication intents (docs/REPLICATION.md): an acked PUT/DELETE on a
# replicated bucket journals its cross-cluster intent BEFORE the task
# enters the in-memory queue, and journals DONE only once the far
# cluster acknowledged — replay re-enqueues every intent without a
# matching DONE, so a SIGKILL between the S3 ack and the replication
# attempt cannot lose the intent. `volume` is the bucket, `path` the
# unique intent id, `raw` the msgpack task document. The replication
# journal rides the same frame format + torn-tail contract in its own
# segment (`replication.wal`); if one of these records ever lands in a
# drive journal it folds with blob semantics (intent = doc write,
# done = doc removal).
REC_REPL_INTENT = 6
REC_REPL_DONE = 7
# Closed record-type registry (static rule MTPU009, docs/ANALYSIS.md):
# every WAL dispatch site — the replay fold apply, the commit staging,
# the overlay publish — must handle every member or carry a written
# suppression; a record type added here without teaching replay would
# otherwise silently drop acked state at the next crash.
WAL_RECORD_TYPES = {
    "REC_COMMIT": REC_COMMIT,
    "REC_REMOVE": REC_REMOVE,
    "REC_REMOVE_PREFIX": REC_REMOVE_PREFIX,
    "REC_BLOB": REC_BLOB,
    "REC_BLOB_REMOVE": REC_BLOB_REMOVE,
    "REC_REPL_INTENT": REC_REPL_INTENT,
    "REC_REPL_DONE": REC_REPL_DONE,
}

_FRAME = struct.Struct("<II")       # payload_len, crc32
_HEAD = struct.Struct("<BdHHI")     # type, mt, vol_len, path_len, raw_len

# writev gather-list bound: 4 buffers per record, stay far under IOV_MAX.
_IOV_RECORDS = 128


class Record(NamedTuple):
    rtype: int
    mt: float
    volume: str
    path: str
    raw: bytes


def frame_record(rtype: int, mt: float, volume: str, path: str,
                 raw) -> list:
    """The writev gather list for one record: [frame+head, vol, path,
    raw]. `raw` may be bytes or a memoryview — it is never copied."""
    vb = volume.encode("utf-8")
    pb = path.encode("utf-8")
    head = _HEAD.pack(rtype, mt, len(vb), len(pb), len(raw))
    crc = zlib.crc32(head)
    crc = zlib.crc32(vb, crc)
    crc = zlib.crc32(pb, crc)
    crc = zlib.crc32(raw, crc)
    payload_len = len(head) + len(vb) + len(pb) + len(raw)
    return [_FRAME.pack(payload_len, crc) + head, vb, pb, raw]


def append_records(fd: int, recs: list[list]) -> int:
    """writev the framed records (already gather lists from
    frame_record) to an O_APPEND fd; returns bytes written. Chunked so
    one giant batch can't exceed IOV_MAX."""
    total = 0
    flat: list = []
    for gather in recs:
        flat.extend(gather)
        if len(flat) >= _IOV_RECORDS * 4:
            total += _writev_all(fd, flat)
            flat = []
    if flat:
        total += _writev_all(fd, flat)
    return total


def _writev_all(fd: int, bufs: list) -> int:
    want = sum(len(b) for b in bufs)
    done = os.writev(fd, bufs)
    while done < want:
        # Short writev (interrupt / pipe-ish fs): resume at the byte
        # offset without re-slicing whole buffers we already wrote.
        skip = done
        rest = []
        for b in bufs:
            if skip >= len(b):
                skip -= len(b)
                continue
            rest.append(memoryview(b)[skip:] if skip else b)
            skip = 0
        bufs = rest
        n = os.writev(fd, bufs)
        if n <= 0:
            raise OSError("wal writev stalled")
        done += n
    return want


def scan(path: str) -> Iterator[Record]:
    """Yield durable records in file order, stopping cleanly at the
    first torn or corrupt frame (everything after a torn tail was never
    fsync-acknowledged). A file without the magic yields nothing."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return
    if not data.startswith(MAGIC):
        return
    off = len(MAGIC)
    n = len(data)
    while off + _FRAME.size <= n:
        payload_len, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + payload_len
        if payload_len < _HEAD.size or end > n:
            return  # torn tail
        if zlib.crc32(data[start:end]) != crc:
            return  # corrupt frame: stop at last durable record
        rtype, mt, vl, pl, rl = _HEAD.unpack_from(data, start)
        so = start + _HEAD.size
        if so + vl + pl + rl != end:
            return
        vol = data[so:so + vl].decode("utf-8", "replace")
        key = data[so + vl:so + vl + pl].decode("utf-8", "replace")
        raw = data[so + vl + pl:end]
        yield Record(rtype, mt, vol, key, raw)
        off = end


def fold(path: str) -> dict[tuple[str, str], Record]:
    """Last-record-per-key fold of a WAL file — the replay work list.
    File order IS commit order (single committer, O_APPEND). A
    REMOVE_PREFIX record drops every earlier record under its prefix
    (the journals were rmtree'd out-of-band; replay must not
    resurrect them)."""
    out: dict[tuple[str, str], Record] = {}
    for rec in scan(path):
        if rec.rtype == REC_REMOVE_PREFIX:
            pre = rec.path
            doomed = [k for k in out
                      if k[0] == rec.volume
                      and (not pre or k[1] == pre
                           or k[1].startswith(pre + "/"))]
            for k in doomed:
                del out[k]
            continue
        out[(rec.volume, rec.path)] = rec
    return out


def segment_paths(wal_dir: str) -> list[str]:
    """Every journal segment under a drive's wal dir, sorted. The
    classic single-owner journal is `journal.wal`; front-door workers
    write single-writer segments `journal.w<id>.wal` (one producer
    process per file — docs/FRONTDOOR.md)."""
    try:
        names = os.listdir(wal_dir)
    except OSError:
        return []
    return sorted(os.path.join(wal_dir, n) for n in names
                  if n.startswith("journal") and n.endswith(".wal"))


def fold_merged(paths: list[str]) -> dict[tuple[str, str], Record]:
    """Cross-segment replay fold: within a segment, file order is
    commit order (single producer, O_APPEND); across segments the only
    order is each record's wall-clock `mt`, so the newest mt wins per
    key and a REMOVE_PREFIX tombstone in one segment drops other
    segments' older records under its prefix. Same-key cross-worker
    races therefore converge last-writer-wins — exactly the S3
    contract concurrent writers already get on the live path."""
    folds = []
    tombs: list[tuple[int, Record]] = []
    for si, p in enumerate(paths):
        out: dict[tuple[str, str], Record] = {}
        for rec in scan(p):
            if rec.rtype == REC_REMOVE_PREFIX:
                pre = rec.path
                for k in [k for k in out
                          if k[0] == rec.volume
                          and (not pre or k[1] == pre
                               or k[1].startswith(pre + "/"))]:
                    del out[k]
                tombs.append((si, rec))
                continue
            out[(rec.volume, rec.path)] = rec
        folds.append(out)
    merged: dict[tuple[str, str], tuple[int, Record]] = {}
    for si, out in enumerate(folds):
        for k, rec in out.items():
            cur = merged.get(k)
            if cur is None or rec.mt >= cur[1].mt:
                merged[k] = (si, rec)
    for tsi, tomb in tombs:
        # The tombstone's own segment already applied it in file order
        # (records after it there legitimately survive); other
        # segments' records only have mt to order against.
        pre = tomb.path
        for k in [k for k, (si, rec) in merged.items()
                  if si != tsi and k[0] == tomb.volume
                  and rec.mt <= tomb.mt
                  and (not pre or k[1] == pre
                       or k[1].startswith(pre + "/"))]:
            del merged[k]
    return {k: rec for k, (_si, rec) in merged.items()}


def reset(path: str) -> None:
    """(Re)write an empty journal: magic only, durably. Called at
    checkpoint after every folded record is materialized + synced, and
    at mount after replay."""
    tmp = path + ".tmp"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, MAGIC)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path), os.O_RDONLY)
    except OSError:
        return  # best-effort: the rename above already landed
    try:
        os.fsync(dfd)
    except OSError:
        return
    finally:
        os.close(dfd)
