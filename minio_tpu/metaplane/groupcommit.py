"""DriveWAL — per-drive group commit over the WAL journal.

One committer thread per armed drive. Concurrent journal stores
(`LocalDrive._store_meta` / the inline-PUT single-journal fast path)
enqueue records and block on futures; the committer drains the queue,
appends the whole batch to the WAL with one `writev`, and fsyncs ONCE —
the futures resolve only after that fsync lands, so the S3 ack rides
exactly one shared fsync instead of a write+fsync+rename per request.

`meta.mp` files materialize asynchronously: after the fsync the batch
is published to an in-memory pending overlay (reads — `read_version`,
`read_xl`, `_load_meta` — consult it first, so read-your-write holds
the instant the future resolves), and the committer writes the actual
per-object journals when the queue goes idle (or when the backlog
exceeds `MTPU_WAL_MAX_PENDING`), *without* per-file fsync — durability
is the WAL until checkpoint. Checkpoint (WAL past `MTPU_WAL_MAX_BYTES`)
materializes everything, `os.sync()`s once, and truncates the journal.

Crash anatomy (proven by tests/test_metaplane.py + the armed chaos
storm):

- SIGKILL before the batch fsync — the WAL tail is torn; `wal.scan`
  stops before it; the writes were never acked and are legally lost.
- SIGKILL after fsync, before materialize — replay on next mount folds
  the WAL and rewrites every key's journal bit-exact; acked writes
  survive.
- SIGKILL mid-checkpoint — the WAL still holds every record until the
  post-sync truncate, and replay is idempotent.

Error discipline: an append/fsync failure marks the WAL broken, fails
the batch's futures with FaultyDisk (the caller's quorum accounting
treats the drive as failed), and subsequent submits fail fast. A
materialize failure leaves the record pending (still served from
memory, still durable in the WAL) and blocks checkpoint truncation.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future

from minio_tpu import metaplane, obs, qos
from minio_tpu.metaplane import wal as walfmt
from minio_tpu.obs import flight
from minio_tpu.utils import admission
from minio_tpu.utils import errors as se

_COMMITS = obs.counter(
    "minio_tpu_metaplane_commits_total",
    "Journal records group-committed through the per-drive WAL",
    ("drive",))
_FSYNCS = obs.counter(
    "minio_tpu_metaplane_fsyncs_total",
    "WAL fsyncs — commits/fsyncs is the live group-commit amortization",
    ("drive",))
_BATCH_FILL = obs.histogram(
    "minio_tpu_metaplane_batch_fill",
    "Records per WAL group commit",
    ("drive",),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
_WAL_BYTES = obs.gauge(
    "minio_tpu_metaplane_wal_bytes",
    "Current WAL journal size (truncates at checkpoint)",
    ("drive",))

_seq_lock = threading.Lock()
_seq = 0

# Same-process segment ownership: the single-writer contract is per
# PROCESS (the flock enforces it across processes), so a LocalDrive
# re-mounted over the same root in one process — the restart pattern
# every format/heal bootstrap uses — gracefully takes the segment over
# by closing its predecessor (drain + checkpoint + flock release)
# instead of refusing with a duplicate-owner error.
_live_mu = threading.Lock()
_live_by_path: dict = {}


def _wal_cost(item) -> int:
    """Byte cost of one WAL submit for QoS byte quotas: the serialized
    payload length (index 3 across every record shape; "single" nests
    the raw journal at payload[1])."""
    raw = item[3]
    if isinstance(raw, tuple):
        raw = raw[1] if len(raw) > 1 else None
    if raw is None:
        return 0
    try:
        return len(raw)
    except TypeError:
        return 0


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


class Entry:
    """One pending (committed-but-not-materialized) journal state.
    `raw is None` means the journal was deleted (tombstone). `blob`
    marks a raw sys-file record (REC_BLOB): `path` is then the file
    path itself and materialization writes the bytes verbatim — meta
    readers (`pending_entry`) never see blob entries and blob readers
    (`pending_blob`) never see journal entries."""

    __slots__ = ("lsn", "raw", "meta", "memo", "mt", "blob")

    def __init__(self, lsn: int, raw, meta, mt: float,
                 blob: bool = False):
        self.lsn = lsn
        self.raw = raw
        self.meta = meta
        self.memo: dict = {}
        self.mt = mt
        self.blob = blob

    @property
    def removed(self) -> bool:
        return self.raw is None


def replay(drive, wal_path: str) -> "tuple[int, int]":
    """Fold + apply a WAL left by a previous process; returns
    (applied, failed) record counts — the journal is truncated only
    when failed == 0. Runs on EVERY mount (armed or not): a crashed
    armed session's acked writes must converge regardless of the next
    boot's gate. The `mt` tiebreak guards the armed→unarmed→armed
    interleave: state written directly by an unarmed process is newer
    than the stale WAL record and wins."""
    final = walfmt.fold(wal_path)
    applied, failed = _apply_fold(drive, final)
    if failed == 0:
        walfmt.reset(wal_path)
    return applied, failed


def replay_all(drive, wal_dir: str) -> "tuple[int, int]":
    """Replay every ORPHANED journal segment under the drive's wal dir
    in one merged fold — the multi-worker mount path
    (docs/FRONTDOOR.md). Serialized across concurrently-booting workers
    by an exclusive flock on `.replay.lock`; segments whose owner
    process is STILL ALIVE (it holds an exclusive flock on its open
    segment fd for its whole life — released by the kernel even on
    SIGKILL) are skipped entirely: folding them would race the live
    committer, and resetting them would silently unlink the fd its
    durability rides on. Orphan segments are truncated only on a
    fully-applied fold, exactly like the single-segment contract."""
    import fcntl

    os.makedirs(wal_dir, exist_ok=True)
    lfd = _replay_lock(wal_dir)
    try:
        applied, failed, _orphans = _replay_orphans(drive, wal_dir)
        return applied, failed
    finally:
        try:
            fcntl.flock(lfd, fcntl.LOCK_UN)
        finally:
            os.close(lfd)


def _replay_lock(wal_dir: str) -> int:
    import fcntl

    lfd = os.open(os.path.join(wal_dir, ".replay.lock"),
                  os.O_CREAT | os.O_RDWR, 0o644)
    fcntl.flock(lfd, fcntl.LOCK_EX)
    return lfd


def _replay_orphans(drive, wal_dir: str) -> "tuple[int, int, list]":
    """Core of replay_all; caller holds the `.replay.lock` flock.
    Returns (applied, failed, orphan_paths) — orphans are kept on disk
    when failed > 0 so the caller can seed its overlay from them."""
    import fcntl

    orphan_fds: list[int] = []
    orphans: list[str] = []
    try:
        for p in walfmt.segment_paths(wal_dir):
            try:
                fd = os.open(p, os.O_RDWR)
            except OSError:
                continue
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)  # live owner: leave the segment alone
                continue
            orphan_fds.append(fd)
            orphans.append(p)
        if not orphans:
            return 0, 0, []
        final = walfmt.fold_merged(orphans)
        applied, failed = _apply_fold(drive, final)
        if failed == 0:
            for p in orphans:
                walfmt.reset(p)
        return applied, failed, orphans
    finally:
        for fd in orphan_fds:
            try:
                os.close(fd)
            except OSError:
                continue


def _apply_fold(drive, final) -> "tuple[int, int]":
    """Write a replay fold back to the drive; (applied, failed)."""
    from minio_tpu.storage.xlmeta import XLMeta

    applied = 0
    failed = 0
    for (vol, path), rec in final.items():
        stat_err = False
        # REC_REMOVE_PREFIX never reaches a fold: fold()/fold_merged()
        # consume tombstones in-stream (they delete the keys they
        # cover and are dropped), so the dispatch below is total over
        # every record type a fold output can contain.
        # mtpu: allow(MTPU009)
        if rec.rtype in (walfmt.REC_REPL_INTENT, walfmt.REC_REPL_DONE):
            # Replication intents live in their own segment
            # (replication.wal, replayed by replication/journal.py —
            # never by the drive mount). One in a DRIVE journal is
            # misrouted; keep it (failed blocks truncation) rather
            # than guess at materialization.
            failed += 1
            continue
        blob = rec.rtype in (walfmt.REC_BLOB, walfmt.REC_BLOB_REMOVE)
        try:
            # Blob records tiebreak against the blob FILE's mtime; the
            # journal records against the meta.mp under the key.
            disk_mt = (drive._disk_blob_mt(vol, path) if blob
                       else drive._disk_meta_mt(vol, path))
        except se.StorageError:
            disk_mt = None  # unreadable/corrupt journal: the record wins
            stat_err = True
        if disk_mt is not None and disk_mt > rec.mt + 1e-9:
            continue  # disk is newer (unarmed-session write)
        if rec.rtype == walfmt.REC_BLOB:
            try:
                drive._store_blob_disk(vol, path, rec.raw)
                applied += 1
            except se.StorageError:
                failed += 1
            continue
        if rec.rtype == walfmt.REC_BLOB_REMOVE:
            try:
                drive._remove_blob_disk(vol, path)
                applied += 1
            except se.StorageError:
                failed += 1
            continue
        if rec.rtype == walfmt.REC_COMMIT:
            try:
                meta = XLMeta.parse(rec.raw)  # scan hands out real bytes
            # mtpu: allow(MTPU003) - a CRC-valid but unparseable record
            # is unrecoverable by construction; skipping it (rather than
            # wedging the mount) degrades to a missed write on ONE
            # drive, which quorum + heal absorb.
            except Exception:  # noqa: BLE001
                continue
            try:
                drive._store_meta_disk(vol, path, rec.raw,
                                       meta=meta, fsync=False)
                applied += 1
            except se.StorageError:
                failed += 1
                continue
        elif rec.rtype == walfmt.REC_REMOVE:
            if disk_mt is None and not stat_err:
                continue  # genuinely absent: nothing to remove
            # A corrupt/unreadable journal under an acked REMOVE still
            # gets removed (that IS the acked state); a transient stat
            # failure falls through too — a failing _remove_meta_disk
            # then counts as failed and keeps the WAL for the next
            # mount instead of truncating the record away.
            try:
                drive._remove_meta_disk(vol, path)
                applied += 1
            except se.StorageError:
                failed += 1
                continue
        else:
            # A record type this build does not understand (newer
            # writer, older reader). The old bare `else` treated it as
            # a REMOVE and would have DELETED metadata for it — count
            # it failed instead, which keeps the journal for a build
            # that can apply it (truncation requires failed == 0).
            failed += 1
            continue
    if applied:
        os.sync()  # one barrier instead of a per-file fsync storm
    # Only a fully-applied journal may truncate (callers enforce): a
    # record that could not be written back (full/failing disk at
    # mount) is an ACKED state the WAL must keep carrying.
    return applied, failed


class DriveWAL:
    """Group-commit engine for one LocalDrive (see module docstring)."""

    def __init__(self, drive):
        self.drive = drive
        self._dir = os.path.join(drive.root, drive.sys_volume(), "wal")
        # Single-writer ownership under the multi-process front door:
        # each worker journals into its own segment; replay folds all.
        seg = metaplane.wal_segment()
        self.path = os.path.join(
            self._dir, f"journal.{seg}.wal" if seg else "journal.wal")
        os.makedirs(self._dir, exist_ok=True)
        self._max_bytes = metaplane.wal_max_bytes()
        self._max_pending = metaplane.wal_max_pending()
        self._max_batch = metaplane.wal_max_batch()
        # Test-only crash window: hold the committer this long before
        # each batch fsync so a harness can land a real SIGKILL between
        # append and fsync (tests/test_metaplane.py crash matrix).
        self._test_hold_fsync = float(
            os.environ.get("MTPU_WAL_TEST_HOLD_FSYNC_S", "0") or 0)
        # Lazy mode: never materialize between checkpoints (reads serve
        # from the pending overlay). The crash matrix uses it to pin the
        # fsynced-but-not-materialized state; also a valid operating
        # point for pure write bursts.
        self._lazy = os.environ.get("MTPU_WAL_LAZY_MATERIALIZE", "") == "1"
        # Multi-worker coherence (docs/FRONTDOOR.md): sibling workers
        # read through the filesystem, so every batch materializes
        # before its futures resolve (no per-file fsync — the ack still
        # rides exactly one WAL fsync) and the per-key LSN signature is
        # meaningless across processes (key_sig returns None; the set
        # cache falls back to stat triples, which eager materialization
        # keeps current).
        self._multi = not metaplane.single_owner()
        self._eager = metaplane.eager_materialize()

        # Replay-then-claim under ONE replay lock: fold every orphaned
        # segment, then open + flock our own before anyone else's
        # replay could mistake it for an orphan and truncate it out
        # from under the fd (the flock is the liveness mark replay_all
        # keys on; the kernel drops it even on SIGKILL).
        import fcntl

        # In-process predecessor (re-mount over the same root): close
        # it BEFORE taking the replay lock — its committer may need a
        # flush that briefly touches the same drive, and its released
        # flock is what lets the claim below succeed.
        with _live_mu:
            prior = _live_by_path.pop(self.path, None)
        if prior is not None:
            prior_wal = prior()
            if prior_wal is not None and not prior_wal._closed:
                prior_wal.close()

        replay_failed = 0
        replay_kept: list = []
        lfd = _replay_lock(self._dir)
        try:
            _applied, replay_failed, replay_kept = _replay_orphans(
                drive, self._dir)
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                               0o644)
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(self._fd)
                raise se.FaultyDisk(
                    f"wal segment {self.path} is owned by a live "
                    "process (duplicate worker id?)") from None
        finally:
            try:
                fcntl.flock(lfd, fcntl.LOCK_UN)
            finally:
                os.close(lfd)
        if os.fstat(self._fd).st_size == 0:
            os.write(self._fd, walfmt.MAGIC)
            os.fsync(self._fd)
        self._bytes = os.fstat(self._fd).st_size

        # Admission queue: plain bounded queue, or a tenant-fair DRR
        # queue when the QoS plane is armed (MTPU_QOS=1). The tenant
        # key rides the item's Future (attached in _submit, like
        # mtpu_fctx); byte quotas meter the serialized payload — the
        # blob lane's large sys-files count at full weight. flush/close
        # barriers are control items: admitted unconditionally, and the
        # fair queue releases them only after everything enqueued
        # before them, preserving the flush contract under reordering.
        # Tombstones are ordering FENCES: replay's fold() resolves
        # dominance by WAL file order, so a remove_prefix/remove/
        # blob_remove reordered across tenant lanes would resurrect an
        # rmtree'd journal (tombstone written before an earlier commit)
        # or replay-delete a fresh one (later commit written before the
        # tombstone) — the fence pins file order to submit order there.
        self._q = qos.plane_queue(
            "metaplane", metaplane.wal_queue_depth(),
            tenant_of=lambda it: getattr(it[-1], "mtpu_tenant", None),
            cost_of=_wal_cost,
            is_control=lambda it: it[0] in ("flush", "close"),
            is_barrier=lambda it: it[0] in ("remove_prefix", "remove",
                                            "blob_remove"))
        self._mu = threading.Lock()  # pending overlay + key lsn map
        self._pending: "OrderedDict[tuple[str, str], Entry]" = OrderedDict()
        self._key_lsn: "OrderedDict[tuple[str, str], int]" = OrderedDict()
        self._key_lsn_cap = 65536
        # Blob keys that may still have a record in the WAL (cleared at
        # checkpoint — a truncated WAL cannot resurrect anything). None
        # = cap exceeded: "may exist" degrades to "always forget".
        self._blob_keys: "set | None" = set()
        self._blob_keys_cap = 65536
        self._lsn = 0
        self._broken: str | None = None
        self._closed = False
        self._trash: list[str] = []
        if replay_failed:
            # Replay could not write some acked records back (full or
            # flaky disk at mount) and kept the journal: seed the whole
            # fold into the pending overlay — reads serve the acked
            # state, drains retry materialization, and checkpoint stays
            # blocked until every record lands. Seed from the KEPT
            # orphan segments only — live siblings' segments are their
            # owners' to serve.
            for (vol, path), rec in walfmt.fold_merged(
                    replay_kept).items():
                # Not a dispatch gap: REC_REMOVE seeds raw=None (a
                # pending removal Entry) through the else by design,
                # and REC_REMOVE_PREFIX cannot appear in a fold —
                # fold_merged consumes tombstones in-stream.
                # mtpu: allow(MTPU009)
                if rec.rtype in (walfmt.REC_REPL_INTENT,
                                 walfmt.REC_REPL_DONE):
                    # Misrouted replication intent (its home is the
                    # replication.wal segment): it must not seed the
                    # drive overlay as a phantom journal entry.
                    continue
                self._lsn += 1
                blob = rec.rtype in (walfmt.REC_BLOB,
                                     walfmt.REC_BLOB_REMOVE)
                self._pending[(vol, path)] = Entry(
                    self._lsn,
                    rec.raw if rec.rtype in (walfmt.REC_COMMIT,
                                             walfmt.REC_BLOB) else None,
                    None, rec.mt, blob=blob)
                if not blob:
                    self._key_lsn[(vol, path)] = self._lsn

        self._c_commits = _COMMITS.labels(drive=drive.root)
        self._c_fsyncs = _FSYNCS.labels(drive=drive.root)
        self._h_fill = _BATCH_FILL.labels(drive=drive.root)
        self._g_bytes = _WAL_BYTES.labels(drive=drive.root)
        self._g_bytes.set(self._bytes)

        import weakref

        with _live_mu:
            _live_by_path[self.path] = weakref.ref(self)

        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mtpu-metaplane-{_next_seq()}")
        self._thread.start()

    # ---------- submission (request threads) ----------

    def _bump_lsn(self, key: tuple[str, str]) -> int:
        with self._mu:
            self._lsn += 1
            self._key_lsn[key] = self._lsn
            self._key_lsn.move_to_end(key)
            while len(self._key_lsn) > self._key_lsn_cap:
                self._key_lsn.popitem(last=False)
            return self._lsn

    def _submit(self, item) -> Future:
        if self._broken is not None:
            raise se.FaultyDisk(f"wal broken: {self._broken}")
        if self._closed:
            raise se.FaultyDisk("wal closed")
        # Critical-path attribution rides the Future itself (every
        # submit shape ends in one): the committer thread reads it back
        # after the covering fsync to stamp the submitting request's
        # timeline and link the group's member trace ids. Attached
        # BEFORE enqueue — the committer may drain the item immediately.
        tid = obs.trace_id()
        tl = flight.current()
        if tid is not None or tl is not None:
            item[-1].mtpu_fctx = (tid, tl, time.perf_counter())
        tenant = qos.current_key()
        if tenant != qos.UNATTRIBUTED:
            item[-1].mtpu_tenant = tenant
        try:
            self._q.put_nowait(item)
        except queue.Full as e:
            # Unified admission: a full WAL queue sheds exactly like a
            # full dataplane lane — OperationTimedOut -> 503 SlowDown,
            # one shared shed family (utils/admission.py). Quorum
            # reducers raise the dominant error, so a set whose drives
            # all shed surfaces SlowDown, never a 500. A QoS
            # token-bucket reject is the same wire contract, distinct
            # cause slug.
            if isinstance(e, qos.QuotaFull):
                raise admission.shed(
                    "metaplane", "tenant_quota",
                    "tenant over wal rate quota") from None
            raise admission.shed(
                "metaplane", "wal_full",
                "wal commit queue full (backpressure)") from None
        return item[-1]

    def submit_commit(self, volume: str, path: str, raw, meta) -> Future:
        """Enqueue a full-journal store; resolves after the covering
        WAL fsync. `raw` is the serialized journal (bytes/memoryview,
        not copied); `meta` the parsed XLMeta (seeds the read overlay)."""
        self.drive._note_journal_key(volume, path)
        lsn = self._bump_lsn((volume, path))
        mt = meta.latest_mt if meta is not None else time.time()
        return self._submit(
            ("commit", volume, path, raw, meta, mt, lsn, Future()))

    def submit_remove(self, volume: str, path: str) -> Future:
        """Enqueue a journal deletion (last version removed)."""
        lsn = self._bump_lsn((volume, path))
        return self._submit(
            ("remove", volume, path, None, None, time.time(), lsn, Future()))

    def _bump_lsn_only(self) -> int:
        """LSN for a blob record: orders overlay entries without
        entering the per-key signature map (blobs have no set-cache
        signatures to serve)."""
        with self._mu:
            self._lsn += 1
            return self._lsn

    def submit_blob(self, volume: str, path: str, raw) -> Future:
        """Enqueue a raw sys-file store (multipart part journal,
        scanner checkpoint, sys-config doc) — the blob lane of the
        group commit: the ack rides the same shared WAL fsync as
        journal commits, and the file materializes on idle ticks with
        NO per-file fsync. `raw` is bytes/memoryview, not copied."""
        if not isinstance(raw, bytes):
            # Blob docs are small control files (json/msgpack) and in
            # practice arrive as bytes already; real bytes keep the
            # overlay directly servable by read_all and its callers.
            raw = memoryview(raw).tobytes()
        lsn = self._bump_lsn_only()
        with self._mu:
            if self._blob_keys is not None:
                self._blob_keys.add((volume, path))
                if len(self._blob_keys) > self._blob_keys_cap:
                    self._blob_keys = None  # superset tracking lost
        return self._submit(
            ("blob", volume, path, raw, None, time.time(), lsn, Future()))

    def has_blob_state(self, volume: str, path: str) -> bool:
        """True when the WAL may still carry a record for this blob
        (pending overlay, or a record appended since the last
        checkpoint) — the gate for forget_blob, so plain-file deletes
        of never-journaled files cost nothing."""
        key = (volume, path)
        with self._mu:
            ent = self._pending.get(key)
            if ent is not None and ent.blob:
                return True
            return self._blob_keys is None or key in self._blob_keys

    def forget_blob(self, volume: str, path: str) -> bool:
        """A blob file was deleted out-of-band (delete_sys_config, part
        cleanup): drop its overlay entry and log a BLOB_REMOVE so
        replay cannot resurrect a file whose COMMIT record is still in
        the WAL. Fire-and-forget like forget_key. Returns True when a
        LIVE pending entry was dropped — the caller's filesystem
        remove may then legitimately find no file on disk."""
        key = (volume, path)
        dropped = False
        with self._mu:
            ent = self._pending.get(key)
            if ent is not None and ent.blob:
                dropped = not ent.removed
                del self._pending[key]
        try:
            self._submit(("blob_remove", volume, path, None, None,
                          time.time(), self._bump_lsn_only(), Future()))
        except (se.StorageError, se.OperationTimedOut):
            pass  # broken/full: the stale copy loses the election
        return dropped

    def submit_single(self, volume: str, path: str, fi, raw, meta,
                      defer_reclaim: bool) -> Future:
        """Enqueue a single-journal store (the inline-PUT commit) whose
        PREWORK — vol stat, displaced-version stash, merge fallback —
        runs in the committer, so this call is pure memory: request
        threads never touch the drive on the submit side (no pool hop
        needed for hang isolation; a hung drive surfaces as a future
        the caller's deadline'd await stamps). The future resolves to
        the reclaim token (or raises the per-drive error).

        Same-key commits are serialized by the erasure layer's
        namespace lock, so a batch never carries two singles for one
        key whose prework could read around each other."""
        # Evaluated BEFORE noting the key: proves to the committer that
        # no journal predates this record, skipping its existence stat.
        assume_new = self.drive.journal_known_absent(volume, path)
        self.drive._note_journal_key(volume, path)
        lsn = self._bump_lsn((volume, path))
        mt = meta.latest_mt if meta is not None else time.time()
        return self._submit(
            ("single", volume, path, (fi, raw, defer_reclaim, assume_new),
             meta, mt, lsn, Future()))

    def flush(self, timeout: float = 60.0) -> None:
        """Barrier: every record enqueued before this call is durable
        AND materialized on return — listings/walks that read `meta.mp`
        straight off the filesystem call this first. Cheap when idle."""
        with self._mu:
            idle = not self._pending
        if idle and self._q.empty():
            return
        if self._broken is not None or self._closed:
            self._drain_materialize(force=True)
            return
        fut: Future = Future()
        try:
            self._q.put(("flush", fut), timeout=timeout)
        except queue.Full:
            raise admission.shed(
                "metaplane", "wal_flush_full",
                "wal commit queue full (backpressure)") from None
        fut.result(timeout=timeout)

    def forget_subtree(self, volume: str, prefix: str) -> None:
        """A recursive filesystem delete (session/tmp rmtree, volume
        force-delete) removed journals out-of-band: drop pending overlay
        entries AND per-key signature LSNs under the prefix (a stale
        ("w", lsn) signature must not keep validating a set-cache entry
        for a destroyed journal), and append one REMOVE_PREFIX tombstone
        so replay drops every earlier WAL record there — including
        records already materialized but not yet checkpointed.
        Fire-and-forget — the rmtree itself carries the operation's
        (pre-existing) durability semantics."""
        def under(k):
            return k[0] == volume and (not prefix or k[1] == prefix
                                       or k[1].startswith(prefix + "/"))

        with self._mu:
            for k in [k for k in self._pending if under(k)]:
                del self._pending[k]
            for k in [k for k in self._key_lsn if under(k)]:
                del self._key_lsn[k]
        try:
            self._submit(("remove_prefix", volume, prefix, None, None,
                          time.time(), 0, Future()))
        except (se.StorageError, se.OperationTimedOut):
            return  # broken/full: a replay resurrection here is the
            # dangling-object case deep heal already purges

    def forget_key(self, volume: str, path: str) -> None:
        """Exact-key variant of forget_subtree for a single journal
        removed out-of-band (never touches nested keys like 'a/b/c'
        when 'a/b' is forgotten)."""
        with self._mu:
            self._pending.pop((volume, path), None)
        try:
            self.submit_remove(volume, path)
        except (se.StorageError, se.OperationTimedOut):
            return  # as above: heal purges the dangling remnant

    # ---------- read overlay (request threads) ----------

    def pending_entry(self, volume: str, path: str) -> Entry | None:
        """The committed-but-unmaterialized state for a key, or None
        when disk is authoritative. `entry.removed` marks deletion.
        Blob entries are invisible here (journal readers only)."""
        with self._mu:
            ent = self._pending.get((volume, path))
            return None if ent is not None and ent.blob else ent

    def pending_blob(self, volume: str, path: str) -> Entry | None:
        """The committed-but-unmaterialized state of a raw sys file
        (read_all's overlay), or None when disk is authoritative."""
        with self._mu:
            ent = self._pending.get((volume, path))
            return ent if ent is not None and ent.blob else None

    def key_sig(self, volume: str, path: str):
        """Logical journal signature while armed: every mutation bumps
        the key's LSN at submit, so ("w", lsn) names the journal state
        exactly (one owning process per drive by contract). None once
        the key ages out of the LRU — callers fall back to stat — and
        always None under a multi-worker front door, where a sibling's
        commits move state this process's LSNs never see."""
        if self._multi:
            return None
        with self._mu:
            lsn = self._key_lsn.get((volume, path))
        return None if lsn is None else ("w", lsn)

    # ---------- committer ----------

    def _run(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if self._closed:
                    return
                self._drain_materialize()
                continue
            batch = [item]
            while len(batch) < self._max_batch:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            close_fut = None
            flushes: list[Future] = []
            recs: list[tuple] = []
            for it in batch:
                if it[0] == "flush":
                    flushes.append(it[1])
                elif it[0] == "close":
                    close_fut = it[1]
                else:
                    recs.append(it)
            if recs:
                self._commit_batch(recs)
            with self._mu:
                backlog = len(self._pending)
            # Materialize on IDLE (the queue-empty timeout tick above),
            # on barriers, and on backlog pressure — never eagerly after
            # every batch: per-key journal files cost ~5 filesystem
            # round-trips each, and paying them inside a burst would put
            # the deferred work right back on the commit path's medium.
            # A burst therefore rides the WAL at writev+fsync cost and
            # the backlog drains in the gaps (bounded by max_pending).
            if flushes or close_fut is not None \
                    or backlog > self._max_pending:
                self._drain_materialize(force=True)
            for f in flushes:
                f.set_result(None)
            if self._bytes > self._max_bytes and self._broken is None:
                self._checkpoint()
            if close_fut is not None:
                self._checkpoint()
                close_fut.set_result(None)
                return

    def _commit_batch(self, recs: list[tuple]) -> None:
        # Resolve "single" records' prework (vol stat, displaced-state
        # stash, merge fallback) HERE in the committer — the submit side
        # stayed pure memory. A prework failure fails only that record's
        # future; the rest of the batch commits.
        staged: list[tuple] = []  # (rtype, vol, path, raw, meta, mt,
        #                            lsn, fut, token)
        for kind, vol, path, payload, meta, mt, lsn, fut in recs:
            if kind == "single":
                fi, raw, defer_reclaim, assume_new = payload
                try:
                    self.drive._stat_vol_cached(vol)
                    token, merged = self.drive._single_prework(
                        vol, path, fi, defer_reclaim,
                        assume_new=assume_new, defer_fs=True)
                except Exception as e:  # noqa: BLE001 - per-record: the
                    # error travels to exactly the caller whose commit
                    # it is (quorum counts the drive as failed)
                    fut.set_exception(e if isinstance(e, se.StorageError)
                                      else se.FaultyDisk(str(e)))
                    continue
                if merged is not None:
                    meta = merged
                    raw = merged.serialize()
                    mt = merged.latest_mt
                staged.append((walfmt.REC_COMMIT, vol, path, raw, meta,
                               mt, lsn, fut, token))
            elif kind == "commit":
                staged.append((walfmt.REC_COMMIT, vol, path, payload,
                               meta, mt, lsn, fut, None))
            elif kind == "remove_prefix":
                staged.append((walfmt.REC_REMOVE_PREFIX, vol, path, b"",
                               None, mt, lsn, fut, None))
            elif kind == "blob":
                staged.append((walfmt.REC_BLOB, vol, path, payload,
                               None, mt, lsn, fut, None))
            elif kind == "blob_remove":
                staged.append((walfmt.REC_BLOB_REMOVE, vol, path, b"",
                               None, mt, lsn, fut, None))
            else:
                staged.append((walfmt.REC_REMOVE, vol, path, b"", None,
                               mt, lsn, fut, None))
        if not staged:
            return
        frames = [walfmt.frame_record(rtype, mt, vol, path, raw)
                  for rtype, vol, path, raw, _m, mt, _l, _f, _t in staged]
        try:
            n = walfmt.append_records(self._fd, frames)
            if self._test_hold_fsync:
                time.sleep(self._test_hold_fsync)
            os.fsync(self._fd)
        except OSError as e:
            self._broken = str(e)
            err = se.FaultyDisk(f"wal append/fsync failed: {e}")
            for rec in staged:
                rec[7].set_exception(err)
            return
        self._bytes += n
        self._g_bytes.set(self._bytes)
        self._c_fsyncs.inc()
        self._c_commits.inc(len(staged))
        self._h_fill.observe(len(staged))
        # Attribution: the fsync above is the durability point — stamp
        # each member request's timeline with its submit→fsync wait and
        # link the group's members in one `batch` record.
        t_ack = time.perf_counter()
        members = []
        tenants = set()
        for rec in staged:
            ten = getattr(rec[7], "mtpu_tenant", None)
            if ten:
                tenants.add(ten)
            fctx = getattr(rec[7], "mtpu_fctx", None)
            if fctx is None:
                continue
            tid, tl, t_sub = fctx
            if tid:
                members.append(tid)
            if tl is not None:
                tl.stamp("wal_fsync_wait", t_ack - t_sub, "metaplane")
        if obs.has_subscribers():
            obs.publish({"type": "batch", "plane": "metaplane",
                         "records": len(staged), "members": members,
                         "tenants": sorted(tenants),
                         "time": time.time()})
        # Publish the overlay BEFORE resolving futures: the instant the
        # ack fires, a read must see the new state. Entries carry LSNs
        # so a newer published state is never downgraded.
        with self._mu:
            for rtype, vol, path, raw, meta, mt, lsn, _fut, _tok in staged:
                # REC_REPL_INTENT/REC_REPL_DONE never enter the commit
                # queue — replication/journal.py appends them to its
                # own segment, never through DriveWAL staging.
                # mtpu: allow(MTPU009)
                if rtype == walfmt.REC_REMOVE_PREFIX:
                    # Drop anything that slipped into the overlay for
                    # the destroyed subtree between forget and commit.
                    pre = path
                    for k in [k for k in self._pending
                              if k[0] == vol
                              and (not pre or k[1] == pre
                                   or k[1].startswith(pre + "/"))]:
                        del self._pending[k]
                    continue
                key = (vol, path)
                cur = self._pending.get(key)
                if cur is not None and cur.lsn > lsn:
                    continue
                blob = rtype in (walfmt.REC_BLOB, walfmt.REC_BLOB_REMOVE)
                self._pending[key] = Entry(
                    lsn,
                    raw if rtype in (walfmt.REC_COMMIT, walfmt.REC_BLOB)
                    else None,
                    meta, mt, blob=blob)
                self._pending.move_to_end(key)
        if self._eager:
            # Cross-process read-your-write: sibling workers have no
            # view of this overlay, so the journals must be on the
            # filesystem before the ack fires (page-cache writes only —
            # durability stays the WAL fsync above).
            self._drain_materialize(force=True)
        for rec in staged:
            rec[7].set_result(rec[8])

    def note_trash(self, path: str) -> None:
        """A displaced data dir parked by an O(1) rename during commit
        prework; the tree is destroyed at the next idle drain instead
        of head-of-line blocking the committer's batch (a multi-GiB
        rmtree inside the commit cycle would stall every concurrent
        group commit on this drive past the meta deadline)."""
        self._trash.append(path)

    def _drain_trash(self) -> None:
        while self._trash:
            shutil.rmtree(self._trash.pop(), ignore_errors=True)

    def _drain_materialize(self, force: bool = False) -> None:
        """Write every currently-pending journal to its meta.mp (no
        per-file fsync — the WAL is durability until checkpoint). One
        pass over a snapshot: entries that fail stay pending (still
        served from memory, still in the WAL) and pin the checkpoint;
        entries superseded mid-write keep their newer overlay."""
        self._drain_trash()
        if self._lazy and not (force or self._closed):
            return
        with self._mu:
            snapshot = list(self._pending.items())
        for key, entry in snapshot:
            vol, path = key
            try:
                if entry.blob:
                    if entry.removed:
                        self.drive._remove_blob_disk(vol, path)
                    else:
                        self.drive._store_blob_disk(vol, path, entry.raw)
                elif entry.removed:
                    self.drive._remove_meta_disk(vol, path)
                else:
                    self.drive._store_meta_disk(
                        vol, path, entry.raw, meta=entry.meta, fsync=False)
            except se.StorageError:
                continue  # stays pending; checkpoint refuses to truncate
            with self._mu:
                if self._pending.get(key) is entry:
                    del self._pending[key]

    def _checkpoint(self) -> None:
        """Materialize everything, one sync barrier, truncate the WAL."""
        self._drain_materialize(force=True)
        with self._mu:
            if self._pending:
                return  # a stuck materialization pins the WAL
        try:
            os.sync()
            os.ftruncate(self._fd, 0)
            os.write(self._fd, walfmt.MAGIC)
            os.fsync(self._fd)
        except OSError as e:
            self._broken = str(e)
            return
        self._bytes = len(walfmt.MAGIC)
        self._g_bytes.set(self._bytes)
        with self._mu:
            # Truncated WAL cannot resurrect any blob: forget tracking
            # restarts empty (and recovers from a prior cap overflow).
            self._blob_keys = set()

    # ---------- lifecycle ----------

    def abandon(self) -> None:
        """Test-only SIGKILL simulation: stop the committer dead and
        release the segment flock WITHOUT materializing, checkpointing
        or resolving anything — on-disk state is exactly what a real
        crash leaves, and the segment reads as orphaned to the next
        mount's replay (a live committer's flock otherwise correctly
        blocks replay from folding a file mid-write)."""
        self._closed = True
        self._broken = "abandoned (test crash)"
        self._thread.join(5.0)
        try:
            os.close(self._fd)
        except OSError:
            pass

    def close(self, timeout: float = 30.0) -> None:
        """Drain, checkpoint, stop the committer (tests; process-lived
        drives just die with their daemon)."""
        if self._closed:
            return
        try:
            fut: Future = Future()
            self._q.put(("close", fut), timeout=timeout)
            self._closed = True
            fut.result(timeout=timeout)
        # mtpu: allow(MTPU003) - teardown: a broken WAL already failed
        # its waiters with typed errors; close only needs the committer
        # thread stopped.
        except Exception:  # noqa: BLE001
            self._closed = True
        self._thread.join(timeout=timeout)
        try:
            os.close(self._fd)
        except OSError:
            return
