"""SetFileInfoCache — post-election FileInfo cache for one erasure set.

A hot GET/HEAD pays N per-drive `read_version` calls plus a quorum
election per request even when nothing changed. This cache stores the
ELECTED FileInfo (inline payload included) keyed by (bucket, object,
version_id) and revalidates it with one cheap per-LOCAL-drive journal
signature check instead of the fan-out:

- while the metaplane WAL is armed, a drive's signature is its
  ("w", lsn) — a dict lookup; every journal mutation on that drive
  bumps it at submit time;
- otherwise it is the journal's (inode, mtime_ns, size) stat triple —
  the same racy-stat-hardened signature the per-drive journal cache
  uses (storage/local.py).

Coherence with writers in OTHER processes (the distributed case: every
node serves the same set) rides the same signatures: a remote node's
commit reaches this node's local drives through the storage RPC, moves
their signatures, and the next lookup misses into a fresh election. An
entry is only stored when at least one local-drive signature could be
captured; a write that reached quorum while missing EVERY local drive
is the one stale window (bounded by heal, which rewrites the local
copies and moves the signatures). In-process mutating paths
additionally invalidate eagerly (delete, multipart complete, heal,
tags/metadata writes) so the common case never waits on a signature
mismatch.

Entries hand out clones both ways (callers mutate FileInfo freely).
Delete markers and error results are never cached — negative caching
would turn an in-flight PUT into a spurious 404.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from minio_tpu import obs

_HITS = obs.counter(
    "minio_tpu_metaplane_cache_hits_total",
    "Set-level FileInfo cache hits (quorum fan-out + election skipped)"
).labels()
_MISSES = obs.counter(
    "minio_tpu_metaplane_cache_misses_total",
    "Set-level FileInfo cache misses (absent or signature-invalidated)"
).labels()
_INVALIDATIONS = obs.counter(
    "minio_tpu_metaplane_cache_invalidations_total",
    "Set-level FileInfo cache entries dropped by mutating paths"
).labels()


def _local_base(drive):
    """The underlying LocalDrive for signature checks, or None. Peels
    only the health/disk-id decorators (healthcheck.unwrap): remote
    clients and fault injectors are not signature sources."""
    from minio_tpu.storage import healthcheck as _health
    from minio_tpu.storage.local import LocalDrive

    base = _health.unwrap(drive)
    return base if isinstance(base, LocalDrive) else None


class SetFileInfoCache:
    def __init__(self, cap: int = 4096):
        self._cap = max(16, cap)
        self._mu = threading.Lock()
        # (bucket, obj) -> {version_id: (FileInfo, [(LocalDrive, sig)])}
        self._objects: "OrderedDict[tuple[str, str], dict]" = OrderedDict()

    # ---------- read path ----------

    def lookup(self, bucket: str, obj: str, version_id: str = ""):
        """The cached elected FileInfo (a private clone) when every
        recorded local-drive signature still matches; else None."""
        key = (bucket, obj)
        with self._mu:
            vids = self._objects.get(key)
            rec = vids.get(version_id) if vids else None
            if rec is not None:
                self._objects.move_to_end(key)
        if rec is None:
            _MISSES.inc()
            return None
        fi, sigs = rec
        # Signature checks run outside the lock: stat-backed sigs touch
        # the filesystem.
        for drive, sig in sigs:
            if drive.meta_sig(bucket, obj) != sig:
                with self._mu:
                    vids = self._objects.get(key)
                    if vids is not None and vids.get(version_id) is rec:
                        del vids[version_id]
                        if not vids:
                            self._objects.pop(key, None)
                _MISSES.inc()
                return None
        _HITS.inc()
        return fi.clone()

    # ---------- write-through ----------

    def snapshot_sigs(self, bucket: str, obj: str, drives) -> list:
        """Per-local-drive signatures captured BEFORE a quorum election
        (pass to populate): if a mutation interleaves with the fan-out
        read, these pre-read signatures no longer match the drives at
        the next lookup, so the stale election can never be served. A
        populate with post-read signatures would validate a pre-read
        FileInfo against post-mutation state — caching exactly the
        write the reader raced."""
        sigs = []
        for d in drives:
            base = _local_base(d)
            if base is None:
                continue
            sigs.append((base, base.meta_sig(bucket, obj)))
        return sigs

    def populate(self, bucket: str, obj: str, version_id: str, fi,
                 drives, sigs: "list | None" = None) -> None:
        """Store an elected (or just-committed) FileInfo. `sigs` must be
        a pre-read snapshot_sigs() capture for election results; None
        (capture now) is only safe when the caller holds the object's
        namespace lock around both the commit and this call (the
        write-through path). No-op unless at least one local-drive
        signature is known — a node with no local member of this set
        cannot validate and must re-elect."""
        if fi is None or getattr(fi, "deleted", False):
            return
        if sigs is None:
            sigs = self.snapshot_sigs(bucket, obj, drives)
        if not sigs or any(sig is None for _b, sig in sigs):
            return  # journal not (yet) visible on a local drive: unsafe
        rec = (fi.clone(), sigs)
        key = (bucket, obj)
        with self._mu:
            vids = self._objects.get(key)
            if vids is None:
                vids = {}
                self._objects[key] = vids
            # Bound the per-object version dict too: the object-level
            # LRU never evicts a HOT object, so distinct-version reads
            # against one key would otherwise accumulate entries (and
            # inline payloads) without limit.
            if version_id not in vids:
                while len(vids) >= 8:
                    vids.pop(next(iter(vids)))
            vids[version_id] = rec
            self._objects.move_to_end(key)
            while len(self._objects) > self._cap:
                self._objects.popitem(last=False)

    # ---------- invalidation ----------

    def invalidate(self, bucket: str, obj: str) -> None:
        """Drop every cached version of an object (mutating paths)."""
        with self._mu:
            had = self._objects.pop((bucket, obj), None)
        if had:
            _INVALIDATIONS.inc()

    def clear(self) -> None:
        with self._mu:
            self._objects.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._objects)
