"""Group-commit metadata plane (docs/METAPLANE.md).

The host-side twin of the batched device data plane (PR 8): where
`dataplane/` coalesces concurrent codec work into fused-kernel lane
launches, this package coalesces concurrent journal commits into one
durable WAL fsync per drive per batch, and puts a set-level
post-election FileInfo cache in front of the N-drive quorum read.

Three pieces:

- `wal.py` — the per-drive append-only journal format: CRC-framed
  records, torn-tail-tolerant scan, replay-on-mount fold.
- `groupcommit.py` — `DriveWAL`: one committer thread per drive;
  concurrent journal stores enqueue records and get futures, the
  committer appends a batch and fsyncs ONCE (durability is the WAL
  fsync, not the materialized `meta.mp`); per-object journals
  materialize asynchronously, with checkpoint/truncate keeping the
  journal bounded.
- `setcache.py` — `SetFileInfoCache`: write-through post-election
  FileInfo cache consulted by GET/HEAD before the per-drive fan-out,
  validated against per-local-drive journal signatures.

ON BY DEFAULT since the pipeline convergence (PR 12): the env gate is
opt-OUT — `MTPU_METAPLANE=0` restores the per-request
write+fsync+rename path, which survives as the fallback and the
correctness oracle (the chaos-storm oracle runs are its remaining
deployment). WAL replay on drive mount runs regardless of the gate (a
journal left by a crashed armed process must converge even if the next
boot is unarmed).
Committer threads are session-lived daemons named `mtpu-metaplane-*`
(exempted in utils/sanitize.py).
"""

from __future__ import annotations

import os

ENABLE_ENV = "MTPU_METAPLANE"


def enabled() -> bool:
    """Read the env gate live — cheap, and tests flip it per-case.
    Default ON; "0"/"false"/"off" opts out (per-request oracle)."""
    return os.environ.get(ENABLE_ENV, "1") not in ("0", "false", "off")


def wal_max_bytes() -> int:
    """Checkpoint threshold: WAL size that triggers materialize-all +
    sync + truncate (the journal stays bounded)."""
    return int(os.environ.get("MTPU_WAL_MAX_BYTES", str(16 << 20)))


def wal_max_pending() -> int:
    """Materialization backlog bound: above this many distinct pending
    keys the committer drains even under sustained commit load."""
    return int(os.environ.get("MTPU_WAL_MAX_PENDING", "4096"))


def wal_max_batch() -> int:
    """Records per group commit (writev bound; IOV_MAX headroom)."""
    return int(os.environ.get("MTPU_WAL_MAX_BATCH", "256"))


def wal_queue_depth() -> int:
    """Bounded submission queue per drive — full queue is backpressure
    (FaultyDisk to the caller, counted in quorum), never unbounded RAM."""
    return int(os.environ.get("MTPU_WAL_QUEUE", "8192"))


def wal_segment() -> str:
    """Journal segment suffix for this process (`journal.<seg>.wal`).
    Empty = the classic single-owner `journal.wal`. The front-door
    supervisor stamps `MTPU_WAL_SEGMENT=w<id>` into every worker so
    each per-drive WAL file keeps exactly one writer process
    (docs/FRONTDOOR.md single-writer contract)."""
    return os.environ.get("MTPU_WAL_SEGMENT", "")


def single_owner() -> bool:
    """True when this process is the drive's only journal writer — the
    classic deployment. False under a multi-worker front door, where
    cross-process coherence rules apply: journals materialize eagerly
    inside the ack (still no per-file fsync), cache signatures fall
    back to stat triples, and the fresh-volume existence proof is
    disabled (a sibling may have created the journal)."""
    from minio_tpu import frontdoor

    return not frontdoor.multiworker()


def eager_materialize() -> bool:
    """Materialize each batch before resolving its futures. Forced in
    multi-worker mode (cross-process read-your-write flows through the
    filesystem); opt-in via MTPU_WAL_EAGER=1 otherwise."""
    return (not single_owner()
            or os.environ.get("MTPU_WAL_EAGER", "") == "1")


def cache_objects() -> int:
    """Set-level FileInfo cache capacity in objects (LRU)."""
    return int(os.environ.get("MTPU_METAPLANE_CACHE", "4096"))
