"""Cluster assembly: endpoint args → a running distributed node.

Role-equivalent of cmd/server-main.go:404-553 for the distributed path:
expand endpoints, start the RPC fabric (storage + lock + peer + bootstrap
planes), verify topology with peers, then build pools × sets over
local + remote drives. Every node is symmetric — any node serves any S3
request; per-drive calls route to the drive's owner over the storage plane.

The RPC fabric listens on its own port (S3 port + RPC_PORT_OFFSET by
default — the reference muxes both onto one listener; two listeners keep
the async S3 front door and the threaded RPC plane independent).
"""

from __future__ import annotations

import os

from minio_tpu.dist import endpoint as epmod
from minio_tpu.dist.dsync import LocalLocker, RemoteLocker, lock_routes
from minio_tpu.dist.nslock import NamespaceLockMap
from minio_tpu.dist.peer import (
    NotificationSys,
    PeerClient,
    PeerHooks,
    bootstrap_routes,
    peer_routes,
    verify_cluster_bootstrap,
)
from minio_tpu.dist.rpc import RestClient
from minio_tpu.dist.server import NodeServer
from minio_tpu.dist.storage_remote import RemoteDrive, storage_routes
from minio_tpu.storage.api import StorageAPI
from minio_tpu.storage.local import LocalDrive

RPC_PORT_OFFSET = 1000


class ClusterNode:
    """One symmetric node of a distributed deployment."""

    def __init__(self, pool_args: list[list[str]], host: str, port: int,
                 secret: str, root_dir_map=None, set_drive_count: int = 0,
                 local_names: set[str] | None = None,
                 rpc_port: int | None = None, parity: int | None = None,
                 rpc_port_of=None, certs_dir: str = ""):
        """pool_args: endpoint args per pool (already split). host/port:
        this node's advertised S3 address — endpoints matching it are local.
        root_dir_map: optional fn(endpoint_path) -> local fs dir (tests map
        drive paths into tmp dirs; production uses the path as-is).
        rpc_port_of: fn(host, s3_port) -> rpc port for a peer (defaults to
        s3_port + RPC_PORT_OFFSET; tests use OS-assigned ports).
        certs_dir: when set, the ENTIRE node fabric (storage/lock/peer/
        bootstrap) serves TLS with the dir's key pair and every client
        pins the dir's public.crt as its CA — the reference serves all
        inter-node planes on its TLS listener (pkg/certs role). All
        nodes share one certs dir (or one CA) by deployment convention."""
        self.host = host
        self.port = port
        self.secret = secret
        self.certs_dir = certs_dir
        self._client_ssl = None
        server_ssl = None
        self.rpc_scheme = "http"
        if certs_dir:
            from minio_tpu.utils.certs import CertManager, ClientCAManager

            # Pass the managers, not contexts: both sides of the fabric
            # consult .current() per connection, so rotated certs
            # hot-reload inbound AND outbound. Peers are addressed by
            # IP/host, not the cert CN: the client verifies the chain
            # against the pinned cluster cert, skipping name matching.
            server_ssl = CertManager(certs_dir)
            self._client_ssl = ClientCAManager(
                os.path.join(certs_dir, "public.crt"))
            self.rpc_scheme = "https"
        self.rpc_port = rpc_port if rpc_port is not None else port + RPC_PORT_OFFSET
        self._rpc_port_of = rpc_port_of or (
            lambda h, p: p + RPC_PORT_OFFSET)
        root_dir_map = root_dir_map or (lambda p: p)

        self.pools_layout = epmod.create_pool_layouts(
            pool_args, local_host=host, local_port=port,
            set_drive_count=set_drive_count, local_names=local_names)
        self.layout_sig = epmod.layout_signature(self.pools_layout)

        # --- local drives + RPC fabric ---
        self.local_drives: dict[str, LocalDrive] = {}
        for pool in self.pools_layout:
            for ep in pool.endpoints:
                if ep.is_local and ep.path not in self.local_drives:
                    self.local_drives[ep.path] = LocalDrive(
                        root_dir_map(ep.path), endpoint=ep.url)

        self.locker = LocalLocker()
        self.hooks = PeerHooks()
        # Advertised identity: the `node` stamp on trace records emitted
        # while serving peers, and the `server` label this node's scrape
        # carries in the federated cluster metrics.
        self.node_name = f"{host}:{port}"
        self.node_server = NodeServer(host="0.0.0.0" if host not in
                                      ("127.0.0.1", "localhost") else host,
                                      port=self.rpc_port, secret=secret,
                                      ssl_context=server_ssl,
                                      node_name=self.node_name)
        self.node_server.register_plane(
            "storage", storage_routes(self.local_drives))
        self.node_server.register_plane("lock", lock_routes(self.locker))
        self.node_server.register_plane("peer", peer_routes(self.hooks))
        self.node_server.register_plane(
            "bootstrap", bootstrap_routes(self.layout_sig))
        self.node_server.start()
        self.rpc_port = self.node_server.port  # resolves OS-assigned port 0

        # --- peer clients (one RestClient per remote node) ---
        self._clients: dict[tuple[str, int], RestClient] = {}
        self.peer_nodes: list[tuple[str, int]] = []
        seen = set()
        for pool in self.pools_layout:
            for ep in pool.endpoints:
                if ep.is_local or not ep.host or ep.node in seen:
                    continue
                seen.add(ep.node)
                self.peer_nodes.append(ep.node)
        self.peers = [PeerClient(self._client_for(n), name=f"{n[0]}:{n[1]}")
                      for n in self.peer_nodes]
        self.notification = NotificationSys(self.peers)

        # Quorum lockers: this node's local locker + every peer's.
        self.lockers: list = [self.locker] + [
            RemoteLocker(self._client_for(n)) for n in self.peer_nodes]

        self._parity = parity
        self.object_layer = None

    def _client_for(self, node: tuple[str, int]) -> RestClient:
        if node not in self._clients:
            host, port = node
            # name: advertised S3 identity, so metric `peer` labels and
            # fault-injection partitions are declared in TOPOLOGY terms
            # (not transport ports) — asymmetric partitions then work
            # with many in-process nodes.
            c = RestClient(
                host, self._rpc_port_of(host, port), self.secret,
                scheme=self.rpc_scheme, ssl_context=self._client_ssl,
                name=f"{host}:{port}")
            c.fault_src = self.node_name
            self._clients[node] = c
        return self._clients[node]

    def peer_fabric_info(self) -> list[dict]:
        """Per-peer circuit breaker state + retry/shed counters — the
        admin server-info surface of the peer-resilience plane (mirror of
        the per-drive healthState entries)."""
        return [self._client_for(n).breaker_info() for n in self.peer_nodes]

    # -- boot --

    def wait_for_peers(self, timeout: float = 60.0) -> None:
        verify_cluster_bootstrap(self.peers, self.layout_sig, timeout=timeout)

    def drive_for(self, ep: epmod.Endpoint) -> StorageAPI:
        if ep.is_local:
            return self.local_drives[ep.path]
        return RemoteDrive(self._client_for(ep.node), ep.path, endpoint=ep.url)

    def build_object_layer(self, **set_kwargs):
        """Pools × sets over the expanded endpoints. Distributed topologies
        get a dsync-quorum namespace lock spanning all nodes."""
        from minio_tpu.erasure.pools import ErasureServerPools
        from minio_tpu.erasure.sets import ErasureSets

        distributed = bool(self.peer_nodes)
        pools = []
        try:
            for pool in self.pools_layout:
                drives = [self.drive_for(ep) for ep in pool.endpoints]
                nslock = NamespaceLockMap(
                    distributed=distributed, lockers=self.lockers,
                    owner=f"{self.host}:{self.port}") if distributed else None
                # Fresh-format leadership: only the node owning the pool's
                # FIRST endpoint may mint a deployment id; everyone else
                # retries until the leader's format lands (reference
                # firstDisk gating in waitForFormatErasure).
                pools.append(ErasureSets(
                    drives, set_drive_count=pool.set_drive_count,
                    parity=self._parity, nslock=nslock,
                    can_format_fresh=pool.endpoints[0].is_local,
                    **set_kwargs))
        except Exception:
            # A later pool failing (e.g. waiting on the format leader)
            # must not leak earlier pools' worker threads across the
            # caller's boot retries.
            for p in pools:
                try:
                    p.close()
                except Exception:  # noqa: BLE001 — teardown only
                    pass
            raise
        self.object_layer = ErasureServerPools(pools)
        return self.object_layer

    def close(self) -> None:
        if self.object_layer is not None:
            self.object_layer.close()
        for p in self.peers:
            p.close()
        for c in self._clients.values():
            c.close()
        self.node_server.close()
