"""Bucket federation directory — the etcd/DNS federation role.

Role-equivalent of cmd/etcd.go + pkg/dns + initFederatorBackend
(cmd/bucket-handlers.go:71): multiple independent clusters share one
namespace of buckets. Each cluster registers the buckets it owns in a
shared directory; a request for a bucket owned elsewhere answers with a
307 redirect to the owning cluster (the server-side half of what the
reference's DNS records do client-side).

The directory backend is a shared JSON file (NFS/shared volume — the
zero-egress stand-in for etcd): atomic same-directory rename writes,
mtime-checked reloads, last-writer-wins per bucket. The interface is the
seam where an etcd/Consul client would plug.
"""

from __future__ import annotations

import json
import os
import threading
import time


class FederationError(Exception):
    pass


class FederationStore:
    """bucket -> owning cluster endpoint, backed by a shared JSON file."""

    def __init__(self, path: str, endpoint: str):
        """path: the shared directory file; endpoint: THIS cluster's
        advertised URL (scheme://host:port), recorded as the owner for
        buckets registered here."""
        self.path = path
        self.endpoint = endpoint.rstrip("/")
        self._mu = threading.Lock()
        self._cache: dict[str, str] = {}
        self._mtime = -1.0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    # -- directory I/O --

    def _load_locked(self) -> dict[str, str]:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            self._cache, self._mtime = {}, -1.0
            return self._cache
        if mtime != self._mtime:
            try:
                with open(self.path, encoding="utf-8") as f:
                    doc = json.load(f)
                self._cache = {str(k): str(v)
                               for k, v in doc.get("buckets", {}).items()}
                self._mtime = mtime
            except (OSError, ValueError):
                pass  # half-written by a peer: keep the last good view
        return self._cache

    def _write_locked(self, table: dict[str, str]) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"buckets": table, "updated": time.time()}, f,
                      indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._cache = dict(table)
        try:
            self._mtime = os.stat(self.path).st_mtime
        except OSError:
            self._mtime = -1.0

    # -- the federation surface --

    def lookup(self, bucket: str) -> str | None:
        """Owning endpoint, or None when unclaimed."""
        with self._mu:
            return self._load_locked().get(bucket)

    def is_remote(self, bucket: str) -> bool:
        owner = self.lookup(bucket)
        return owner is not None and owner != self.endpoint

    def register(self, bucket: str) -> None:
        """Claim `bucket` for this cluster; FederationError if another
        cluster already owns it (global bucket-name uniqueness — the
        reference returns BucketAlreadyExists from the DNS check)."""
        with self._mu:
            table = dict(self._load_locked())
            owner = table.get(bucket)
            if owner is not None and owner != self.endpoint:
                raise FederationError(
                    f"bucket {bucket!r} is owned by {owner}")
            table[bucket] = self.endpoint
            self._write_locked(table)

    def unregister(self, bucket: str) -> None:
        with self._mu:
            table = dict(self._load_locked())
            if table.get(bucket) == self.endpoint:
                del table[bucket]
                self._write_locked(table)

    def buckets(self) -> dict[str, str]:
        with self._mu:
            return dict(self._load_locked())
