"""Peer control plane + bootstrap verification.

Role-equivalent of cmd/peer-rest-{server,client}.go (the node-to-node admin
fabric) and cmd/bootstrap-peer-server.go (pre-start topology handshake).
The peer plane starts minimal — health, layout verification, cache
invalidation hooks — and grows with the subsystems that need fan-out
(IAM reload, bucket-metadata invalidation, trace subscription).
"""

from __future__ import annotations

import time
from typing import Callable

from minio_tpu.dist.rpc import RestClient, pack, unpack
from minio_tpu.utils import errors as se

PLANE = "peer"
BOOTSTRAP_PLANE = "bootstrap"


# --- server side -------------------------------------------------------------

def bootstrap_routes(layout_sig: str, version: str = "1") -> dict:
    """The handshake target: peers compare topology before serving
    (cmd/bootstrap-peer-server.go:162)."""

    def h_verify(params, body):
        return pack({"sig": layout_sig, "version": version,
                     "time": time.time()})

    return {"verify": h_verify}


class PeerHooks:
    """Callbacks the peer plane invokes on this node. Subsystems register
    theirs at init (NotificationSys role, cmd/notification.go:60)."""

    def __init__(self):
        self.on_bucket_metadata_invalidate: Callable[[str], None] = lambda b: None
        self.on_iam_reload: Callable[[], None] = lambda: None
        self.health: Callable[[], dict] = lambda: {"ok": True}


def peer_routes(hooks: PeerHooks) -> dict:
    def h_health(params, body):
        return pack(hooks.health())

    def h_invalidate_bucket_metadata(params, body):
        hooks.on_bucket_metadata_invalidate(params.get("bucket", ""))

    def h_reload_iam(params, body):
        hooks.on_iam_reload()

    return {"health": h_health,
            "invalidate_bucket_metadata": h_invalidate_bucket_metadata,
            "reload_iam": h_reload_iam}


# --- client side -------------------------------------------------------------

class PeerClient:
    """One per peer node (cmd/peer-rest-client.go)."""

    def __init__(self, client: RestClient):
        self._client = client

    def health(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{PLANE}/v1/health")

    def invalidate_bucket_metadata(self, bucket: str) -> None:
        self._client.call(f"/rpc/{PLANE}/v1/invalidate_bucket_metadata",
                          {"bucket": bucket})

    def reload_iam(self) -> None:
        self._client.call(f"/rpc/{PLANE}/v1/reload_iam")

    def verify_bootstrap(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{BOOTSTRAP_PLANE}/v1/verify")

    def is_online(self) -> bool:
        return self._client.is_online()


def verify_cluster_bootstrap(peers: list[PeerClient], layout_sig: str,
                             timeout: float = 60.0,
                             interval: float = 0.25) -> None:
    """Retry until every peer answers with the same topology signature
    (the reference's retry loop, cmd/server-main.go:484-498). Raises
    CorruptedFormat on a signature mismatch (misconfigured cluster) and
    OperationTimedOut if peers never come up."""
    deadline = time.monotonic() + timeout
    pending = list(peers)
    while pending:
        still = []
        for p in pending:
            try:
                doc = p.verify_bootstrap()
            except Exception:
                still.append(p)
                continue
            if doc.get("sig") != layout_sig:
                raise se.CorruptedFormat(
                    f"peer topology mismatch: {doc.get('sig')!r} != "
                    f"{layout_sig!r} — all nodes must be started with the "
                    f"same endpoint arguments")
        pending = still
        if pending:
            if time.monotonic() > deadline:
                raise se.OperationTimedOut(
                    "", "", f"{len(pending)} peers unreachable during bootstrap")
            time.sleep(interval)


class NotificationSys:
    """Fan-out wrapper over all peers (cmd/notification.go:60): best-effort
    broadcast of control-plane events; a down peer reconciles from
    persistent state when it returns."""

    def __init__(self, peers: list[PeerClient]):
        self.peers = peers

    def _fanout(self, fn: Callable[[PeerClient], None]) -> list[Exception | None]:
        out: list[Exception | None] = []
        for p in self.peers:
            try:
                fn(p)
                out.append(None)
            except Exception as e:  # noqa: BLE001 - best-effort plane
                out.append(e)
        return out

    def invalidate_bucket_metadata(self, bucket: str) -> None:
        self._fanout(lambda p: p.invalidate_bucket_metadata(bucket))

    def reload_iam(self) -> None:
        self._fanout(lambda p: p.reload_iam())
