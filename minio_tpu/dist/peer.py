"""Peer control plane + bootstrap verification.

Role-equivalent of cmd/peer-rest-{server,client}.go (the node-to-node admin
fabric) and cmd/bootstrap-peer-server.go (pre-start topology handshake).
The peer plane starts minimal — health, layout verification, cache
invalidation hooks — and grows with the subsystems that need fan-out
(IAM reload, bucket-metadata invalidation, trace subscription).
"""

from __future__ import annotations

import time
from typing import Callable

from minio_tpu.dist.rpc import RestClient, pack, unpack
from minio_tpu.utils import errors as se

PLANE = "peer"
BOOTSTRAP_PLANE = "bootstrap"


# --- server side -------------------------------------------------------------

def bootstrap_routes(layout_sig: str, version: str = "1") -> dict:
    """The handshake target: peers compare topology before serving
    (cmd/bootstrap-peer-server.go:162)."""

    def h_verify(params, body):
        return pack({"sig": layout_sig, "version": version,
                     "time": time.time()})

    return {"verify": h_verify}


class PeerHooks:
    """Callbacks the peer plane invokes on this node. Subsystems register
    theirs at init (NotificationSys role, cmd/notification.go:60)."""

    def __init__(self):
        self.on_bucket_metadata_invalidate: Callable[[str], None] = lambda b: None
        self.on_iam_reload: Callable[[], None] = lambda: None
        self.health: Callable[[], dict] = lambda: {"ok": True}
        # Observability fan-in (cmd/peer-rest-common.go:27-61 breadth):
        self.server_info: Callable[[], dict] = lambda: {}
        self.obd_info: Callable[[], dict] = lambda: {}
        self.trace_bus = None        # admin.pubsub.PubSub | None
        self.console_bus = None      # admin.pubsub.PubSub | None
        self.profiler = None         # admin.profiling.Profiler | None
        # Node-scope Prometheus exposition (bytes) — what the federated
        # cluster scrape pulls and relabels under server=<this node>.
        self.metrics: Callable[[], bytes] = lambda: b""
        # Flight-recorder query: params {traceid, api, worst} -> this
        # node's stage timelines (admin perf/timeline federation).
        self.perf_timeline: Callable[[dict], dict] = lambda params: {
            "node": "", "timelines": []}
        # SLO plane (obs/slo.py): this node's worker-merged burn-rate
        # state, pulled by the federated GET /minio/admin/v3/slo.
        self.slo: Callable[[], dict] = lambda: {}


def _stream_bus(bus):
    """Chunked-stream a pubsub as msgpack docs with 1 s heartbeats (the
    heartbeat is what lets the server notice a gone subscriber)."""
    if bus is None:
        return
    with bus.subscribe() as sub:
        while True:
            item = sub.get(timeout=1.0)
            yield pack({"hb": 1} if item is None else item)


def peer_routes(hooks: PeerHooks) -> dict:
    def h_health(params, body):
        return pack(hooks.health())

    def h_invalidate_bucket_metadata(params, body):
        hooks.on_bucket_metadata_invalidate(params.get("bucket", ""))

    def h_reload_iam(params, body):
        hooks.on_iam_reload()

    def h_server_info(params, body):
        return pack(hooks.server_info())

    def h_obd_info(params, body):
        return pack(hooks.obd_info())

    def h_metrics(params, body):
        return bytes(hooks.metrics())

    def h_perf_timeline(params, body):
        return pack(hooks.perf_timeline(params or {}))

    def h_slo(params, body):
        return pack(hooks.slo())

    def h_trace(params, body):
        return _stream_bus(hooks.trace_bus)

    def h_consolelog(params, body):
        return _stream_bus(hooks.console_bus)

    def h_profile_start(params, body):
        if hooks.profiler is None:
            raise se.FaultyDisk("no profiler on this node")
        kinds = tuple((params.get("kinds") or "cpu").split(","))
        hooks.profiler.start(kinds)
        return pack({"ok": True})

    def h_profile_download(params, body):
        if hooks.profiler is None:
            raise se.FaultyDisk("no profiler on this node")
        return pack(hooks.profiler.stop_collect())

    return {"health": h_health,
            "invalidate_bucket_metadata": h_invalidate_bucket_metadata,
            "reload_iam": h_reload_iam,
            "server_info": h_server_info,
            "obd_info": h_obd_info,
            "metrics": h_metrics,
            "perf_timeline": h_perf_timeline,
            "slo": h_slo,
            "trace": h_trace,
            "consolelog": h_consolelog,
            "profile_start": h_profile_start,
            "profile_download": h_profile_download}


# --- client side -------------------------------------------------------------

class PeerClient:
    """One per peer node (cmd/peer-rest-client.go)."""

    def __init__(self, client: RestClient, name: str = ""):
        """name: the peer's ADVERTISED identity (S3 host:port) — what its
        own trace records carry as `node` and its scrape carries as the
        `server` label. Falls back to the fabric address (RPC port)."""
        self._client = client
        self._name = name
        self._obs_client: RestClient | None = None

    def _metrics_client(self) -> RestClient:
        """Dedicated client for the federated metrics pull. The scrape
        must NEVER ride the shared fabric client: a peer whose metrics
        hook stalls past the adaptive metadata deadline would otherwise
        mark the whole peer offline (storage, locks, everything) and
        inflate the shared DynamicTimeout — an observability call
        degrading the data plane. This clone keeps its own offline state
        and deadline convergence, scoped to the metrics route."""
        if self._obs_client is None:
            c = self._client

            class _SSLShim:  # re-pin the fabric CA without sharing state
                current = staticmethod(c._get_ssl)

            # name= pins the same advertised identity as the fabric
            # client, so the `peer` metric labels and fault-injection
            # destination agree across both clients: a partition
            # covering the peer blacks out the metrics pull too (its
            # breaker stays independent by design), and dashboards see
            # one peer, not a transport-address phantom.
            self._obs_client = RestClient(
                c.host, c.port, c.secret, timeout=c.timeout,
                scheme=c.scheme,
                ssl_context=_SSLShim() if c.scheme == "https" else None,
                name=c.fault_dst, lane="metrics")
            self._obs_client.fault_src = c.fault_src
        return self._obs_client

    @property
    def name(self) -> str:
        return self._name or f"{self._client.host}:{self._client.port}"

    def health(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{PLANE}/v1/health")

    def invalidate_bucket_metadata(self, bucket: str) -> None:
        self._client.call(f"/rpc/{PLANE}/v1/invalidate_bucket_metadata",
                          {"bucket": bucket})

    def reload_iam(self) -> None:
        self._client.call(f"/rpc/{PLANE}/v1/reload_iam")

    def verify_bootstrap(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{BOOTSTRAP_PLANE}/v1/verify")

    def server_info(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{PLANE}/v1/server_info")

    def obd_info(self) -> dict:
        return self._client.call_msgpack(f"/rpc/{PLANE}/v1/obd_info")

    def metrics(self) -> bytes:
        """The peer's node-scope Prometheus exposition (raw bytes)."""
        return self._metrics_client().call(f"/rpc/{PLANE}/v1/metrics")

    def perf_timeline(self, params: dict | None = None) -> dict:
        """The peer's flight-recorder timelines (filtered server-side).
        Rides the dedicated observability client for the same reason as
        metrics(): a stalled query must not poison the fabric client."""
        return self._metrics_client().call_msgpack(
            f"/rpc/{PLANE}/v1/perf_timeline", params or {})

    def slo(self) -> dict:
        """The peer's worker-merged SLO burn-rate state (obs/slo.py).
        Same dedicated observability client as metrics()."""
        return self._metrics_client().call_msgpack(
            f"/rpc/{PLANE}/v1/slo")

    def trace_stream(self, heartbeats: bool = False):
        """Iterator over the peer's trace records — the remote half of
        `mc admin trace` (cmd/peer-rest-client.go:782). heartbeats=True
        also yields the 1 s keepalive docs ({"hb": 1}) so a consumer can
        re-check its stop condition on an idle peer."""
        for doc in self._client.iter_msgpack(f"/rpc/{PLANE}/v1/trace"):
            if doc.get("hb") and not heartbeats:
                continue
            yield doc

    def console_stream(self, heartbeats: bool = False):
        for doc in self._client.iter_msgpack(f"/rpc/{PLANE}/v1/consolelog"):
            if doc.get("hb") and not heartbeats:
                continue
            yield doc

    def profile_start(self, kinds: str = "cpu") -> None:
        self._client.call(f"/rpc/{PLANE}/v1/profile_start", {"kinds": kinds})

    def profile_download(self) -> dict:
        """-> {filename: bytes} of the peer's collected profiles."""
        return self._client.call_msgpack(f"/rpc/{PLANE}/v1/profile_download")

    def is_online(self) -> bool:
        return self._client.is_online()

    def close(self) -> None:
        """Release the dedicated metrics client (the shared fabric client
        is owned and closed by the cluster node)."""
        if self._obs_client is not None:
            self._obs_client.close()
            self._obs_client = None


def verify_cluster_bootstrap(peers: list[PeerClient], layout_sig: str,
                             timeout: float = 60.0,
                             interval: float = 0.25) -> None:
    """Retry until every peer answers with the same topology signature
    (the reference's retry loop, cmd/server-main.go:484-498). Raises
    CorruptedFormat on a signature mismatch (misconfigured cluster) and
    OperationTimedOut if peers never come up."""
    deadline = time.monotonic() + timeout
    pending = list(peers)
    while pending:
        still = []
        for p in pending:
            try:
                doc = p.verify_bootstrap()
            except Exception:
                still.append(p)
                continue
            if doc.get("sig") != layout_sig:
                raise se.CorruptedFormat(
                    f"peer topology mismatch: {doc.get('sig')!r} != "
                    f"{layout_sig!r} — all nodes must be started with the "
                    f"same endpoint arguments")
        pending = still
        if pending:
            if time.monotonic() > deadline:
                raise se.OperationTimedOut(
                    "", "", f"{len(pending)} peers unreachable during bootstrap")
            time.sleep(interval)


class NotificationSys:
    """Fan-out wrapper over all peers (cmd/notification.go:60): best-effort
    broadcast of control-plane events; a down peer reconciles from
    persistent state when it returns."""

    def __init__(self, peers: list[PeerClient]):
        self.peers = peers

    def _fanout(self, fn: Callable[[PeerClient], object]) -> list:
        """Concurrent best-effort broadcast — latency is one peer's RPC
        (bounded by the client timeout), not the sum over peers (the
        reference fans out in goroutines, cmd/notification.go)."""
        if not self.peers:
            return []
        from concurrent.futures import ThreadPoolExecutor

        def one(p):
            try:
                return fn(p)
            except Exception as e:  # noqa: BLE001 - best-effort plane
                return e

        with ThreadPoolExecutor(max_workers=min(16, len(self.peers))) as ex:
            return list(ex.map(one, self.peers))

    def invalidate_bucket_metadata(self, bucket: str) -> None:
        self._fanout(lambda p: p.invalidate_bucket_metadata(bucket))

    def reload_iam(self) -> None:
        self._fanout(lambda p: p.reload_iam())

    # -- observability fan-in (cmd/notification.go:286-1237) --

    def server_info_all(self) -> list[dict]:
        results = self._fanout(lambda p: p.server_info())
        return [r if not isinstance(r, Exception)
                else {"error": str(r), "node": p.name}
                for p, r in zip(self.peers, results)]

    def obd_all(self) -> list[dict]:
        results = self._fanout(lambda p: p.obd_info())
        return [r if not isinstance(r, Exception)
                else {"error": str(r), "node": p.name}
                for p, r in zip(self.peers, results)]

    def perf_all(self, params: dict | None = None) -> list[dict]:
        """Every peer's flight-recorder timelines — the perf/timeline
        endpoint's cluster fan-out (same shape as server_info_all)."""
        results = self._fanout(lambda p: p.perf_timeline(params))
        return [r if not isinstance(r, Exception)
                else {"error": str(r), "node": p.name}
                for p, r in zip(self.peers, results)]

    def start_profiling_all(self, kinds: str = "cpu") -> list:
        return self._fanout(lambda p: p.profile_start(kinds))

    def download_profiling_all(self) -> dict[str, dict[str, bytes]]:
        results = self._fanout(lambda p: p.profile_download())
        return {p.name: r for p, r in zip(self.peers, results)
                if not isinstance(r, Exception)}
