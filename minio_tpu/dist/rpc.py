"""Generic inter-node REST client — the shared transport for all RPC planes.

Role-equivalent of cmd/rest/client.go: POST with URL-encoded args, streaming
request/response bodies, msgpack payloads, and a health-check-driven
online/offline state machine with background reconnect (rest.Client:75,
Call:120, MarkOffline:208).

Auth: every call carries an HMAC token derived from the cluster secret
(the reference signs inter-node requests with a JWT from the root
credentials, cmd/jwt/). Tokens are cheap to mint per call and expire.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import socket
import threading
import time
import urllib.parse
from typing import BinaryIO, Iterable, Iterator

import msgpack

from minio_tpu import obs
from minio_tpu.utils import errors as se

DEFAULT_TIMEOUT = 30.0
HEALTH_INTERVAL = 1.0        # reconnect probe cadence during the grace runs
HEALTH_GRACE_PROBES = 3      # probes at base cadence before backing off
HEALTH_BACKOFF_CAP = 10.0    # max delay between reconnect probes
ERR_STATUS = 599  # carries a typed storage error in the body

# Fabric observability: the r5 TCP_NODELAY fix and the adaptive connect
# deadline are only provable with a live latency distribution + failure
# counters per peer (reference minio_inter_node_* metric families).
_RPC_LATENCY = obs.histogram(
    "minio_tpu_rpc_latency_seconds",
    "Inter-node RPC call latency by peer", ("peer",))
_RPC_ERRORS = obs.counter(
    "minio_tpu_rpc_errors_total",
    "RPC calls failed on network/timeout by peer", ("peer",))
_RPC_OFFLINE = obs.counter(
    "minio_tpu_rpc_offline_total",
    "Transitions of a peer to offline", ("peer",))
_RPC_RECONNECTS = obs.counter(
    "minio_tpu_rpc_reconnects_total",
    "Successful reconnects after a peer went offline", ("peer",))


# --- auth tokens -------------------------------------------------------------

def sign_token(secret: str, ttl: float = 900.0, now: float | None = None) -> str:
    """Mint an expiring HMAC bearer token binding the cluster secret."""
    payload = json.dumps({"exp": (now or time.time()) + ttl}).encode()
    mac = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    return (base64.urlsafe_b64encode(payload).decode().rstrip("=")
            + "." + base64.urlsafe_b64encode(mac).decode().rstrip("="))


def verify_token(secret: str, token: str, now: float | None = None) -> bool:
    try:
        p64, m64 = token.split(".")
        pad = lambda s: s + "=" * (-len(s) % 4)  # noqa: E731
        payload = base64.urlsafe_b64decode(pad(p64))
        mac = base64.urlsafe_b64decode(pad(m64))
        want = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
        if not hmac.compare_digest(mac, want):
            return False
        return json.loads(payload)["exp"] >= (now or time.time())
    except Exception:
        return False


# --- wire helpers ------------------------------------------------------------

def pack(obj) -> bytes:
    return msgpack.packb(obj)


def unpack(raw: bytes):
    return msgpack.unpackb(raw, strict_map_key=False)


class _ResponseStream:
    """File-like over an HTTP response that returns its connection to the
    pool on close (exactly-once)."""

    def __init__(self, resp: http.client.HTTPResponse, client: "RestClient",
                 conn: http.client.HTTPConnection):
        self._resp = resp
        self._client = client
        self._conn = conn
        self._closed = False

    def _fail(self, e: Exception) -> "se.StorageError":
        """Mid-stream network failure: degrade like any per-drive error
        (quorum layers expect StorageError subtypes, not raw socket
        exceptions) and stop pooling the broken connection."""
        self._closed = True
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001
            pass
        self._client.mark_offline()
        return se.DiskNotFound(
            f"{self._client.host}:{self._client.port}: {e}")

    def read(self, n: int = -1) -> bytes:
        try:
            return (self._resp.read() if n is None or n < 0
                    else self._resp.read(n))
        except (OSError, http.client.HTTPException) as e:
            raise self._fail(e) from e

    def read1(self, n: int = 65536) -> bytes:
        """Return whatever is available (at most n) without waiting for n
        bytes — read(n) on a chunked response blocks until it accumulates n,
        which would stall live streams (trace/console subscriptions) whose
        documents trickle in."""
        try:
            return self._resp.read1(n)
        except (OSError, http.client.HTTPException) as e:
            raise self._fail(e) from e

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Drain so the connection is reusable — but bounded in both bytes
        # (1 MiB) and time (250 ms): an endless subscription stream
        # (trace/console heartbeats) would otherwise block this close
        # forever. Undrainable connections are dropped, not pooled.
        try:
            if self._resp.isclosed():
                self._client._put_conn(self._conn)
                return
            sock = self._conn.sock
            prev_timeout = sock.gettimeout() if sock is not None else None
            if sock is not None:
                sock.settimeout(0.25)
            leftover = self._resp.read(1 << 20)
            if leftover and len(leftover) == (1 << 20):
                self._conn.close()
                return
            if sock is not None:
                sock.settimeout(prev_timeout)  # the client's configured timeout
            self._client._put_conn(self._conn)
        except Exception:
            try:
                self._conn.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RestClient:
    """One per (node, plane-root). `call()` raises typed storage errors
    re-hydrated from the wire; network failures mark the client offline and
    a daemon probe brings it back (cmd/rest/client.go:135-168)."""

    def __init__(self, host: str, port: int, secret: str,
                 timeout: float = DEFAULT_TIMEOUT, scheme: str = "http",
                 ssl_context=None):
        """scheme "https" runs the fabric over TLS. ssl_context should pin
        the cluster CA (ClusterNode pins certs_dir/public.crt) — either a
        plain SSLContext or an object with .current() (ClientCAManager),
        consulted per connection so CA rotation hot-reloads. The default
        is a verifying system-CA context. An unverified context would let
        an active MITM replay the bearer token, so never default to
        CERT_NONE here."""
        from minio_tpu.utils.dyntimeout import DynamicTimeout

        self.host = host
        self.port = port
        self.secret = secret
        self.timeout = timeout
        # Self-tuning per-call deadline (reference dynamicTimeout,
        # cmd/dynamic-timeouts.go:35): a congested fabric inflates it,
        # a healthy one converges it down for faster failure detection.
        self.dyn_timeout = DynamicTimeout(timeout, minimum=min(1.0, timeout))
        self.scheme = scheme
        if scheme == "https" and ssl_context is None:
            import ssl as _ssl

            ssl_context = _ssl.create_default_context()
        self._get_ssl = (ssl_context.current
                         if hasattr(ssl_context, "current")
                         else lambda: ssl_context)
        self._online = True
        self._lock = threading.Lock()
        self._pool: list[http.client.HTTPConnection] = []
        self._probing = False
        self._closed = False
        self._probe_stop = threading.Event()
        peer = f"{host}:{port}"
        self._obs_peer = peer
        self._obs_lat = _RPC_LATENCY.labels(peer=peer)
        self._obs_err = _RPC_ERRORS.labels(peer=peer)
        self._obs_off = _RPC_OFFLINE.labels(peer=peer)
        self._obs_rec = _RPC_RECONNECTS.labels(peer=peer)

    # -- connection pool --

    def _new_conn(self, timeout: float | None = None
                  ) -> http.client.HTTPConnection:
        # Connection ESTABLISHMENT is a metadata-class round trip: bound
        # it by the adaptive deadline (converged ~1 s on a healthy
        # fabric), not the static bulk timeout — a blackholed peer must
        # trip failure detection fast.
        deadline = (timeout if timeout is not None
                    else self.dyn_timeout.timeout())
        if self.scheme == "https":
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=deadline,
                context=self._get_ssl())
        else:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=deadline)
        # http.client sends headers and small bodies as separate
        # segments; without TCP_NODELAY, Nagle holds the second one for
        # the peer's delayed ACK (~40 ms) on EVERY metadata round trip.
        # Eager connect keeps failure semantics: a dead node surfaces as
        # the per-drive DiskNotFound the quorum reducers expect, exactly
        # as it would have at request time.
        try:
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            if isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            self.mark_offline()
            raise se.DiskNotFound(
                f"{self.host}:{self.port}: {e}") from e
        return conn

    def _get_conn(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return self._new_conn()

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(conn)
                return
        conn.close()

    # -- online state machine --

    def is_online(self) -> bool:
        return self._online

    def mark_offline(self) -> None:
        with self._lock:
            if not self._online:
                return
            self._online = False
            self._obs_off.inc()
            if self._probing or self._closed:
                return
            self._probing = True
        t = threading.Thread(target=self._probe_loop, daemon=True,
                             name=f"rpc-health-{self.host}:{self.port}")
        t.start()

    def _probe_loop(self) -> None:
        """Reconnect probe: a short grace run at the base cadence (quick
        restarts — the common case — reconnect as fast as ever), then
        exponential backoff with jitter (capped) so a long-dead peer
        costs one cheap probe every ~HEALTH_BACKOFF_CAP seconds instead
        of one per second forever, with probes across many clients
        decorrelated instead of thundering in lockstep. close() stops a
        running probe via the event (no leaked daemon)."""
        import random

        delay = HEALTH_INTERVAL
        failures = 0
        while not self._probe_stop.wait(delay * random.uniform(0.6, 1.0)):
            try:
                conn = self._new_conn(timeout=2.0)
                conn.request("GET", "/health")
                ok = conn.getresponse().status == 200
                conn.close()
            except Exception:
                ok = False
            if ok:
                with self._lock:
                    self._online = True
                    self._probing = False
                self._obs_rec.inc()
                return
            failures += 1
            if failures >= HEALTH_GRACE_PROBES:
                delay = min(delay * 2.0, HEALTH_BACKOFF_CAP)
        with self._lock:
            self._probing = False

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for c in self._pool:
                try:
                    c.close()
                except Exception:
                    pass
            self._pool.clear()
        self._probe_stop.set()

    # -- calls --

    def _obs_done(self, path: str, dt: float, status: int = 0,
                  err: Exception | None = None) -> None:
        """Record one fabric round trip: latency for completed round
        trips, the error counter for network failures, and a typed `rpc`
        trace record when watched. Failures stay OUT of the latency
        histogram — connect refusals (near-zero) and timeouts (deadline-
        length) would bend the very distribution the family exists to
        prove; they have their own counter."""
        if err is None:
            self._obs_lat.observe(dt)
        else:
            self._obs_err.inc()
        if obs.has_subscribers():
            rec = {"type": "rpc", "time": time.time(),
                   "peer": self._obs_peer, "path": path,
                   "durationNs": int(dt * 1e9)}
            if status:
                rec["status"] = status
            if err is not None:
                rec["error"] = f"{type(err).__name__}: {err}"
            obs.publish(rec)

    def call(self, path: str, params: dict | None = None,
             body: bytes | Iterable[bytes] | None = None,
             stream: bool = False) -> bytes | _ResponseStream:
        """POST {path}?{params} with optional (possibly chunked) body.

        Returns the full response body, or a file-like if stream=True.
        Raises DiskNotFound when the node is offline / unreachable
        (the per-drive error the quorum reducers expect)."""
        if not self._online:
            raise se.DiskNotFound(f"{self.host}:{self.port} offline")
        qs = urllib.parse.urlencode(params or {})
        url = path + ("?" + qs if qs else "")
        headers = {"Authorization": "Bearer " + sign_token(self.secret)}
        # Distributed tracing: carry the originating request's trace id
        # across the fabric so the peer's storage/RPC records correlate
        # with ours (the reference forwards its amz request id on peer
        # REST the same way). One contextvar read — nil outside a traced
        # request.
        tid = obs.trace_id()
        if tid:
            headers["x-mtpu-trace-id"] = tid
        t_conn = time.monotonic()
        try:
            conn = self._get_conn()
        except se.StorageError as e:
            self._obs_done(path, time.monotonic() - t_conn, err=e)
            raise
        # The adaptive deadline governs METADATA-class calls only (no
        # body / small body). Bulk transfers (chunked shard uploads) keep
        # the static timeout — a deadline converged on 10 ms metadata
        # round-trips must not declare a healthy node dead because one
        # multi-MB send waited out a congested TCP window. Convergence
        # likewise learns only from the metadata class.
        adaptive = body is None or (
            isinstance(body, (bytes, bytearray)) and len(body) <= (1 << 20))
        deadline = self.dyn_timeout.timeout() if adaptive else self.timeout
        if conn.sock is not None:
            conn.sock.settimeout(deadline)
        else:
            conn.timeout = deadline
        t0 = time.monotonic()
        try:
            if body is None:
                conn.request("POST", url, headers=headers)
            elif isinstance(body, (bytes, bytearray)):
                conn.request("POST", url, body=bytes(body), headers=headers)
            else:
                headers["Transfer-Encoding"] = "chunked"
                conn.request("POST", url, body=iter(body), headers=headers,
                             encode_chunked=True)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            try:
                conn.close()
            except Exception:
                pass
            if adaptive and isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            self._obs_done(path, time.monotonic() - t0, err=e)
            self.mark_offline()
            raise se.DiskNotFound(
                f"{self.host}:{self.port}: {e}") from e
        if adaptive:
            self.dyn_timeout.log_success(time.monotonic() - t0)

        try:
            if resp.status == ERR_STATUS:
                doc = unpack(resp.read())
                self._put_conn(conn)
                # A typed storage error is a SUCCESSFUL fabric round trip
                # — latency counts, the error counter does not.
                self._obs_done(path, time.monotonic() - t0,
                               status=resp.status)
                raise se.by_name(doc.get("err", "StorageError"),
                                 doc.get("msg", ""))
            if resp.status != 200:
                msg = resp.read()[:512].decode(errors="replace")
                self._put_conn(conn)
                # Completed round trip (like the 599 path): real latency,
                # not a network failure — keep it out of the error counter.
                self._obs_done(path, time.monotonic() - t0,
                               status=resp.status)
                raise se.FaultyDisk(
                    f"{self.host}:{self.port}{path}: HTTP {resp.status} {msg}")
            if stream:
                # Long-lived body (walk streams, shard reads, trace subs):
                # restore the STATIC timeout — the adaptive deadline paces
                # request/first-byte only, and a converged ~1s deadline
                # must not kill a legitimately slow stream mid-read.
                if conn.sock is not None:
                    conn.sock.settimeout(self.timeout)
                # Stream latency = time to first byte; the body pays as
                # the caller drains.
                self._obs_done(path, time.monotonic() - t0, status=200)
                return _ResponseStream(resp, self, conn)
            data = resp.read()
        except (OSError, http.client.HTTPException) as e:
            # Body-read failure (incl. a timeout on a converged deadline):
            # same per-drive degradation as a connect failure — quorum
            # layers expect StorageError subtypes, never raw TimeoutError.
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
            if isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            self._obs_done(path, time.monotonic() - t0, err=e)
            self.mark_offline()
            raise se.DiskNotFound(
                f"{self.host}:{self.port}: {e}") from e
        self._put_conn(conn)
        self._obs_done(path, time.monotonic() - t0, status=200)
        return data

    def call_msgpack(self, path: str, params: dict | None = None,
                     body: bytes | Iterable[bytes] | None = None):
        raw = self.call(path, params, body)
        return unpack(raw) if raw else None

    def iter_msgpack(self, path: str, params: dict | None = None) -> Iterator:
        """Stream a sequence of msgpack documents (walk_dir entries)."""
        st = self.call(path, params, stream=True)
        assert isinstance(st, _ResponseStream)
        try:
            unpacker = msgpack.Unpacker(strict_map_key=False)
            while True:
                chunk = st.read1(1 << 16)
                if not chunk:
                    break
                unpacker.feed(chunk)
                yield from unpacker
        finally:
            st.close()
