"""Generic inter-node REST client — the shared transport for all RPC planes.

Role-equivalent of cmd/rest/client.go: POST with URL-encoded args, streaming
request/response bodies, msgpack payloads, and a health-check-driven
online/offline state machine with background reconnect (rest.Client:75,
Call:120, MarkOffline:208).

Peer resilience (the peer-plane mirror of storage/healthcheck.py): every
client runs a per-peer circuit breaker —

    CLOSED --hard connect failure / N consecutive soft failures--> OPEN
    OPEN   --health probe success--> HALF_OPEN (one trial call)
    HALF_OPEN --trial success--> CLOSED   --trial failure--> OPEN

OPEN fails every call instantly with the per-drive DiskNotFound the
quorum reducers expect, with ZERO socket work (the drive plane's OFFLINE
state, applied to a peer). Idempotent metadata-class routes get bounded
retries with jittered exponential backoff drawn from a per-peer token
bucket, so a cluster of retrying clients cannot amplify an outage into a
retry storm; when the bucket is dry the call is shed instead of retried.

Auth: every call carries an HMAC token derived from the cluster secret
(the reference signs inter-node requests with a JWT from the root
credentials, cmd/jwt/). Tokens are cheap to mint per call and expire.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import os
import random
import socket
import threading
import time
import urllib.parse
import weakref
from typing import BinaryIO, Iterable, Iterator

import msgpack

from minio_tpu import obs
from minio_tpu.dist import faultplane as _faults
from minio_tpu.utils import errors as se

DEFAULT_TIMEOUT = 30.0
HEALTH_INTERVAL = 1.0        # reconnect probe cadence during the grace runs
HEALTH_GRACE_PROBES = 3      # probes at base cadence before backing off
HEALTH_BACKOFF_CAP = 10.0    # max delay between reconnect probes
ERR_STATUS = 599  # carries a typed storage error in the body

# Circuit-breaker states (also the gauge encoding, mirroring the drive
# plane's 0=online/1=faulty/2=offline convention).
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2
_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_HALF_OPEN: "half-open",
                BREAKER_OPEN: "open"}

# Soft (mid-call) transport failures tolerated before the breaker opens;
# hard failures (connect refused/timeout — the partition signature) open
# it immediately, exactly as mark_offline always has.
BREAKER_FAILURES = int(os.environ.get("MTPU_PEER_BREAKER_FAILURES", "3"))
# Retry policy for idempotent metadata-class routes.
RETRY_MAX = int(os.environ.get("MTPU_PEER_RETRIES", "2"))
RETRY_BUDGET = float(os.environ.get("MTPU_PEER_RETRY_BUDGET", "8"))
RETRY_REFILL = float(os.environ.get("MTPU_PEER_RETRY_REFILL", "1.0"))

# Routes safe to replay: reads and pure checks. Mutating routes and the
# whole lock plane (dsync owns its own retry loop) NEVER retry — a
# replayed rename_data or lock() could double-apply.
IDEMPOTENT_ROUTES = frozenset({
    # storage plane reads / checks
    "disk_info", "get_disk_id", "read_format", "list_vols", "stat_vol",
    "read_all", "list_dir", "stat_file", "read_version", "read_xl",
    "read_file_stream", "walk_dir", "verify_file", "check_parts",
    # peer / bootstrap control reads
    "health", "server_info", "obd_info", "metrics", "verify",
})

# Fabric observability: the r5 TCP_NODELAY fix and the adaptive connect
# deadline are only provable with a live latency distribution + failure
# counters per peer (reference minio_inter_node_* metric families).
_RPC_LATENCY = obs.histogram(
    "minio_tpu_rpc_latency_seconds",
    "Inter-node RPC call latency by peer", ("peer",))
_RPC_ERRORS = obs.counter(
    "minio_tpu_rpc_errors_total",
    "RPC calls failed on network/timeout by peer", ("peer",))
_RPC_OFFLINE = obs.counter(
    "minio_tpu_rpc_offline_total",
    "Transitions of a peer to offline", ("peer",))
_RPC_RECONNECTS = obs.counter(
    "minio_tpu_rpc_reconnects_total",
    "Successful reconnects after a peer went offline", ("peer",))
# Breaker families carry a `lane` label: the fabric client and the
# dedicated metrics-pull client run INDEPENDENT breakers to the same
# peer (by design — an observability stall must not mark the data plane
# offline), so sharing one gauge child would let whichever client wrote
# last mask the other's OPEN state.
_BREAKER_STATE = obs.gauge(
    "minio_tpu_peer_breaker_state",
    "Per-peer circuit breaker: 0=closed, 1=half-open, 2=open",
    ("peer", "lane"))
_BREAKER_TRANSITIONS = obs.counter(
    "minio_tpu_peer_breaker_transitions_total",
    "Circuit breaker state entries by peer, lane, and state",
    ("peer", "lane", "state"))
_RPC_RETRIES = obs.counter(
    "minio_tpu_rpc_retries_total",
    "Idempotent RPC retries attempted by peer", ("peer",))
_RPC_SHED = obs.counter(
    "minio_tpu_rpc_retry_shed_total",
    "Retries shed because the per-peer retry budget was exhausted",
    ("peer",))

# Every live RestClient, weakly — the composed chaos plane's teardown
# (minio_tpu/chaos.clear_all) force-closes breakers a storm opened so
# an aborted chaos test cannot bleed OPEN peers into the next test.
_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()
_CLIENTS_MU = threading.Lock()


def _clients() -> list:
    with _CLIENTS_MU:
        return list(_CLIENTS)


def reset_breakers() -> int:
    """Force every OPEN/HALF_OPEN breaker in the process back to CLOSED
    (chaos teardown hygiene). Returns how many breakers were reset."""
    return sum(1 for c in _clients() if c.reset_breaker())


# --- auth tokens -------------------------------------------------------------

def sign_token(secret: str, ttl: float = 900.0, now: float | None = None) -> str:
    """Mint an expiring HMAC bearer token binding the cluster secret."""
    payload = json.dumps({"exp": (now or time.time()) + ttl}).encode()
    mac = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    return (base64.urlsafe_b64encode(payload).decode().rstrip("=")
            + "." + base64.urlsafe_b64encode(mac).decode().rstrip("="))


def verify_token(secret: str, token: str, now: float | None = None) -> bool:
    try:
        p64, m64 = token.split(".")
        pad = lambda s: s + "=" * (-len(s) % 4)  # noqa: E731
        payload = base64.urlsafe_b64decode(pad(p64))
        mac = base64.urlsafe_b64decode(pad(m64))
        want = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
        if not hmac.compare_digest(mac, want):
            return False
        return json.loads(payload)["exp"] >= (now or time.time())
    except Exception:
        return False


# --- wire helpers ------------------------------------------------------------

def pack(obj) -> bytes:
    return msgpack.packb(obj)


def unpack(raw: bytes):
    return msgpack.unpackb(raw, strict_map_key=False)


# ("plane", "method") from /rpc/{plane}/v1/{method} — ONE parser shared
# with fault matching, so retry-idempotence classification can never
# desynchronize from it.
_route_of = _faults.FaultPlane._route_of


class _RetryBudget:
    """Token bucket bounding retries per peer: capacity tokens, refilled
    at `refill`/s. One retry = one token; an empty bucket sheds instead
    of retrying (the SRE retry-budget discipline — retries must never
    multiply offered load during an outage)."""

    __slots__ = ("capacity", "tokens", "refill", "last", "_mu")

    def __init__(self, capacity: float, refill: float):
        self.capacity = float(capacity)
        self.tokens = float(capacity)
        self.refill = float(refill)
        self.last = time.monotonic()
        self._mu = threading.Lock()

    def take(self) -> bool:
        with self._mu:
            now = time.monotonic()
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.last) * self.refill)
            self.last = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False


class _ResponseStream:
    """File-like over an HTTP response that returns its connection to the
    pool on close (exactly-once) — and NEVER after a stream error: a
    connection whose body read failed mid-flight is out of protocol sync,
    and pooling it would surface the breakage as a confusing failure on
    the next unrelated call."""

    def __init__(self, resp: http.client.HTTPResponse, client: "RestClient",
                 conn: http.client.HTTPConnection, fault=None):
        self._resp = resp
        self._client = client
        self._conn = conn
        self._closed = False
        self._fault = fault          # claimed truncate/corrupt FaultRule
        self._fault_seen = 0

    def _fail(self, e: Exception) -> "se.StorageError":
        """Mid-stream network failure: degrade like any per-drive error
        (quorum layers expect StorageError subtypes, not raw socket
        exceptions) and stop pooling the broken connection."""
        self._closed = True
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001
            pass
        self._client._note_failure()
        return self._client._transport_error(e)

    def _check_fault(self, data: bytes) -> bytes:
        rule = self._fault
        if rule is None:
            return data
        if rule.action == _faults.TRUNCATE:
            # Cut at EXACTLY after_bytes: deliver only the valid prefix
            # of the violating chunk, then reset on the next read — the
            # consumer really receives a stream cut mid-flight, not a
            # whole extra chunk.
            remaining = rule.after_bytes - self._fault_seen
            if remaining <= 0:
                raise self._fail(ConnectionResetError(
                    f"faultplane: stream truncated after "
                    f"{rule.after_bytes} bytes"))
            if len(data) > remaining:
                self._fault_seen = rule.after_bytes
                return data[:remaining]
            self._fault_seen += len(data)
            return data
        if data:  # corrupt: flip the first byte of every chunk
            return bytes([data[0] ^ rule.xor]) + data[1:]
        return data

    def read(self, n: int = -1) -> bytes:
        try:
            data = (self._resp.read() if n is None or n < 0
                    else self._resp.read(n))
        except (OSError, http.client.HTTPException) as e:
            raise self._fail(e) from e
        return self._check_fault(data)

    def read1(self, n: int = 65536) -> bytes:
        """Return whatever is available (at most n) without waiting for n
        bytes — read(n) on a chunked response blocks until it accumulates n,
        which would stall live streams (trace/console subscriptions) whose
        documents trickle in."""
        try:
            data = self._resp.read1(n)
        except (OSError, http.client.HTTPException) as e:
            raise self._fail(e) from e
        return self._check_fault(data)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Drain so the connection is reusable — but bounded in both bytes
        # (1 MiB) and time (250 ms): an endless subscription stream
        # (trace/console heartbeats) would otherwise block this close
        # forever. Undrainable connections are dropped, not pooled.
        try:
            if self._resp.isclosed():
                self._client._put_conn(self._conn)
                return
            sock = self._conn.sock
            prev_timeout = sock.gettimeout() if sock is not None else None
            if sock is not None:
                sock.settimeout(0.25)
            leftover = self._resp.read(1 << 20)
            if leftover and len(leftover) == (1 << 20):
                self._conn.close()
                return
            if sock is not None:
                sock.settimeout(prev_timeout)  # the client's configured timeout
            self._client._put_conn(self._conn)
        except Exception:
            try:
                self._conn.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RestClient:
    """One per (node, plane-root). `call()` raises typed storage errors
    re-hydrated from the wire; network failures feed the per-peer circuit
    breaker and a daemon probe brings an OPEN peer back through HALF_OPEN
    (cmd/rest/client.go:135-168)."""

    def __init__(self, host: str, port: int, secret: str,
                 timeout: float = DEFAULT_TIMEOUT, scheme: str = "http",
                 ssl_context=None, breaker_failures: int | None = None,
                 retries: int | None = None,
                 retry_budget: float | None = None,
                 retry_refill: float | None = None, name: str = "",
                 lane: str = "fabric"):
        """name: the peer's ADVERTISED identity (S3 host:port in a
        cluster) — the `peer` label on every fabric metric and the
        fault-injection destination; defaults to the transport address.
        lane: distinguishes independent breakers to the same peer on the
        breaker metric families (the metrics-pull client passes
        "metrics" so its breaker cannot mask the fabric one).

        scheme "https" runs the fabric over TLS. ssl_context should pin
        the cluster CA (ClusterNode pins certs_dir/public.crt) — either a
        plain SSLContext or an object with .current() (ClientCAManager),
        consulted per connection so CA rotation hot-reloads. The default
        is a verifying system-CA context. An unverified context would let
        an active MITM replay the bearer token, so never default to
        CERT_NONE here."""
        from minio_tpu.utils.dyntimeout import DynamicTimeout

        self.host = host
        self.port = port
        self.secret = secret
        self.timeout = timeout
        # Self-tuning per-call deadline (reference dynamicTimeout,
        # cmd/dynamic-timeouts.go:35): a congested fabric inflates it,
        # a healthy one converges it down for faster failure detection.
        self.dyn_timeout = DynamicTimeout(timeout, minimum=min(1.0, timeout))
        self.scheme = scheme
        if scheme == "https" and ssl_context is None:
            import ssl as _ssl

            ssl_context = _ssl.create_default_context()
        self._get_ssl = (ssl_context.current
                         if hasattr(ssl_context, "current")
                         else lambda: ssl_context)
        self._lock = threading.Lock()
        self._pool: list[http.client.HTTPConnection] = []
        self._probing = False
        self._closed = False
        self._probe_stop = threading.Event()
        peer = name or f"{host}:{port}"
        # Fault-injection identity: src is OUR node ("" for standalone
        # clients, overridden by the cluster with its advertised name),
        # dst the peer's advertised identity — partitions are declared
        # in topology terms, not transport ports.
        self.fault_src = ""
        self.fault_dst = peer
        # -- circuit breaker + retry budget --
        self._state = BREAKER_CLOSED
        self._consec = 0
        self._half_open_busy = False
        self._opens = 0
        self._retries = 0
        self._shed = 0
        self._breaker_failures = (BREAKER_FAILURES if breaker_failures is None
                                  else int(breaker_failures))
        self._retry_max = RETRY_MAX if retries is None else int(retries)
        self._retry_budget = _RetryBudget(
            RETRY_BUDGET if retry_budget is None else retry_budget,
            RETRY_REFILL if retry_refill is None else retry_refill)
        self._retry_rng = random.Random()
        self._obs_peer = peer
        self._obs_lane = lane
        self._obs_lat = _RPC_LATENCY.labels(peer=peer)
        self._obs_err = _RPC_ERRORS.labels(peer=peer)
        self._obs_off = _RPC_OFFLINE.labels(peer=peer)
        self._obs_rec = _RPC_RECONNECTS.labels(peer=peer)
        self._obs_breaker = _BREAKER_STATE.labels(peer=peer, lane=lane)
        self._obs_retry = _RPC_RETRIES.labels(peer=peer)
        self._obs_shed = _RPC_SHED.labels(peer=peer)
        self._obs_breaker.set(BREAKER_CLOSED)
        with _CLIENTS_MU:
            _CLIENTS.add(self)

    def _transport_error(self, e: Exception) -> se.StorageError:
        """Typed per-drive error for a NETWORK failure, tagged so the
        retry loop can tell it from a DiskNotFound the peer sent over the
        wire (which must never be retried — the peer answered)."""
        err = se.DiskNotFound(f"{self.host}:{self.port}: {e}")
        err.transport = True
        return err

    # -- connection pool --

    def _new_conn(self, timeout: float | None = None, path: str = ""
                  ) -> http.client.HTTPConnection:
        # Connection ESTABLISHMENT is a metadata-class round trip: bound
        # it by the adaptive deadline (converged ~1 s on a healthy
        # fabric), not the static bulk timeout — a blackholed peer must
        # trip failure detection fast.
        deadline = (timeout if timeout is not None
                    else self.dyn_timeout.timeout())
        try:
            fp = _faults.get()
            if fp is not None:
                # Partition / refusal faults fire BEFORE any socket
                # exists — an OPEN breaker on a partitioned peer really
                # does zero socket work.
                fp.on_connect(self.fault_src, self.fault_dst, path)
            if self.scheme == "https":
                conn = http.client.HTTPSConnection(
                    self.host, self.port, timeout=deadline,
                    context=self._get_ssl())
            else:
                conn = http.client.HTTPConnection(self.host, self.port,
                                                  timeout=deadline)
            # http.client sends headers and small bodies as separate
            # segments; without TCP_NODELAY, Nagle holds the second one for
            # the peer's delayed ACK (~40 ms) on EVERY metadata round trip.
            # Eager connect keeps failure semantics: a dead node surfaces as
            # the per-drive DiskNotFound the quorum reducers expect, exactly
            # as it would have at request time.
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            if isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            # Connect-phase failure is the partition signature: the
            # breaker opens immediately (hard), as mark_offline always
            # did here.
            self.mark_offline()
            raise self._transport_error(e) from e
        return conn

    def _get_conn(self, path: str = "") -> http.client.HTTPConnection:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return self._new_conn(path=path)

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            # A client closed while this call was in flight must not have
            # its socket resurrected into the pool (it would leak).
            if not self._closed and len(self._pool) < 8:
                self._pool.append(conn)
                return
        conn.close()

    # -- circuit breaker --

    def is_online(self) -> bool:
        return self._state != BREAKER_OPEN

    def breaker_state(self) -> int:
        return self._state

    def breaker_info(self) -> dict:
        """Admin server-info surface: one peer's fabric health."""
        return {"peer": self.fault_dst,
                "transport": f"{self.host}:{self.port}",
                "state": _STATE_NAMES[self._state],
                "consecutiveFailures": self._consec,
                "opens": self._opens,
                "retries": self._retries,
                "retriesShed": self._shed}

    def _enter_state(self, state: int) -> None:
        self._obs_breaker.set(state)
        _BREAKER_TRANSITIONS.labels(peer=self._obs_peer,
                                    lane=self._obs_lane,
                                    state=_STATE_NAMES[state]).inc()

    def _note_failure(self, hard: bool = False) -> None:
        """Account one transport failure. Soft (mid-call) failures open
        the breaker after `breaker_failures` consecutive strikes; hard
        ones (connect refusal, a failed HALF_OPEN trial) open it now."""
        with self._lock:
            self._consec += 1
            tripped = (hard or self._state == BREAKER_HALF_OPEN
                       or self._consec >= self._breaker_failures)
        if tripped:
            self.mark_offline()

    def _note_success(self) -> None:
        closed = False
        with self._lock:
            self._consec = 0
            if self._state == BREAKER_HALF_OPEN:
                self._state = BREAKER_CLOSED
                self._half_open_busy = False
                closed = True
        if closed:
            self._enter_state(BREAKER_CLOSED)

    def reset_breaker(self) -> bool:
        """Force the breaker back to CLOSED — chaos-plane teardown only
        (production breakers heal through the probe/HALF_OPEN cycle).
        The probe loop observes the state flip and retires itself; a
        closed client is left alone. Returns True when a non-CLOSED
        breaker was actually reset."""
        with self._lock:
            if self._closed or self._state == BREAKER_CLOSED:
                return False
            self._state = BREAKER_CLOSED
            self._half_open_busy = False
            self._consec = 0
        self._enter_state(BREAKER_CLOSED)
        return True

    def mark_offline(self) -> None:
        start_probe = False
        with self._lock:
            if self._state == BREAKER_OPEN:
                return
            self._state = BREAKER_OPEN
            self._half_open_busy = False
            self._consec = 0
            self._opens += 1
            self._obs_off.inc()
            if not self._probing and not self._closed:
                self._probing = True
                start_probe = True
        self._enter_state(BREAKER_OPEN)
        if start_probe:
            t = threading.Thread(target=self._probe_loop, daemon=True,
                                 name=f"rpc-health-{self.host}:{self.port}")
            t.start()

    def _probe_loop(self) -> None:
        """Reconnect probe: a short grace run at the base cadence (quick
        restarts — the common case — reconnect as fast as ever), then
        exponential backoff with jitter (capped) so a long-dead peer
        costs one cheap probe every ~HEALTH_BACKOFF_CAP seconds instead
        of one per second forever, with probes across many clients
        decorrelated instead of thundering in lockstep. A probe success
        enters HALF_OPEN — the next real call is the single trial that
        decides CLOSED vs back to OPEN. close() stops a running probe
        via the event (no leaked daemon)."""
        delay = HEALTH_INTERVAL
        failures = 0
        while not self._probe_stop.wait(delay * random.uniform(0.6, 1.0)):
            with self._lock:
                # A breaker forced CLOSED out-of-band (reset_breakers,
                # chaos teardown) retires the probe: it must not race a
                # reset by re-entering HALF_OPEN on its next success.
                if self._state != BREAKER_OPEN:
                    self._probing = False
                    return
            try:
                conn = self._new_conn(timeout=2.0, path="/health")
                conn.request("GET", "/health")
                ok = conn.getresponse().status == 200
                conn.close()
            except Exception:
                ok = False
            if ok:
                with self._lock:
                    # Recheck under the lock: a reset_breaker() landing
                    # while this probe's round trip was in flight has
                    # already closed the breaker — the success must not
                    # overwrite CLOSED with HALF_OPEN.
                    if self._state != BREAKER_OPEN:
                        self._probing = False
                        return
                    self._state = BREAKER_HALF_OPEN
                    self._half_open_busy = False
                    self._probing = False
                self._obs_rec.inc()
                self._enter_state(BREAKER_HALF_OPEN)
                return
            failures += 1
            if failures >= HEALTH_GRACE_PROBES:
                delay = min(delay * 2.0, HEALTH_BACKOFF_CAP)
        with self._lock:
            self._probing = False

    def close(self) -> None:
        """Idempotent; safe against in-flight calls — their pooled
        connections are closed on return (_put_conn checks _closed) and
        the probe thread can neither survive nor respawn."""
        with self._lock:
            self._closed = True
            for c in self._pool:
                try:
                    c.close()
                except Exception:
                    pass
            self._pool.clear()
        self._probe_stop.set()

    # -- calls --

    def _obs_done(self, path: str, dt: float, status: int = 0,
                  err: Exception | None = None) -> None:
        """Record one fabric round trip: latency for completed round
        trips, the error counter for network failures, and a typed `rpc`
        trace record when watched. Failures stay OUT of the latency
        histogram — connect refusals (near-zero) and timeouts (deadline-
        length) would bend the very distribution the family exists to
        prove; they have their own counter."""
        if err is None:
            self._obs_lat.observe(dt)
        else:
            self._obs_err.inc()
        if obs.has_subscribers():
            rec = {"type": "rpc", "time": time.time(),
                   "peer": self._obs_peer, "path": path,
                   "durationNs": int(dt * 1e9)}
            if status:
                rec["status"] = status
            if err is not None:
                rec["error"] = f"{type(err).__name__}: {err}"
            obs.publish(rec)

    def call(self, path: str, params: dict | None = None,
             body: bytes | Iterable[bytes] | None = None,
             stream: bool = False) -> bytes | _ResponseStream:
        """POST {path}?{params} with optional (possibly chunked) body.

        Returns the full response body, or a file-like if stream=True.
        Raises DiskNotFound when the node is offline / unreachable
        (the per-drive error the quorum reducers expect).

        Idempotent metadata-class routes retry transport failures with
        jittered exponential backoff, bounded by `retries` and the
        per-peer retry budget; everything else is single-shot."""
        plane, route = _route_of(path)
        retryable = (route in IDEMPOTENT_ROUTES and plane != "lock"
                     and (body is None
                          or isinstance(body, (bytes, bytearray))))
        attempt = 0
        while True:
            try:
                return self._call_once(path, params, body, stream)
            except se.StorageError as e:
                if (not retryable or attempt >= self._retry_max
                        or not getattr(e, "transport", False)
                        or not self.is_online()):
                    raise
                if not self._retry_budget.take():
                    self._shed += 1
                    self._obs_shed.inc()
                    raise
                attempt += 1
                self._retries += 1
                self._obs_retry.inc()
                # Decorrelated exponential backoff, capped at 1 s.
                time.sleep(min(1.0, 0.05 * (1 << (attempt - 1)))
                           * self._retry_rng.uniform(0.5, 1.0))

    def _call_once(self, path: str, params: dict | None,
                   body, stream: bool) -> bytes | _ResponseStream:
        state = self._state
        if state == BREAKER_OPEN:
            # Fail-fast: zero socket work, exactly like a drive OFFLINE.
            raise se.DiskNotFound(
                f"{self.host}:{self.port} offline (breaker open)")
        trial = False
        if state == BREAKER_HALF_OPEN:
            with self._lock:
                if self._state == BREAKER_HALF_OPEN:
                    if self._half_open_busy:
                        raise se.DiskNotFound(
                            f"{self.host}:{self.port} half-open: trial "
                            f"call in flight")
                    self._half_open_busy = True
                    trial = True
        try:
            return self._do_call(path, params, body, stream, trial)
        finally:
            if trial:
                with self._lock:
                    self._half_open_busy = False

    def _do_call(self, path: str, params: dict | None, body, stream: bool,
                 trial: bool) -> bytes | _ResponseStream:
        qs = urllib.parse.urlencode(params or {})
        url = path + ("?" + qs if qs else "")
        headers = {"Authorization": "Bearer " + sign_token(self.secret)}
        # Distributed tracing: carry the originating request's trace id
        # across the fabric so the peer's storage/RPC records correlate
        # with ours (the reference forwards its amz request id on peer
        # REST the same way). One contextvar read — nil outside a traced
        # request.
        tid = obs.trace_id()
        if tid:
            headers["x-mtpu-trace-id"] = tid
        fp = _faults.get()
        t_conn = time.monotonic()
        try:
            conn = self._get_conn(path)
        except se.StorageError as e:
            self._obs_done(path, time.monotonic() - t_conn, err=e)
            raise
        # The adaptive deadline governs METADATA-class calls only (no
        # body / small body). Bulk transfers (chunked shard uploads) keep
        # the static timeout — a deadline converged on 10 ms metadata
        # round-trips must not declare a healthy node dead because one
        # multi-MB send waited out a congested TCP window. Convergence
        # likewise learns only from the metadata class.
        adaptive = body is None or (
            isinstance(body, (bytes, bytearray)) and len(body) <= (1 << 20))
        deadline = self.dyn_timeout.timeout() if adaptive else self.timeout
        if conn.sock is not None:
            conn.sock.settimeout(deadline)
        else:
            conn.timeout = deadline
        t0 = time.monotonic()
        try:
            if fp is not None:
                # Delay/reset faults degrade through this except block,
                # exactly like their real-network counterparts.
                fp.on_request(self.fault_src, self.fault_dst, path)
            if body is None:
                conn.request("POST", url, headers=headers)
            elif isinstance(body, (bytes, bytearray)):
                conn.request("POST", url, body=bytes(body), headers=headers)
            else:
                headers["Transfer-Encoding"] = "chunked"
                conn.request("POST", url, body=iter(body), headers=headers,
                             encode_chunked=True)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            try:
                conn.close()
            except Exception:
                pass
            if adaptive and isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            self._obs_done(path, time.monotonic() - t0, err=e)
            self._note_failure(hard=trial)
            raise self._transport_error(e) from e
        if adaptive:
            self.dyn_timeout.log_success(time.monotonic() - t0)
        fspec = (fp.response_fault(self.fault_src, self.fault_dst, path)
                 if fp is not None else None)

        try:
            if resp.status == ERR_STATUS:
                raw = resp.read()
                if fspec is not None:
                    raw = self._apply_body_fault(fspec, raw)
                self._put_conn(conn)
                # A typed storage error is a SUCCESSFUL fabric round trip
                # — latency counts, the error counter does not.
                self._obs_done(path, time.monotonic() - t0,
                               status=resp.status)
                self._note_success()
                try:
                    doc = unpack(raw)
                except Exception as e:  # noqa: BLE001 - corrupt payload
                    # The round trip completed (body fully read, conn
                    # already safely pooled) but the error document is
                    # garbage: surface typed, never a raw msgpack error.
                    raise se.FaultyDisk(
                        f"{self.host}:{self.port}{path}: corrupt error "
                        f"payload: {e}") from e
                raise se.by_name(doc.get("err", "StorageError"),
                                 doc.get("msg", ""))
            if resp.status != 200:
                msg = resp.read()[:512].decode(errors="replace")
                self._put_conn(conn)
                # Completed round trip (like the 599 path): real latency,
                # not a network failure — keep it out of the error counter.
                self._obs_done(path, time.monotonic() - t0,
                               status=resp.status)
                self._note_success()
                raise se.FaultyDisk(
                    f"{self.host}:{self.port}{path}: HTTP {resp.status} {msg}")
            if stream:
                # Long-lived body (walk streams, shard reads, trace subs):
                # restore the STATIC timeout — the adaptive deadline paces
                # request/first-byte only, and a converged ~1s deadline
                # must not kill a legitimately slow stream mid-read.
                if conn.sock is not None:
                    conn.sock.settimeout(self.timeout)
                # Stream latency = time to first byte; the body pays as
                # the caller drains.
                self._obs_done(path, time.monotonic() - t0, status=200)
                self._note_success()
                return _ResponseStream(resp, self, conn, fault=fspec)
            data = resp.read()
            if fspec is not None:
                data = self._apply_body_fault(fspec, data)
        except (OSError, http.client.HTTPException) as e:
            # Body-read failure (incl. a timeout on a converged deadline):
            # same per-drive degradation as a connect failure — quorum
            # layers expect StorageError subtypes, never raw TimeoutError.
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
            if isinstance(e, TimeoutError):
                self.dyn_timeout.log_failure()
            self._obs_done(path, time.monotonic() - t0, err=e)
            self._note_failure(hard=trial)
            raise self._transport_error(e) from e
        self._put_conn(conn)
        self._obs_done(path, time.monotonic() - t0, status=200)
        self._note_success()
        return data

    @staticmethod
    def _apply_body_fault(rule, data: bytes) -> bytes:
        """Injected response faults on a buffered body: truncation is a
        transport failure (raises into the body-read except path, so the
        connection is dropped, never pooled); corruption is a payload
        fault on an intact transport (the conn stays reusable)."""
        if rule.action == _faults.TRUNCATE:
            raise ConnectionResetError(
                f"faultplane: body truncated after {rule.after_bytes} bytes")
        if data:
            return bytes([data[0] ^ rule.xor]) + data[1:]
        return data

    def call_msgpack(self, path: str, params: dict | None = None,
                     body: bytes | Iterable[bytes] | None = None):
        raw = self.call(path, params, body)
        return unpack(raw) if raw else None

    def iter_msgpack(self, path: str, params: dict | None = None) -> Iterator:
        """Stream a sequence of msgpack documents (walk_dir entries)."""
        st = self.call(path, params, stream=True)
        assert isinstance(st, _ResponseStream)
        try:
            unpacker = msgpack.Unpacker(strict_map_key=False)
            while True:
                chunk = st.read1(1 << 16)
                if not chunk:
                    break
                unpacker.feed(chunk)
                yield from unpacker
        finally:
            st.close()
