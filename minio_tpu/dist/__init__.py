"""Distributed plane: node-to-node RPC fabric.

Four planes share one generic HTTP client/server pair, exactly the
reference's layering (SURVEY §5.8; cmd/rest/client.go):

  storage  - per-drive StorageAPI served remotely (cmd/storage-rest-*.go)
  lock     - dsync NetLocker quorum locks       (cmd/lock-rest-*.go)
  peer     - control plane fan-out              (cmd/peer-rest-*.go)
  bootstrap- startup topology verification      (cmd/bootstrap-peer-server.go)

The TPU split (SURVEY §5.8): control planes are host RPC; the *data* plane
keeps the StorageAPI seam so "remote drive" is transparent to the erasure
engine — shard bytes stream over DCN into host buffers that feed the same
batched device kernels as local drives.
"""

from minio_tpu.dist.rpc import RestClient, sign_token, verify_token
from minio_tpu.dist.server import NodeServer

__all__ = ["RestClient", "NodeServer", "sign_token", "verify_token"]
