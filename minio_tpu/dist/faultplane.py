"""Fault-injection fabric for the inter-node RPC transport.

Role-equivalent of the reference's network-fault shell harnesses
(buildscripts/verify-healing.sh kills processes; the Go race tests use
custom net.Conn wrappers) folded into a deterministic, rule-driven plane
the RestClient consults at three points of every fabric call:

  connect  — before a socket is created (refusal = partition)
  request  — before the request is written (delay / mid-call reset)
  response — while the body is read (truncation / corruption)

Rules are matched by (src node, dst peer, route) and fire a bounded
number of times; named partitions (symmetric or asymmetric, healable at
runtime) compile down to connection-refusal checks. All randomness
(delay jitter) comes from per-rule `random.Random` children seeded from
the plane seed, so the same seed always yields the same fault schedule —
chaos tests replay bit-identically (`schedule()` previews the draws
without consuming them).

The plane is process-global but *addressed*: in-process multi-node tests
give every node's clients a `fault_src` identity, so an asymmetric
partition (A→B dead, B→A alive) works with both nodes in one process.
Install from tests via `install()`, or over HTTP through the guarded
admin endpoint (`MTPU_FAULT_INJECTION=1` + `admin:*`); when nothing is
installed the RestClient pays one module-attribute read per call.
"""

from __future__ import annotations

import random
import threading

# Rule actions.
REFUSE = "refuse"        # connect raises ConnectionRefusedError (zero sockets)
DELAY = "delay"          # sleep delay+jitter before the request is written
RESET = "reset"          # ConnectionResetError as the request is written
TRUNCATE = "truncate"    # response body cut after `after_bytes`, then reset
CORRUPT = "corrupt"      # response bytes XOR-flipped (payload, not transport)

_ACTIONS = (REFUSE, DELAY, RESET, TRUNCATE, CORRUPT)


class FaultRule:
    """One programmable fault. Match fields are exact (or None = any):
    `src` / `peer` are node identities ("host:port", the ADVERTISED S3
    address in a cluster), `route` is the RPC method name (the last path
    segment, e.g. "read_version"), `plane` the path's plane segment.
    `times` bounds how often the rule fires (None = forever)."""

    __slots__ = ("action", "src", "peer", "route", "plane", "delay",
                 "jitter", "after_bytes", "xor", "times", "fired", "_rng")

    def __init__(self, action: str, *, src: str | None = None,
                 peer: str | None = None, route: str | None = None,
                 plane: str | None = None, delay: float = 0.0,
                 jitter: float = 0.0, after_bytes: int = 0,
                 xor: int = 0xFF, times: int | None = None, seed: int = 0):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        self.action = action
        self.src = src
        self.peer = peer
        self.route = route
        self.plane = plane
        self.delay = float(delay)
        self.jitter = float(jitter)
        self.after_bytes = int(after_bytes)
        self.xor = int(xor) & 0xFF
        self.times = times
        self.fired = 0
        self._rng = random.Random(seed)

    def matches(self, src: str, peer: str, route: str, plane: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return ((self.src is None or self.src == src)
                and (self.peer is None or self.peer == peer)
                and (self.route is None or self.route == route)
                and (self.plane is None or self.plane == plane))

    def draw_delay(self) -> float:
        if self.jitter <= 0:
            return self.delay
        return self.delay + self._rng.uniform(0.0, self.jitter)

    def describe(self) -> dict:
        return {"action": self.action, "src": self.src, "peer": self.peer,
                "route": self.route, "plane": self.plane,
                "delay": self.delay, "jitter": self.jitter,
                "afterBytes": self.after_bytes, "times": self.times,
                "fired": self.fired}


class FaultPlane:
    """Rule set + named partitions, consulted by every RestClient."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._mu = threading.Lock()
        self._rules: list[FaultRule] = []
        # name -> list of (src, dst) one-way refusal edges.
        self._partitions: dict[str, list[tuple[str, str]]] = {}

    # -- programming ---------------------------------------------------

    def add_rule(self, action: str, **kw) -> FaultRule:
        """Child seeds derive from (plane seed, rule index): the same
        programming order under the same seed replays the same jitter."""
        with self._mu:
            rule = FaultRule(action, seed=hash((self.seed, len(self._rules)))
                             & 0x7FFFFFFF, **kw)
            self._rules.append(rule)
            return rule

    def partition(self, name: str, *groups) -> None:
        """Symmetric named partition: every cross-group (src, dst) pair
        refuses connections, both directions."""
        edges = []
        gs = [list(g) for g in groups]
        for i, ga in enumerate(gs):
            for gb in gs[i + 1:]:
                for a in ga:
                    for b in gb:
                        edges.append((a, b))
                        edges.append((b, a))
        with self._mu:
            self._partitions[name] = edges

    def isolate(self, name: str, src: str, dst: str) -> None:
        """Asymmetric edge: src can no longer reach dst (dst→src stays
        alive — the half-partition a broken switch port produces)."""
        with self._mu:
            self._partitions.setdefault(name, []).append((src, dst))

    def heal(self, name: str) -> bool:
        with self._mu:
            return self._partitions.pop(name, None) is not None

    def clear(self) -> None:
        with self._mu:
            self._rules.clear()
            self._partitions.clear()

    def describe(self) -> dict:
        with self._mu:
            return {"seed": self.seed,
                    "rules": [r.describe() for r in self._rules],
                    "partitions": {n: [list(e) for e in edges]
                                   for n, edges in self._partitions.items()}}

    # -- matching ------------------------------------------------------

    @staticmethod
    def _route_of(path: str) -> tuple[str, str]:
        """("plane", "method") from /rpc/{plane}/v1/{method}; bare paths
        (the probe's /health) match as plane="", route=path."""
        parts = path.strip("/").split("/")
        if len(parts) == 4 and parts[0] == "rpc":
            return parts[1], parts[3]
        return "", path.strip("/")

    def _take(self, action: str, src: str, peer: str, path: str
              ) -> FaultRule | None:
        plane, route = self._route_of(path)
        with self._mu:
            for r in self._rules:
                if r.action == action and r.matches(src, peer, route, plane):
                    r.fired += 1
                    return r
        return None

    def partitioned(self, src: str, peer: str) -> bool:
        with self._mu:
            for edges in self._partitions.values():
                if (src, peer) in edges:
                    return True
        return False

    # -- hooks (called by RestClient) ----------------------------------

    def on_connect(self, src: str, peer: str, path: str = "") -> None:
        """Raises ConnectionRefusedError before any socket exists when a
        partition or refusal rule covers (src → peer). `path` is the
        request the connection is being opened FOR, so route/plane
        matchers apply to refusals too (a route-scoped REFUSE fires at
        establishment; a pooled keep-alive conn sidesteps it by design —
        use a partition to cut live links). The probe loop rides the
        same hook, so a partitioned peer stays OPEN until the partition
        heals."""
        if self.partitioned(src, peer):
            raise ConnectionRefusedError(
                f"faultplane: partition {src or '?'} -> {peer}")
        if self._take(REFUSE, src, peer, path) is not None:
            raise ConnectionRefusedError(
                f"faultplane: refused {src or '?'} -> {peer}")

    def on_request(self, src: str, peer: str, path: str) -> None:
        """Delay and mid-call reset faults, applied as the request is
        about to be written (inside the caller's transport try block, so
        a raised reset degrades exactly like a real one). A named
        partition also bites HERE, not just at connect: a live link cut
        resets established keep-alive connections too — without this, a
        warm connection pool would tunnel straight through the
        partition."""
        import time as _time

        if self.partitioned(src, peer):
            raise ConnectionResetError(
                f"faultplane: partition {src or '?'} -> {peer} "
                f"(established connection reset)")
        rule = self._take(DELAY, src, peer, path)
        if rule is not None:
            _time.sleep(rule.draw_delay())
        if self._take(RESET, src, peer, path) is not None:
            raise ConnectionResetError(
                f"faultplane: reset {src or '?'} -> {peer} {path}")

    def response_fault(self, src: str, peer: str, path: str
                       ) -> FaultRule | None:
        """Claim a truncation/corruption rule for this call's response
        body (consumed now so `times` counts calls, not reads)."""
        rule = self._take(TRUNCATE, src, peer, path)
        if rule is not None:
            return rule
        return self._take(CORRUPT, src, peer, path)

    # -- determinism (tests) -------------------------------------------

    def schedule(self, n: int) -> list[tuple[str, float]]:
        """Preview the next `n` jitter draws per rule WITHOUT consuming
        them: a pure function of (seed, programming order), so two planes
        programmed identically under one seed preview — and then fire —
        the identical fault schedule."""
        out: list[tuple[str, float]] = []
        with self._mu:
            for r in self._rules:
                rng = random.Random()
                rng.setstate(r._rng.getstate())
                for _ in range(n):
                    d = (r.delay if r.jitter <= 0
                         else r.delay + rng.uniform(0.0, r.jitter))
                    out.append((r.action, d))
        return out


# --- process-global installation ---------------------------------------------

_PLANE: FaultPlane | None = None


def install(plane: FaultPlane | None = None,
            seed: int | None = None) -> FaultPlane:
    """Install the process-global plane. With seed=None the plane seed
    derives from the composed-chaos master (`MTPU_CHAOS_SEED`, via
    chaos.subseed(master, "net")): one integer then reproduces the
    network schedule together with the drive and crash schedules. An
    explicit seed overrides — single-plane tests keep their pinning."""
    global _PLANE
    if plane is None and seed is None:
        from minio_tpu import chaos

        seed = chaos.subseed(chaos.master_seed(), "net")
    _PLANE = plane if plane is not None else FaultPlane(seed=seed)
    return _PLANE


def uninstall() -> None:
    global _PLANE
    _PLANE = None


def get() -> FaultPlane | None:
    return _PLANE


def describe() -> dict:
    return {"installed": _PLANE is not None,
            **(_PLANE.describe() if _PLANE is not None else {})}


def apply_admin(doc: dict) -> dict:
    """Apply one admin-endpoint document to the global plane (installing
    it on first use). Shapes:
      {"op": "rule", "action": "...", ...FaultRule kwargs}
      {"op": "partition", "name": "...", "groups": [["a:1"], ["b:2"]]}
      {"op": "isolate", "name": "...", "src": "a:1", "dst": "b:2"}
      {"op": "heal", "name": "..."}
      {"op": "clear"}
    """
    plane = _PLANE if _PLANE is not None else install(
        seed=int(doc["seed"]) if doc.get("seed") is not None else None)
    op = doc.get("op", "")
    if op == "rule":
        kw = {k: doc[k] for k in ("src", "peer", "route", "plane", "delay",
                                  "jitter", "times", "xor")
              if doc.get(k) is not None}
        if doc.get("afterBytes") is not None:
            kw["after_bytes"] = doc["afterBytes"]
        plane.add_rule(doc.get("action", ""), **kw)
    elif op == "partition":
        plane.partition(doc.get("name", ""), *doc.get("groups", []))
    elif op == "isolate":
        plane.isolate(doc.get("name", ""), doc.get("src", ""),
                      doc.get("dst", ""))
    elif op == "heal":
        plane.heal(doc.get("name", ""))
    elif op == "clear":
        plane.clear()
    else:
        raise ValueError(f"unknown faultplane op {op!r}")
    return plane.describe()
