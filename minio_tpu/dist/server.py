"""NodeServer — one HTTP mux hosting every inter-node RPC plane.

Role-equivalent of the dist-erasure routers (cmd/routers.go:26-38): a single
listener serves storage REST, lock REST, peer REST and bootstrap REST under
distinct path roots. Handlers are plain callables registered per
(plane, method); bodies stream both ways.

Wire contract (shared with dist/rpc.py):
  POST /rpc/{plane}/v1/{method}?{urlencoded params}   body = raw bytes
  200  -> result bytes (msgpack for structured results, raw for file data)
  599  -> msgpack {"err": <error class name>, "msg": ...}  (typed error)
  GET  /health -> 200 (the reconnect probe target, cmd/rest/client.go:208)
"""

from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import BinaryIO, Callable, Iterable, Iterator

from minio_tpu import obs
from minio_tpu.dist import rpc
from minio_tpu.utils import errors as se

# A handler takes (params, body) and returns response bytes, an iterator of
# chunks (chunked streaming response), or None (empty 200).
Handler = Callable[[dict, BinaryIO], "bytes | Iterator[bytes] | None"]


class _BodyReader:
    """Bounded reader over the request body (Content-Length or chunked)."""

    def __init__(self, rfile: BinaryIO, length: int | None, chunked: bool):
        self._rfile = rfile
        self._remaining = length
        self._chunked = chunked
        self._chunk_left = 0
        self._done = False

    def read(self, n: int = -1) -> bytes:
        if self._chunked:
            return self._read_chunked(n)
        if self._remaining is None:
            return b""
        if n is None or n < 0:
            n = self._remaining
        n = min(n, self._remaining)
        if n <= 0:
            return b""
        data = self._rfile.read(n)
        self._remaining -= len(data)
        return data

    def _read_chunked(self, n: int) -> bytes:
        out = bytearray()
        want = None if n is None or n < 0 else n
        while not self._done and (want is None or len(out) < want):
            if self._chunk_left == 0:
                line = self._rfile.readline(32)
                if not line:
                    self._done = True
                    break
                self._chunk_left = int(line.strip().split(b";")[0], 16)
                if self._chunk_left == 0:
                    self._rfile.readline(32)  # trailing CRLF
                    self._done = True
                    break
            take = self._chunk_left if want is None else min(
                self._chunk_left, want - len(out))
            data = self._rfile.read(take)
            out += data
            self._chunk_left -= len(data)
            if self._chunk_left == 0:
                self._rfile.readline(32)  # CRLF after chunk
        return bytes(out)


class NodeServer:
    """Threaded HTTP server with pluggable RPC planes."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: str = "", ssl_context=None, node_name: str = ""):
        """ssl_context: serve the fabric over TLS (the reference serves
        every inter-node plane on its TLS listener). Accepts a plain
        server-side SSLContext, or an object with .current() (CertManager)
        — then every new connection handshakes against the freshest
        context, i.e. rotated certs hot-reload without restart.

        node_name: this node's advertised identity, stamped as `node` on
        trace records emitted while serving an RPC (carried on the
        context, not a process global — two in-process test nodes must
        not share it)."""
        self.secret = secret
        self.node_name = node_name
        self._routes: dict[tuple[str, str], Handler] = {}
        outer = self

        class _Req(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Metadata-class RPCs are small request/response pairs;
            # without this, Nagle + delayed ACK adds ~40 ms to every
            # round trip on the fabric.
            disable_nagle_algorithm = True
            daemon_threads = True

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/health":
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_error(404)

            def do_POST(self):
                outer._dispatch(self)

        if ssl_context is None:
            self._server = ThreadingHTTPServer((host, port), _Req)
        else:
            get_ctx = (ssl_context.current
                       if hasattr(ssl_context, "current")
                       else lambda: ssl_context)

            class _TLSServer(ThreadingHTTPServer):
                """TLS handshake runs in the PER-CONNECTION thread (with a
                timeout), never in the accept loop — a client that opens a
                socket and sends no ClientHello must not freeze every RPC
                plane of the node."""

                def finish_request(self, request, client_address):
                    import ssl as _ssl

                    request.settimeout(10.0)
                    try:
                        tls_sock = get_ctx().wrap_socket(
                            request, server_side=True)
                        tls_sock.settimeout(None)
                    except (_ssl.SSLError, OSError):
                        try:
                            request.close()
                        except OSError:
                            pass
                        return
                    super().finish_request(tls_sock, client_address)

            self._server = _TLSServer((host, port), _Req)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    # -- plane registration --

    def register(self, plane: str, method: str, fn: Handler) -> None:
        self._routes[(plane, method)] = fn

    def register_plane(self, plane: str, table: dict[str, Handler]) -> None:
        for method, fn in table.items():
            self.register(plane, method, fn)

    # -- lifecycle --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"node-server-{self.port}")
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- dispatch --

    def _dispatch(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urllib.parse.urlsplit(req.path)
        parts = parsed.path.strip("/").split("/")
        # /rpc/{plane}/v1/{method}
        if len(parts) != 4 or parts[0] != "rpc" or parts[2] != "v1":
            req.send_error(404)
            return
        plane, method = parts[1], parts[3]
        fn = self._routes.get((plane, method))
        if fn is None:
            req.send_error(404, f"no handler {plane}/{method}")
            return

        auth = req.headers.get("Authorization", "")
        if not (auth.startswith("Bearer ")
                and rpc.verify_token(self.secret, auth[7:])):
            req.send_error(403)
            return

        params = dict(urllib.parse.parse_qsl(parsed.query,
                                             keep_blank_values=True))
        chunked = req.headers.get("Transfer-Encoding", "").lower() == "chunked"
        length = req.headers.get("Content-Length")
        body = _BodyReader(req.rfile, int(length) if length else 0, chunked)

        # Restore the caller's trace context before dispatch — and hold
        # it through the RESPONSE write too: streaming handlers are lazy
        # generators whose bodies (and their storage records) execute
        # inside the chunked-write loop. Records the handler emits
        # correlate with the originating S3 request, stamped with THIS
        # node's identity.
        tokens = obs.set_trace_context(
            trace_id=req.headers.get("x-mtpu-trace-id") or None,
            node=self.node_name or None)
        try:
            self._invoke(req, fn, params, body)
        finally:
            obs.reset_trace_context(tokens)

    def _invoke(self, req, fn, params, body):
        try:
            result = fn(params, body)
        except (se.StorageError, se.ObjectError) as e:
            payload = rpc.pack({"err": type(e).__name__, "msg": str(e)})
            req.send_response(rpc.ERR_STATUS)
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            req.wfile.write(payload)
            return
        except Exception as e:  # unexpected → FaultyDisk on the client
            payload = rpc.pack({"err": "FaultyDisk",
                                "msg": f"{type(e).__name__}: {e}"})
            req.send_response(rpc.ERR_STATUS)
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            req.wfile.write(payload)
            return

        if result is None:
            req.send_response(200)
            req.send_header("Content-Length", "0")
            req.end_headers()
        elif isinstance(result, (bytes, bytearray)):
            req.send_response(200)
            req.send_header("Content-Length", str(len(result)))
            req.end_headers()
            req.wfile.write(result)
        else:  # chunked stream
            req.send_response(200)
            req.send_header("Transfer-Encoding", "chunked")
            req.end_headers()
            try:
                for chunk in result:
                    if not chunk:
                        continue
                    req.wfile.write(f"{len(chunk):x}\r\n".encode())
                    req.wfile.write(chunk)
                    req.wfile.write(b"\r\n")
                req.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                pass
