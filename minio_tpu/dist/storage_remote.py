"""Storage RPC: StorageAPI served over the node fabric + the remote client.

Role-equivalent of cmd/storage-rest-server.go / cmd/storage-rest-client.go:
every StorageAPI method becomes one route under /rpc/storage/v1/, bodies
stream for file data, structured values ride msgpack. The client implements
StorageAPI so the erasure engine cannot tell a remote drive from a local one
— the exact seam the reference uses to make "distributed" transparent
(SURVEY §1 L1).

FileInfo crosses the wire with the same doc encoding the xl.meta journal
uses (storage/xlmeta.py), plus volume/name/fresh envelope fields.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Iterable, Iterator

from minio_tpu import obs
from minio_tpu.dist.rpc import RestClient, pack, unpack
from minio_tpu.storage.api import DiskInfo, StorageAPI, VolInfo, WalkEntry
from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.storage.local import LocalDrive
from minio_tpu.storage.xlmeta import XLMeta, _doc_to_fi, _fi_to_doc
from minio_tpu.utils import errors as se

PLANE = "storage"
_READAHEAD = 1 << 20  # ranged-read granularity for remote shard streams


def fi_to_wire(fi: FileInfo) -> dict:
    doc = _fi_to_doc(fi)
    doc["_vol"] = fi.volume
    doc["_name"] = fi.name
    doc["_fresh"] = fi.fresh
    return doc


def fi_from_wire(doc: dict) -> FileInfo:
    fi = _doc_to_fi(doc, doc.get("_vol", ""), doc.get("_name", ""))
    fi.fresh = bool(doc.get("_fresh", False))
    return fi


# --- server side -------------------------------------------------------------

def storage_routes(drives: dict[str, LocalDrive]) -> dict:
    """Build the /rpc/storage/v1/* handler table for this node's local
    drives. `drives` maps the drive's on-node path (the endpoint path part,
    e.g. "/data/disk3") to its LocalDrive."""

    def drive(params: dict) -> LocalDrive:
        d = drives.get(params.get("disk", ""))
        if d is None:
            raise se.DiskNotFound(f"no local drive {params.get('disk', '')!r}")
        return d

    def h_disk_info(p, body):
        di = drive(p).disk_info()
        return pack({
            "total": di.total, "free": di.free, "used": di.used,
            "used_inodes": di.used_inodes, "endpoint": di.endpoint,
            "mount_path": di.mount_path, "id": di.id,
            "healing": di.healing, "error": di.error,
            # health metrics (drive state / timeout counts) ride along so
            # the admin drive-info surface sees the whole fleet.
            "metrics": dict(di.metrics),
        })

    def h_get_disk_id(p, body):
        return pack({"id": drive(p).get_disk_id()})

    def h_set_disk_id(p, body):
        drive(p).set_disk_id(p["id"])

    def h_read_format(p, body):
        return pack(drive(p).read_format())

    def h_write_format(p, body):
        drive(p).write_format(unpack(body.read(-1)))

    def h_make_vol(p, body):
        drive(p).make_vol(p["vol"])

    def h_list_vols(p, body):
        return pack([{"name": v.name, "created": v.created}
                     for v in drive(p).list_vols()])

    def h_stat_vol(p, body):
        v = drive(p).stat_vol(p["vol"])
        return pack({"name": v.name, "created": v.created})

    def h_delete_vol(p, body):
        drive(p).delete_vol(p["vol"], force=p.get("force") == "1")

    def h_write_all(p, body):
        drive(p).write_all(p["vol"], p["path"], body.read(-1))

    def h_read_all(p, body):
        return drive(p).read_all(p["vol"], p["path"])

    def h_delete(p, body):
        drive(p).delete(p["vol"], p["path"], recursive=p.get("rec") == "1")

    def h_list_dir(p, body):
        return pack(drive(p).list_dir(p["vol"], p["path"],
                                      count=int(p.get("count", "-1"))))

    def h_create_file(p, body):
        def chunks() -> Iterator[bytes]:
            while True:
                c = body.read(1 << 20)
                if not c:
                    return
                yield c
        n = drive(p).create_file(p["vol"], p["path"], chunks())
        return pack({"n": n})

    def h_append_file(p, body):
        drive(p).append_file(p["vol"], p["path"], body.read(-1))

    def h_stat_file(p, body):
        with drive(p).read_file_stream(p["vol"], p["path"]) as f:
            f.seek(0, 2)
            return pack({"size": f.tell()})

    def h_read_file_stream(p, body):
        off = int(p.get("off", "0"))
        length = int(p.get("len", "-1"))
        f = drive(p).read_file_stream(p["vol"], p["path"])

        def gen() -> Iterator[bytes]:
            try:
                f.seek(off)
                remaining = length
                while remaining != 0:
                    take = (1 << 20) if remaining < 0 else min(1 << 20, remaining)
                    c = f.read(take)
                    if not c:
                        return
                    if remaining > 0:
                        remaining -= len(c)
                    yield c
            finally:
                f.close()
        return gen()

    def h_rename_file(p, body):
        drive(p).rename_file(p["svol"], p["spath"], p["dvol"], p["dpath"])

    def h_write_metadata(p, body):
        drive(p).write_metadata(p["vol"], p["path"],
                                fi_from_wire(unpack(body.read(-1))))

    def h_write_metadata_single(p, body):
        # `raw` IS a journal holding exactly the one version being
        # written — reconstruct fi (and the journal-cache seed) from it
        # instead of shipping the inline body twice on the wire.
        raw = body.read(-1)
        journal = XLMeta.parse(raw)
        fi = journal.to_fileinfo(p["vol"], p["path"])
        tok = drive(p).write_metadata_single(
            p["vol"], p["path"], fi, raw, meta=journal,
            defer_reclaim=p.get("defer") == "1")
        return pack({"token": tok or ""})

    def h_read_version(p, body):
        fi = drive(p).read_version(p["vol"], p["path"],
                                   version_id=p.get("vid", ""),
                                   read_data=p.get("data") == "1")
        return pack(fi_to_wire(fi))

    def h_read_xl(p, body):
        return drive(p).read_xl(p["vol"], p["path"])

    def h_delete_version(p, body):
        drive(p).delete_version(p["vol"], p["path"],
                                fi_from_wire(unpack(body.read(-1))))

    def h_rename_data(p, body):
        tok = drive(p).rename_data(
            p["svol"], p["spath"], fi_from_wire(unpack(body.read(-1))),
            p["dvol"], p["dpath"],
            defer_reclaim=p.get("defer") == "1")
        return pack({"token": tok or ""})

    def h_commit_rename(p, body):
        drive(p).commit_rename(p.get("token", ""))

    def h_undo_rename(p, body):
        drive(p).undo_rename(p["vol"], p["path"],
                             fi_from_wire(unpack(body.read(-1))),
                             p.get("token", "") or None)

    def h_verify_file(p, body):
        drive(p).verify_file(p["vol"], p["path"],
                             fi_from_wire(unpack(body.read(-1))))

    def h_check_parts(p, body):
        drive(p).check_parts(p["vol"], p["path"],
                             fi_from_wire(unpack(body.read(-1))))

    def h_walk_dir(p, body):
        def gen() -> Iterator[bytes]:
            for e in drive(p).walk_dir(p["vol"], p.get("prefix", ""),
                                       p.get("start_after", "")):
                yield pack({"n": e.name, "m": e.meta})
        return gen()

    return {name[2:]: fn for name, fn in locals().items()
            if name.startswith("h_")}


# --- client side -------------------------------------------------------------

class _RemoteFile(io.RawIOBase):
    """Seekable read-only view of a remote file via ranged read RPCs.

    BitrotReader seeks to [digest][chunk] record offsets and reads
    sequentially; a 1 MiB read-ahead buffer turns that into ~one RPC per
    MiB of shard data (the reference instead pre-computes the ranged
    ReadFileStream per part, cmd/erasure-decode.go)."""

    def __init__(self, drv: "RemoteDrive", volume: str, path: str):
        super().__init__()
        self._drv = drv
        self._volume = volume
        self._path = path
        self._pos = 0
        self._size: int | None = None
        self._buf = b""
        self._buf_off = 0
        # Fail fast (and typed) if the file is missing: mirrors local
        # open() raising FileNotFound at stream-open time.
        self._stat()

    def _stat(self) -> int:
        if self._size is None:
            doc = self._drv._client.call_msgpack(
                self._drv._path("stat_file"),
                self._drv._params(vol=self._volume, path=self._path))
            self._size = int(doc["size"])
        return self._size

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        elif whence == 2:
            self._pos = self._stat() + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        size = self._stat()
        if n is None or n < 0:
            n = max(0, size - self._pos)
        if n == 0 or self._pos >= size:
            return b""
        # Serve from buffer when possible.
        rel = self._pos - self._buf_off
        if 0 <= rel < len(self._buf):
            chunk = self._buf[rel:rel + n]
            self._pos += len(chunk)
            if len(chunk) == n:
                return chunk
            return chunk + self.read(n - len(chunk))
        # Refill.
        want = max(n, _READAHEAD)
        want = min(want, size - self._pos)
        st = self._drv._client.call(
            self._drv._path("read_file_stream"),
            self._drv._params(vol=self._volume, path=self._path,
                              off=str(self._pos), len=str(want)),
            stream=True)
        try:
            data = st.read(want)
            rest = bytearray(data)
            while len(rest) < want:
                c = st.read(want - len(rest))
                if not c:
                    break
                rest += c
            data = bytes(rest)
        finally:
            st.close()
        self._buf = data
        self._buf_off = self._pos
        chunk = data[:n]
        self._pos += len(chunk)
        return chunk


class RemoteDrive(StorageAPI):
    """StorageAPI over the node fabric — one per (peer node, drive path)."""

    def __init__(self, client: RestClient, disk_path: str, endpoint: str = ""):
        self._client = client
        self._disk = disk_path
        self._endpoint = endpoint or f"{client.host}:{client.port}{disk_path}"
        self._disk_id = ""
        # Remote drives feed the SAME drive-latency family + storage
        # trace shape LocalDrive uses — the whole fleet as seen from this
        # node, with the fabric hop included in the duration.
        self._observe_op = obs.drive_op_observer(self._endpoint)

    def _path(self, method: str) -> str:
        return f"/rpc/{PLANE}/v1/{method}"

    def _params(self, **kw) -> dict:
        kw["disk"] = self._disk
        return kw

    def _call(self, method: str, body=None, **kw):
        return self._client.call_msgpack(self._path(method),
                                         self._params(**kw), body=body)

    # -- identity / health --

    def disk_info(self) -> DiskInfo:
        doc = self._call("disk_info")
        return DiskInfo(**doc)

    def get_disk_id(self) -> str:
        doc = self._call("get_disk_id")
        self._disk_id = doc["id"]
        return self._disk_id

    def set_disk_id(self, disk_id: str) -> None:
        self._call("set_disk_id", id=disk_id)
        self._disk_id = disk_id

    def is_online(self) -> bool:
        return self._client.is_online()

    def is_local(self) -> bool:
        return False

    def endpoint(self) -> str:
        return self._endpoint

    def close(self) -> None:
        pass  # client is shared per-node; closed by the cluster

    def read_format(self) -> dict:
        return self._call("read_format")

    def write_format(self, fmt: dict) -> None:
        self._call("write_format", body=pack(fmt))

    # -- volumes --

    def make_vol(self, volume: str) -> None:
        self._call("make_vol", vol=volume)

    def list_vols(self) -> list[VolInfo]:
        return [VolInfo(**v) for v in self._call("list_vols")]

    def stat_vol(self, volume: str) -> VolInfo:
        return VolInfo(**self._call("stat_vol", vol=volume))

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call("delete_vol", vol=volume, force="1" if force else "0")

    # -- small files --

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("write_all", body=data, vol=volume, path=path)

    def read_all(self, volume: str, path: str) -> bytes:
        return self._client.call(self._path("read_all"),
                                 self._params(vol=volume, path=path))

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call("delete", vol=volume, path=path,
                   rec="1" if recursive else "0")

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        return self._call("list_dir", vol=volume, path=dir_path,
                          count=str(count))

    # -- file streams --

    def create_file(self, volume: str, path: str,
                    chunks: Iterable[bytes]) -> int:
        with obs.timed_op(self._observe_op, "create_file", volume, path):
            doc = self._call("create_file", body=chunks, vol=volume,
                             path=path)
            return doc["n"]

    def append_file(self, volume: str, path: str, data: bytes) -> None:
        self._call("append_file", body=data, vol=volume, path=path)

    def read_file_stream(self, volume: str, path: str) -> BinaryIO:
        return _RemoteFile(self, volume, path)

    def read_file_range_stream(self, volume: str, path: str, off: int,
                               length: int):
        """ONE long-lived streamed request for [off, off+length) — the
        reference's ReadFileStream shape (cmd/storage-rest-client.go:475):
        a sequential consumer (the mixed GET lane's framed prefetch)
        rides a single socket instead of paying per-window request
        setup. Returns a file-like with read()/close()."""
        return self._client.call(
            self._path("read_file_stream"),
            self._params(vol=volume, path=path, off=str(off),
                         len=str(length)),
            stream=True)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        self._call("rename_file", svol=src_volume, spath=src_path,
                   dvol=dst_volume, dpath=dst_path)

    # -- versioned metadata --

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("write_metadata", body=pack(fi_to_wire(fi)),
                   vol=volume, path=path)

    def write_metadata_single(self, volume: str, path: str, fi: FileInfo,
                              raw: bytes, meta=None,
                              defer_reclaim: bool = False) -> "str | None":
        """Ships ONLY the pre-serialized journal (which holds exactly
        `fi`, inline body included) — the server reconstructs fi and the
        cache seed from it — keeping the single-serialize fast path AND
        the deferred-reclaim contract over the wire (the base-class
        default would fall back to the merge path with no undo
        capsule)."""
        with obs.timed_op(self._observe_op, "write_metadata_single",
                          volume, path):
            doc = self._call("write_metadata_single", body=raw,
                             vol=volume, path=path,
                             defer="1" if defer_reclaim else "0")
            tok = (doc or {}).get("token", "")
            return tok or None

    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo:
        with obs.timed_op(self._observe_op, "read_version", volume, path):
            doc = self._call("read_version", vol=volume, path=path,
                             vid=version_id, data="1" if read_data else "0")
            return fi_from_wire(doc)

    def read_xl(self, volume: str, path: str) -> bytes:
        return self._client.call(self._path("read_xl"),
                                 self._params(vol=volume, path=path))

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("delete_version", body=pack(fi_to_wire(fi)),
                   vol=volume, path=path)

    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str,
                    defer_reclaim: bool = False) -> "str | None":
        with obs.timed_op(self._observe_op, "rename_data",
                          dst_volume, dst_path):
            doc = self._call("rename_data", body=pack(fi_to_wire(fi)),
                             svol=src_volume, spath=src_path,
                             dvol=dst_volume, dpath=dst_path,
                             defer="1" if defer_reclaim else "0")
            tok = (doc or {}).get("token", "")
            return tok or None

    def commit_rename(self, token: str) -> None:
        self._call("commit_rename", token=token or "")

    def undo_rename(self, volume: str, path: str, fi: FileInfo,
                    token: "str | None") -> None:
        self._call("undo_rename", body=pack(fi_to_wire(fi)),
                   vol=volume, path=path, token=token or "")

    # -- verification / listing --

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("verify_file", body=pack(fi_to_wire(fi)),
                   vol=volume, path=path)

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("check_parts", body=pack(fi_to_wire(fi)),
                   vol=volume, path=path)

    def walk_dir(self, volume: str, prefix: str = "",
                 start_after: str = "") -> Iterator[WalkEntry]:
        params = self._params(vol=volume, prefix=prefix)
        if start_after:
            params["start_after"] = start_after
        for doc in self._client.iter_msgpack(
                self._path("walk_dir"), params):
            yield WalkEntry(name=doc["n"], meta=doc["m"])
