"""Namespace locks: per-(bucket, object) RW locking for the object layer.

Role-equivalent of cmd/namespace-lock.go:48-263 — the object engine asks for
a lock on (bucket, object...) around mutating commits; standalone mode uses
an in-process RW mutex table, distributed mode a dsync DRWMutex over the
set's lockers. The context-manager shape replaces the reference's
GetLock/Unlock pairs.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator

from minio_tpu.dist.dsync import DRWMutex
from minio_tpu.utils import errors as se


class _RWLock:
    """Writer-preferring in-process RW mutex (pkg/lsync role)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self, timeout: float) -> bool:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._writer and self._writers_waiting == 0,
                timeout)
            if ok:
                self._readers += 1
            return ok

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self, timeout: float) -> bool:
        with self._cond:
            self._writers_waiting += 1
            try:
                ok = self._cond.wait_for(
                    lambda: not self._writer and self._readers == 0, timeout)
                if ok:
                    self._writer = True
                return ok
            finally:
                self._writers_waiting -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class LockLease:
    """What `lock()` yields: a handle whose `held` goes False if the
    distributed lock loses its refresh quorum mid-critical-section (a
    partition isolating this node from the locker majority). Commit
    paths consult it at the point of no return and roll back instead of
    completing an unprotected write. Local locks can't be lost: `held`
    is constant True."""

    __slots__ = ("_mx",)

    def __init__(self, mx=None):
        self._mx = mx

    @property
    def held(self) -> bool:
        return True if self._mx is None else self._mx.held


_LOCAL_LEASE = LockLease()


class NamespaceLockMap:
    """Lock table keyed by "bucket/object" pathnames.

    distributed=False -> in-process table (nsLockMap local mode);
    distributed=True  -> each lock() builds a DRWMutex over `lockers`
    (the set's lockers, cmd/erasure-sets.go NewNSLock)."""

    def __init__(self, distributed: bool = False, lockers: list | None = None,
                 owner: str = "", refresh_interval: float | None = None):
        self.distributed = distributed
        self.lockers = lockers or []
        self.owner = owner
        # None -> dsync default (MTPU_DSYNC_REFRESH_INTERVAL); tests pin
        # it low so partition-during-commit aborts are provable fast.
        self.refresh_interval = refresh_interval
        # resource -> [lock, refcount]; the refcount is mutated only under
        # _mu (the reference nsLockMap keeps `ref` under lockMapMutex,
        # cmd/namespace-lock.go:141) so an entry can never be GC'd between
        # another thread's _get and its acquire — deleting in that window
        # would hand two writers two different 'same' locks.
        self._table: dict[str, list] = {}
        self._mu = threading.Lock()

    def _get(self, resource: str) -> _RWLock:
        with self._mu:
            entry = self._table.get(resource)
            if entry is None:
                entry = self._table[resource] = [_RWLock(), 0]
            entry[1] += 1
            return entry[0]

    def _unref(self, resource: str) -> None:
        with self._mu:
            entry = self._table.get(resource)
            if entry is None:
                return
            entry[1] -= 1
            if entry[1] <= 0:
                del self._table[resource]

    def rlock(self, bucket: str, obj: str, timeout: float = 30.0):
        """Single-resource READ lock, the GET hot path: a plain __enter__/
        __exit__ object instead of the generator contextmanager + sorted
        multi-resource machinery (measurably cheaper at thousands of ops
        per second). Distributed mode uses the general path — the dsync
        RPC dominates there anyway."""
        if self.distributed:
            return self.lock(bucket, obj, timeout=timeout, readonly=True)
        return _ReadLease(self, f"{bucket}/{obj}" if obj else bucket,
                          timeout)

    @contextlib.contextmanager
    def lock(self, bucket: str, *objects: str, timeout: float = 30.0,
             readonly: bool = False) -> Iterator[LockLease]:
        resources = sorted(f"{bucket}/{o}" if o else bucket
                           for o in (objects or ("",)))
        if self.distributed:
            mx = DRWMutex(resources, self.lockers, owner=self.owner,
                          refresh_interval=self.refresh_interval)
            got = mx.get_rlock(timeout) if readonly else mx.get_lock(timeout)
            if not got:
                mx.unlock()   # release the broadcast pool's workers
                raise se.OperationTimedOut(
                    bucket, ",".join(objects),
                    f"lock timeout on {resources}")
            try:
                yield LockLease(mx)
            finally:
                mx.unlock()
            return

        # Local mode: acquire in sorted order (deadlock-free), all-or-release.
        acquired: list[_RWLock] = []
        referenced: list[str] = []
        try:
            for res in resources:
                lk = self._get(res)
                referenced.append(res)
                ok = (lk.acquire_read(timeout) if readonly
                      else lk.acquire_write(timeout))
                if not ok:
                    raise se.OperationTimedOut(
                        bucket, ",".join(objects), f"lock timeout on {res}")
                acquired.append(lk)
            yield _LOCAL_LEASE
        finally:
            for lk in reversed(acquired):
                if readonly:
                    lk.release_read()
                else:
                    lk.release_write()
            for res in referenced:
                self._unref(res)


class _ReadLease:
    """Allocation-minimal context for one local read lock (see
    NamespaceLockMap.rlock)."""

    __slots__ = ("_map", "_res", "_timeout", "_lk")

    def __init__(self, lock_map: NamespaceLockMap, resource: str,
                 timeout: float):
        self._map = lock_map
        self._res = resource
        self._timeout = timeout
        self._lk = None

    def __enter__(self):
        lk = self._map._get(self._res)
        if not lk.acquire_read(self._timeout):
            self._map._unref(self._res)
            raise se.OperationTimedOut(
                "", self._res, f"lock timeout on {self._res}")
        self._lk = lk
        return self

    def __exit__(self, *exc):
        self._lk.release_read()
        self._map._unref(self._res)
        return False
