"""dsync — quorum-based distributed read/write locks.

Role-equivalent of pkg/dsync: a lock is acquired by sending simultaneous
lock calls to ALL n lockers and succeeds iff a quorum grants it
(drwmutex.go:165-187 — write quorum n/2+1, read quorum n/2, tolerance-
adjusted); failed acquisitions release every granted locker (releaseAll:498)
and retry with jitter until the timeout; held locks are refreshed
continuously and dropped if the refresh quorum is lost (refresh:245).

Lockers are symmetric: every node runs a LocalLocker served over the lock
RPC plane; a DRWMutex talks to all of a set's lockers (local one in-process,
peers via RemoteLocker).
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Protocol

from minio_tpu import obs
from minio_tpu.dist.rpc import RestClient, pack, unpack

# Unrefreshed locks are presumed owned by a dead process and reaped
# (the reference's lock maintenance loop, cmd/lock-rest-server.go:330).
LOCK_STALE_AFTER = 60.0
REFRESH_INTERVAL = float(os.environ.get("MTPU_DSYNC_REFRESH_INTERVAL",
                                        "10.0"))
RETRY_MIN = 0.01
RETRY_MAX = 0.25

# A held lock dropping its refresh quorum is the partition signal the
# degraded-write path keys on (commits check `held` and roll back) —
# count it so an operator can see silent lock losses.
_REFRESH_LOST = obs.counter(
    "minio_tpu_dsync_refresh_lost_total",
    "Held dsync locks dropped after losing their refresh quorum")


@dataclass
class LockArgs:
    uid: str
    resources: list[str]
    owner: str
    readonly: bool = False

    def to_doc(self) -> dict:
        return {"uid": self.uid, "res": self.resources,
                "owner": self.owner, "ro": self.readonly}

    @classmethod
    def from_doc(cls, doc: dict) -> "LockArgs":
        return cls(uid=doc["uid"], resources=list(doc["res"]),
                   owner=doc.get("owner", ""), readonly=bool(doc.get("ro")))


class NetLocker(Protocol):
    """The RPC surface a locker must serve (pkg/dsync/rpc-client-interface.go:42)."""

    def lock(self, args: LockArgs) -> bool: ...
    def unlock(self, args: LockArgs) -> bool: ...
    def rlock(self, args: LockArgs) -> bool: ...
    def runlock(self, args: LockArgs) -> bool: ...
    def refresh(self, args: LockArgs) -> bool: ...
    def force_unlock(self, args: LockArgs) -> bool: ...
    def is_online(self) -> bool: ...


@dataclass
class _Grant:
    uid: str
    owner: str
    readonly: bool
    granted_at: float
    refreshed_at: float


class LocalLocker:
    """In-process lock table: resource -> grants (cmd/local-locker.go:55).

    A write grant excludes everything; read grants coexist. Stale grants
    (no refresh within LOCK_STALE_AFTER) are reaped lazily on conflict —
    this is what lets the cluster survive a lock-holder dying mid-flight.
    """

    def __init__(self):
        self._table: dict[str, list[_Grant]] = {}
        self._mu = threading.Lock()

    def _reap(self, resource: str, now: float) -> list[_Grant]:
        grants = [g for g in self._table.get(resource, ())
                  if now - g.refreshed_at < LOCK_STALE_AFTER]
        if grants:
            self._table[resource] = grants
        else:
            self._table.pop(resource, None)
        return grants

    def _acquire(self, args: LockArgs, readonly: bool) -> bool:
        now = time.time()
        with self._mu:
            # All-or-nothing across the resource list.
            for res in args.resources:
                grants = self._reap(res, now)
                if readonly:
                    if any(not g.readonly for g in grants):
                        return False
                elif grants:
                    return False
            for res in args.resources:
                self._table.setdefault(res, []).append(
                    _Grant(args.uid, args.owner, readonly, now, now))
            return True

    def _release(self, args: LockArgs, readonly: bool) -> bool:
        ok = False
        with self._mu:
            for res in args.resources:
                grants = self._table.get(res, [])
                keep = [g for g in grants
                        if not (g.uid == args.uid and g.readonly == readonly)]
                if len(keep) != len(grants):
                    ok = True
                if keep:
                    self._table[res] = keep
                else:
                    self._table.pop(res, None)
        return ok

    # -- NetLocker --

    def lock(self, args: LockArgs) -> bool:
        return self._acquire(args, readonly=False)

    def rlock(self, args: LockArgs) -> bool:
        return self._acquire(args, readonly=True)

    def unlock(self, args: LockArgs) -> bool:
        return self._release(args, readonly=False)

    def runlock(self, args: LockArgs) -> bool:
        return self._release(args, readonly=True)

    def refresh(self, args: LockArgs) -> bool:
        now = time.time()
        found = False
        with self._mu:
            for res in args.resources:
                for g in self._table.get(res, ()):
                    if g.uid == args.uid:
                        g.refreshed_at = now
                        found = True
        return found

    def force_unlock(self, args: LockArgs) -> bool:
        with self._mu:
            for res in args.resources:
                self._table.pop(res, None)
        return True

    def is_online(self) -> bool:
        return True

    # -- introspection (admin top-locks) --

    def dump(self) -> dict[str, list[dict]]:
        with self._mu:
            return {res: [{"uid": g.uid, "owner": g.owner, "ro": g.readonly,
                           "since": g.granted_at} for g in grants]
                    for res, grants in self._table.items()}


# --- lock RPC plane ----------------------------------------------------------

PLANE = "lock"


def lock_routes(locker: LocalLocker) -> dict:
    """Handlers serving this node's LocalLocker (cmd/lock-rest-server.go)."""

    def wrap(method):
        def h(params: dict, body) -> bytes:
            args = LockArgs.from_doc(unpack(body.read(-1)))
            return pack({"ok": bool(getattr(locker, method)(args))})
        return h

    return {m: wrap(m) for m in
            ["lock", "unlock", "rlock", "runlock", "refresh", "force_unlock"]}


class RemoteLocker:
    """NetLocker over the node fabric (cmd/lock-rest-client.go). Network
    failure = refusal (False) — dsync quorum absorbs locker loss."""

    def __init__(self, client: RestClient):
        self._client = client

    def _call(self, method: str, args: LockArgs) -> bool:
        try:
            doc = self._client.call_msgpack(
                f"/rpc/{PLANE}/v1/{method}", body=pack(args.to_doc()))
            return bool(doc and doc.get("ok"))
        except Exception:
            return False

    def lock(self, args: LockArgs) -> bool:
        return self._call("lock", args)

    def unlock(self, args: LockArgs) -> bool:
        return self._call("unlock", args)

    def rlock(self, args: LockArgs) -> bool:
        return self._call("rlock", args)

    def runlock(self, args: LockArgs) -> bool:
        return self._call("runlock", args)

    def refresh(self, args: LockArgs) -> bool:
        return self._call("refresh", args)

    def force_unlock(self, args: LockArgs) -> bool:
        return self._call("force_unlock", args)

    def is_online(self) -> bool:
        return self._client.is_online()


# --- the distributed mutex ---------------------------------------------------

class DRWMutex:
    """Quorum read/write lock over n lockers (pkg/dsync/drwmutex.go:56)."""

    def __init__(self, resources: list[str], lockers: list,
                 owner: str = "", refresh_interval: float | None = None,
                 on_lost=None):
        """on_lost: called (once) from the refresh thread if the lock
        loses its refresh quorum while held — the abort signal degraded
        writes key on."""
        self.resources = resources
        self.lockers = lockers
        self.owner = owner or str(uuid.uuid4())
        self.refresh_interval = (REFRESH_INTERVAL if refresh_interval is None
                                 else refresh_interval)
        self.on_lost = on_lost
        self._uid = ""
        self._readonly = False
        self._held = False
        self._released = False
        self._stop_refresh = threading.Event()
        self._refresh_thread: threading.Thread | None = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, len(lockers)),
            thread_name_prefix="dsync")

    # write quorum n/2+1; read quorum n/2 (drwmutex.go:165-187)
    def _quorum(self, readonly: bool) -> int:
        n = len(self.lockers)
        q = n // 2 if readonly else n // 2 + 1
        return max(q, 1)

    def _broadcast(self, method: str, args: LockArgs) -> int:
        futs = []
        for lk in self.lockers:
            try:
                futs.append(self._pool.submit(
                    obs.ctx_wrap(getattr(lk, method)), args))
            except RuntimeError:
                # unlock() shut the pool down while the refresh thread
                # was entering a broadcast — count the locker as
                # unreachable instead of crashing the daemon thread.
                pass
        granted = 0
        for f in futs:
            try:
                if f.result(timeout=30):
                    granted += 1
            except Exception:
                pass
        return granted

    def _try_acquire(self, readonly: bool) -> bool:
        uid = str(uuid.uuid4())
        args = LockArgs(uid=uid, resources=self.resources,
                        owner=self.owner, readonly=readonly)
        method = "rlock" if readonly else "lock"
        granted = self._broadcast(method, args)
        if granted >= self._quorum(readonly):
            self._uid = uid
            self._readonly = readonly
            self._held = True
            self._start_refresh()
            return True
        # Release whatever we got (releaseAll, drwmutex.go:498).
        self._broadcast("runlock" if readonly else "unlock", args)
        return False

    def _acquire_blocking(self, readonly: bool, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while True:
            if self._try_acquire(readonly):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(random.uniform(RETRY_MIN, RETRY_MAX))

    def get_lock(self, timeout: float = 30.0) -> bool:
        return self._acquire_blocking(readonly=False, timeout=timeout)

    def get_rlock(self, timeout: float = 30.0) -> bool:
        return self._acquire_blocking(readonly=True, timeout=timeout)

    def unlock(self) -> None:
        # Keyed on _released, NOT _held: a refresh-quorum loss flips
        # _held to abort commits, but the minority lockers that still
        # hold the grant must be released (best effort — partitioned
        # ones fail fast) and the executor shut down, or every lease
        # abort would leak worker threads and block new writers for
        # LOCK_STALE_AFTER.
        if self._released:
            return
        self._released = True
        self._held = False
        self._stop_refresh.set()
        if self._uid:
            args = LockArgs(uid=self._uid, resources=self.resources,
                            owner=self.owner, readonly=self._readonly)
            self._broadcast("runlock" if self._readonly else "unlock", args)
        self._pool.shutdown(wait=False)

    # -- keepalive (drwmutex.go:214,245) --

    def _start_refresh(self) -> None:
        self._stop_refresh = threading.Event()

        def loop():
            args = LockArgs(uid=self._uid, resources=self.resources,
                            owner=self.owner, readonly=self._readonly)
            while not self._stop_refresh.wait(self.refresh_interval):
                refreshed = self._broadcast("refresh", args)
                if self._stop_refresh.is_set():
                    # unlock() raced this tick — a released lock cannot
                    # lose its quorum (no spurious on_lost/metric).
                    return
                if refreshed < self._quorum(self._readonly):
                    # Lost the quorum — the lock is no longer safe to
                    # hold. Commits in flight observe `held` flipping and
                    # roll back instead of completing unprotected.
                    self._held = False
                    _REFRESH_LOST.labels().inc()
                    if self.on_lost is not None:
                        try:
                            self.on_lost()
                        except Exception:  # noqa: BLE001 - observer only
                            pass
                    return

        self._refresh_thread = threading.Thread(
            target=loop, daemon=True, name="dsync-refresh")
        self._refresh_thread.start()

    @property
    def held(self) -> bool:
        return self._held
