"""etcd-backed system-config store — the reference's pluggable external
IAM/config backend (cmd/etcd.go:1-86, cmd/iam-etcd-store.go): federated
deployments keep identity in a SHARED etcd cluster so every site sees the
same users/policies, instead of each cluster's own drive-quorum store.

Speaks etcd v3's gRPC-JSON gateway (`/v3/kv/range|put|deleterange`,
`/v3/auth/authenticate`) over plain HTTP — keys/values travel base64 per
the gateway contract. Implements exactly the SysConfigStore surface
(read/write/delete/list_sys_config), so it drops into `IAMSys(store=...)`
or `BucketMetadataSys` unchanged; sealing (SealedSysStore) layers on top
the same way it does over the drive store.

Change detection is poll-based: `watch()` compares the prefix's max
mod_revision on an interval and fires the callback on movement — the
role of the reference's etcd watch channel (iam-etcd-store.go watchIAM),
chosen over the gateway's streaming watch for robustness across gateway
versions.
"""

from __future__ import annotations

import base64
import threading
import time


def _b64(s: bytes) -> str:
    return base64.b64encode(s).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class EtcdError(Exception):
    pass


def _range_end(key: bytes) -> bytes:
    """etcd prefix-range end: the key's lexicographic successor at the
    prefix level (increment the last byte below 0xff, dropping trailing
    0xff bytes; all-0xff or empty means 'to the end' = b'\\x00' per the
    gateway convention)."""
    k = bytearray(key)
    while k and k[-1] == 0xFF:
        k.pop()
    if not k:
        return b"\x00"
    k[-1] += 1
    return bytes(k)


class EtcdConfigStore:
    def __init__(self, endpoint: str, prefix: str = "minio_tpu/config/",
                 username: str = "", password: str = "",
                 timeout: float = 10.0):
        import requests

        self.endpoint = endpoint.rstrip("/")
        self.prefix = prefix
        self.timeout = timeout
        self._user, self._password = username, password
        self._s = requests.Session()
        if username:
            self._authenticate()
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None

    def _authenticate(self) -> None:
        r = self._s.post(f"{self.endpoint}/v3/auth/authenticate",
                         json={"name": self._user,
                               "password": self._password},
                         timeout=self.timeout)
        if r.status_code != 200:
            raise EtcdError(f"etcd auth failed: HTTP {r.status_code}")
        self._s.headers["Authorization"] = r.json()["token"]

    def _call(self, path: str, doc: dict) -> dict:
        import requests

        try:
            r = self._s.post(f"{self.endpoint}{path}", json=doc,
                             timeout=self.timeout)
            if r.status_code in (401, 403) and self._user:
                # etcd simple tokens expire (~300 s default): re-auth
                # once and retry — otherwise every IAM op fails until
                # restart.
                self._authenticate()
                r = self._s.post(f"{self.endpoint}{path}", json=doc,
                                 timeout=self.timeout)
        except requests.RequestException as e:
            # Typed: the watch loop survives transient outages, IAM ops
            # surface a clean storage error instead of a transport trace.
            raise EtcdError(f"etcd {path}: {e}") from e
        if r.status_code != 200:
            raise EtcdError(f"etcd {path}: HTTP {r.status_code} {r.text[:200]}")
        return r.json()

    def _key(self, path: str) -> bytes:
        return (self.prefix + path).encode()

    # ---- SysConfigStore surface ----

    def read_sys_config(self, path: str) -> bytes:
        from minio_tpu.utils import errors as se

        doc = self._call("/v3/kv/range", {"key": _b64(self._key(path))})
        kvs = doc.get("kvs") or []
        if not kvs:
            raise se.FileNotFound(path)
        return _unb64(kvs[0].get("value", ""))

    def write_sys_config(self, path: str, data: bytes) -> None:
        self._call("/v3/kv/put", {"key": _b64(self._key(path)),
                                  "value": _b64(data)})

    def delete_sys_config(self, path: str) -> None:
        self._call("/v3/kv/deleterange", {"key": _b64(self._key(path))})

    def list_sys_config(self, prefix: str = "") -> list[str]:
        key = self._key(prefix)
        doc = self._call("/v3/kv/range", {
            "key": _b64(key), "range_end": _b64(_range_end(key)),
            "keys_only": True})
        strip = len(self.prefix)
        out = []
        for kv in doc.get("kvs") or []:
            k = _unb64(kv["key"]).decode()
            out.append(k[strip:])
        return sorted(out)

    # ---- change detection (iam-etcd-store.go watchIAM role) ----

    def _change_sig(self, prefix: str) -> tuple[int, int]:
        """(max mod_revision, key count) under prefix: a put moves the
        first component, a delete moves the second."""
        key = self._key(prefix)
        doc = self._call("/v3/kv/range", {
            "key": _b64(key), "range_end": _b64(_range_end(key)),
            "keys_only": True})
        kvs = doc.get("kvs") or []
        return (max((int(kv.get("mod_revision", 0)) for kv in kvs),
                    default=0), len(kvs))

    def watch(self, prefix: str, callback, interval: float = 5.0) -> None:
        """Fire callback() whenever keys under prefix change (poll-based;
        one background thread). The baseline is taken SYNCHRONOUSLY here:
        a change landing between watch() and the first poll tick must
        fire, not be absorbed into the baseline."""
        try:
            last = self._change_sig(prefix)
        except EtcdError:
            last = None

        def loop():
            nonlocal last
            while not self._watch_stop.wait(interval):
                try:
                    cur = self._change_sig(prefix)
                except EtcdError:
                    continue
                if last is not None and cur != last:
                    try:
                        callback()
                    except Exception:  # noqa: BLE001
                        pass
                last = cur

        self._watch_thread = threading.Thread(
            target=loop, daemon=True, name="etcd-watch")
        self._watch_thread.start()

    def close(self) -> None:
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
        self._s.close()
