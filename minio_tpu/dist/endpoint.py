"""Endpoint topology: ellipses expansion → pools × sets × drives layout.

Role-equivalent of pkg/ellipses + cmd/endpoint-ellipses.go:254,279 +
cmd/endpoint.go: server args like

    http://host{1...4}:9000/data/disk{1...16}     (distributed)
    /data/disk{1...16}                            (single node)

expand to drive endpoints; each arg group is one pool; the erasure set
drive count is the largest "nice" divisor of the drive count (16 down to
2, cmd/endpoint-ellipses.go setSizes) unless pinned explicitly.
"""

from __future__ import annotations

import itertools
import re
import socket
import urllib.parse
from dataclasses import dataclass

_ELLIPSIS = re.compile(r"\{(\d+)\.\.\.(\d+)\}")

# Candidate set sizes, preferred large→small (cmd/endpoint-ellipses.go:28).
SET_SIZES = [16, 15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 3, 2]

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1", ""}


def expand_ellipses(arg: str) -> list[str]:
    """Expand every {a...b} range in arg (cartesian, left-to-right)."""
    spans = list(_ELLIPSIS.finditer(arg))
    if not spans:
        return [arg]
    ranges = []
    for m in spans:
        lo, hi = int(m.group(1)), int(m.group(2))
        if hi < lo:
            raise ValueError(f"bad ellipsis range {m.group(0)} in {arg!r}")
        width = len(m.group(1)) if m.group(1).startswith("0") else 0
        ranges.append([str(v).zfill(width) for v in range(lo, hi + 1)])
    out = []
    for combo in itertools.product(*ranges):
        s, last = "", 0
        for m, val in zip(spans, combo):
            s += arg[last:m.start()] + val
            last = m.end()
        out.append(s + arg[last:])
    return out


@dataclass(frozen=True)
class Endpoint:
    """One drive endpoint: local path or remote URL (cmd/endpoint.go:51)."""

    host: str        # "" for a plain path
    port: int        # 0 for a plain path
    path: str
    is_local: bool

    @property
    def url(self) -> str:
        if not self.host:
            return self.path
        return f"http://{self.host}:{self.port}{self.path}"

    @property
    def node(self) -> tuple[str, int]:
        return (self.host, self.port)


def _local_hostnames() -> set[str]:
    names = set(_LOCAL_NAMES)
    try:
        hn = socket.gethostname()
        names.add(hn)
        names.add(socket.getfqdn())
        try:
            names.update(socket.gethostbyname_ex(hn)[2])
        except OSError:
            pass
    except OSError:
        pass
    return names


def parse_endpoint(arg: str, local_host: str = "", local_port: int = 0,
                   local_names: set[str] | None = None) -> Endpoint:
    if "://" not in arg:
        return Endpoint("", 0, arg, True)
    u = urllib.parse.urlsplit(arg)
    if u.scheme not in ("http", "https") or not u.path or u.path == "/":
        raise ValueError(f"invalid endpoint {arg!r}")
    host = u.hostname or ""
    port = u.port or 9000
    names = local_names if local_names is not None else _local_hostnames()
    is_local = (host in names or host == local_host) and (
        local_port == 0 or port == local_port)
    return Endpoint(host, port, u.path.rstrip("/"), is_local)


@dataclass
class PoolLayout:
    """One pool: drives grouped into erasure sets of set_drive_count."""

    endpoints: list[Endpoint]
    set_drive_count: int

    @property
    def set_count(self) -> int:
        return len(self.endpoints) // self.set_drive_count

    def sets(self) -> list[list[Endpoint]]:
        c = self.set_drive_count
        return [self.endpoints[i * c:(i + 1) * c]
                for i in range(self.set_count)]


def choose_set_drive_count(n_drives: int, n_nodes: int = 1,
                           pinned: int = 0) -> int:
    """Largest candidate that divides the drive count and spreads evenly
    across nodes when possible (cmd/endpoint-ellipses.go:80-150)."""
    if pinned:
        if n_drives % pinned:
            raise ValueError(
                f"set drive count {pinned} does not divide {n_drives} drives")
        return pinned
    if n_drives == 1:
        return 1
    # Prefer sizes that are also multiples of the node count (symmetric
    # spread), then any divisor.
    for require_node_spread in (True, False):
        for c in SET_SIZES:
            if c > n_drives or n_drives % c:
                continue
            if require_node_spread and n_nodes > 1 and c % n_nodes:
                continue
            return c
    raise ValueError(f"no valid erasure set size for {n_drives} drives")


def create_pool_layouts(args_groups: list[list[str]],
                        local_host: str = "", local_port: int = 0,
                        set_drive_count: int = 0,
                        local_names: set[str] | None = None
                        ) -> list[PoolLayout]:
    """Each args group (one server invocation arg) becomes one pool
    (cmd/endpoint-ellipses.go:254)."""
    pools = []
    for group in args_groups:
        expanded = [e for arg in group for e in expand_ellipses(arg)]
        eps = [parse_endpoint(e, local_host, local_port, local_names)
               for e in expanded]
        nodes = {ep.node for ep in eps}
        c = choose_set_drive_count(len(eps), len(nodes), set_drive_count)
        pools.append(PoolLayout(eps, c))
    return pools


def layout_signature(pools: list[PoolLayout]) -> str:
    """Deterministic topology fingerprint for bootstrap verification
    (cmd/bootstrap-peer-server.go:99 compares server config across peers)."""
    import hashlib

    h = hashlib.sha256()
    for p in pools:
        h.update(f"set={p.set_drive_count};".encode())
        for ep in p.endpoints:
            h.update(ep.url.encode())
            h.update(b"|")
        h.update(b"//")
    return h.hexdigest()
