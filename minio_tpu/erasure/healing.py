"""Healing: whole-set reconstruct of damaged/missing shards.

Role-equivalent of the reference's healing plane (cmd/erasure-healing.go:233-498,
cmd/erasure-healing-common.go:103,161, cmd/erasure-lowlevel-heal.go): classify
every drive of the set as ok/offline/missing/outdated/corrupt for an object
version, elect the authoritative metadata by modtime, reconstruct the target
shards for every part, and commit them with the same tmp→rename discipline as
PutObject. Dangling objects (ones that can never reach read quorum again) are
purged.

TPU-first difference: the reference heals shard-by-shard through a Decode→
Encode pipe (erasure-lowlevel-heal.go:28). Here reconstruction is the same
batched GF(2) contraction as GET — all missing shard columns for a batch of
blocks are produced by ONE device launch with decode weights for the failure
pattern, so healing a 4-drives-down set costs one matmul per block batch, not
four passes.

The MRF ("most recently failed") queue mirrors cmd/erasure.go:41-75: partial
writes and corrupt reads enqueue (bucket, object, version) and a background
worker re-heals them.
"""

from __future__ import annotations

import os
import queue
import threading
import uuid
from dataclasses import dataclass, field

from minio_tpu import dataplane, obs
from minio_tpu.erasure.codec import ErasureCodec
from minio_tpu.erasure.metadata import parallel_map, shuffle_by_distribution
from minio_tpu.ops import bitrot
from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.utils import errors as se

# Drive states (reference madmin drive states).
DRIVE_STATE_OK = "ok"
DRIVE_STATE_OFFLINE = "offline"
DRIVE_STATE_MISSING = "missing"
DRIVE_STATE_CORRUPT = "corrupt"
DRIVE_STATE_OUTDATED = "outdated"


@dataclass
class HealDriveState:
    endpoint: str
    state: str


@dataclass
class HealResultItem:
    """Result of one heal operation (reference madmin.HealResultItem)."""

    heal_type: str = "object"
    bucket: str = ""
    object: str = ""
    version_id: str = ""
    object_size: int = 0
    data_blocks: int = 0
    parity_blocks: int = 0
    disk_count: int = 0
    before: list[HealDriveState] = field(default_factory=list)
    after: list[HealDriveState] = field(default_factory=list)
    dry_run: bool = False
    purged: bool = False

    @property
    def healed_count(self) -> int:
        return sum(
            1
            for b, a in zip(self.before, self.after)
            if b.state != DRIVE_STATE_OK and a.state == DRIVE_STATE_OK
        )


def latest_fileinfo(results: list) -> FileInfo | None:
    """Elect the authoritative version: the FileInfo cohort with the newest
    mod_time (reference listOnlineDisks modtime election,
    cmd/erasure-healing-common.go:103). Returns None if no drive has one."""
    valid = [r for r in results if isinstance(r, FileInfo)]
    if not valid:
        return None
    latest_mt = max(fi.mod_time for fi in valid)
    cohort = [fi for fi in valid if fi.mod_time == latest_mt]
    # Prefer an entry carrying erasure geometry (a data-holding drive).
    for fi in cohort:
        if fi.deleted or fi.erasure.data_blocks:
            return fi
    return cohort[0]


def _same_version(fi: FileInfo, latest: FileInfo) -> bool:
    return (
        fi.mod_time == latest.mod_time
        and fi.data_dir == latest.data_dir
        and fi.version_id == latest.version_id
        and fi.deleted == latest.deleted
    )


class _ShardWriterPool:
    """Fan-out writer: one streaming create_file per (target drive, part),
    fed from queues — the healing analogue of PutObject's fan-out."""

    def __init__(self, drives_by_pos: dict[int, object], sys_vol: str, tmp_dirs: dict[int, str]):
        self.sys_vol = sys_vol
        self.tmp_dirs = tmp_dirs
        self.drives = drives_by_pos
        self.queues: dict[int, queue.Queue] = {}
        self.threads: dict[int, threading.Thread] = {}
        self.errs: dict[int, Exception | None] = {pos: None for pos in drives_by_pos}

    def start_part(self, part_number: int) -> None:
        for pos, drive in self.drives.items():
            if self.errs[pos] is not None:
                continue
            q: queue.Queue = queue.Queue(maxsize=4)
            self.queues[pos] = q

            def writer(pos=pos, drive=drive, q=q):
                def gen():
                    while True:
                        chunk = q.get()
                        if chunk is None:
                            return
                        yield chunk

                try:
                    drive.create_file(
                        self.sys_vol, f"{self.tmp_dirs[pos]}/part.{part_number}", gen()
                    )
                except Exception as e:  # noqa: BLE001 - per-drive failure is data
                    self.errs[pos] = e
                    while q.get() is not None:
                        pass

            t = threading.Thread(target=writer, daemon=True)
            self.threads[pos] = t
            t.start()

    def put(self, pos: int, framed: bytes) -> None:
        q = self.queues.get(pos)
        if q is not None:
            q.put(framed)

    def finish_part(self) -> None:
        for q in self.queues.values():
            q.put(None)
        for t in self.threads.values():
            t.join()
        self.queues.clear()
        self.threads.clear()


class HealingMixin:
    """Healing entry points for ErasureObjects (self provides drives, parity,
    codec config, bitrot_algorithm)."""

    # -- bucket heal (reference healBucket, cmd/erasure-healing.go:56) --

    def heal_bucket(self, bucket: str, dry_run: bool = False) -> HealResultItem:
        results = parallel_map([lambda d=d: d.stat_vol(bucket) for d in self.drives],
                               deadline=self._meta_deadline())
        res = HealResultItem(heal_type="bucket", bucket=bucket,
                             disk_count=self.n, dry_run=dry_run)
        have = [not isinstance(r, Exception) for r in results]
        for i, ok in enumerate(have):
            st = DRIVE_STATE_OK if ok else (
                DRIVE_STATE_MISSING
                if isinstance(results[i], se.VolumeNotFound)
                else DRIVE_STATE_OFFLINE
            )
            res.before.append(HealDriveState(self.drives[i].endpoint(), st))
        if not any(have):
            raise se.BucketNotFound(bucket)
        res.after = [HealDriveState(s.endpoint, s.state) for s in res.before]
        if dry_run:
            return res
        for i, ok in enumerate(have):
            if ok or not isinstance(results[i], se.VolumeNotFound):
                continue
            try:
                self.drives[i].make_vol(bucket)
                res.after[i].state = DRIVE_STATE_OK
            except se.VolumeExists:
                res.after[i].state = DRIVE_STATE_OK
            except se.StorageError:
                pass
        # The bucket's metadata doc lives in the mirrored sys store;
        # reading it triggers that store's read-repair, converging copies
        # lost/corrupted while a drive was away. Sets that don't host the
        # deployment's store simply have no doc and resolve FileNotFound.
        try:
            self.read_sys_config(f"buckets/{bucket}/metadata.mp")
        except se.StorageError:
            pass    # no doc (default config) or below quorum
        return res

    # -- object heal (reference healObject, cmd/erasure-healing.go:233) --

    def heal_object(
        self,
        bucket: str,
        obj: str,
        version_id: str = "",
        dry_run: bool = False,
        remove_dangling: bool = True,
        scan_deep: bool = False,
    ) -> HealResultItem:
        # Heal mutates shard files + journal: exclusive per-object lock
        # (reference cmd/erasure-healing.go:252-258).
        with self.nslock.lock(bucket, obj):
            return self._heal_object_locked(
                bucket, obj, version_id, dry_run, remove_dangling, scan_deep)

    def _heal_object_locked(
        self,
        bucket: str,
        obj: str,
        version_id: str = "",
        dry_run: bool = False,
        remove_dangling: bool = True,
        scan_deep: bool = False,
    ) -> HealResultItem:
        results = parallel_map(
            [lambda d=d: d.read_version(bucket, obj, version_id) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        latest = latest_fileinfo(results)
        if latest is None:
            if all(isinstance(r, (se.FileNotFound, se.FileVersionNotFound)) for r in results):
                raise se.ObjectNotFound(bucket, obj)
            raise se.InsufficientReadQuorum(bucket, obj, "no readable metadata")

        if latest.deleted or not latest.erasure.distribution:
            return self._heal_metadata_only(bucket, obj, latest, results, dry_run)
        if (latest.metadata.get("x-mtpu-internal-transition-tier")
                and not latest.data_dir):
            # Transitioned stub: the data's only copy lives on the tier;
            # heal just the metadata quorum, never "reconstruct" (and never
            # purge) what is deliberately absent locally.
            return self._heal_metadata_only(bucket, obj, latest, results, dry_run)

        dist = latest.erasure.distribution
        k = latest.erasure.data_blocks
        n = len(dist)
        shuffled_drives = shuffle_by_distribution(self.drives, dist)
        shuffled_results = shuffle_by_distribution(results, dist)

        states = self._classify(bucket, obj, latest, shuffled_drives,
                                shuffled_results, scan_deep)

        res = HealResultItem(
            bucket=bucket, object=obj, version_id=latest.version_id,
            object_size=latest.size, data_blocks=k,
            parity_blocks=latest.erasure.parity_blocks,
            disk_count=self.n, dry_run=dry_run,
            before=[HealDriveState(d.endpoint(), s) for d, s in zip(shuffled_drives, states)],
        )
        res.after = [HealDriveState(s.endpoint, s.state) for s in res.before]

        avail = [i for i, s in enumerate(states) if s == DRIVE_STATE_OK]
        targets = [i for i, s in enumerate(states)
                   if s in (DRIVE_STATE_MISSING, DRIVE_STATE_CORRUPT, DRIVE_STATE_OUTDATED)]

        if len(avail) < k:
            # Can this object ever be healed? If missing-metadata drives alone
            # exceed parity, no quorum is reachable: dangling
            # (reference isObjectDangling, cmd/erasure-healing.go:758).
            notfound = sum(
                1 for r in results
                if isinstance(r, (se.FileNotFound, se.FileVersionNotFound))
            )
            if notfound > latest.erasure.parity_blocks and remove_dangling:
                if not dry_run:
                    self._purge_dangling(bucket, obj, latest)
                    res.purged = True
                return res
            raise se.InsufficientReadQuorum(
                bucket, obj, f"{len(avail)} of {k} shards available"
            )

        if not targets or dry_run:
            return res

        if latest.inline_data:
            self._heal_write_metadata(bucket, obj, latest, shuffled_drives, targets, res)
            return res

        healed = self._reconstruct_to_targets(
            bucket, obj, latest, shuffled_drives, avail, targets
        )
        for pos in healed:
            res.after[pos].state = DRIVE_STATE_OK
        return res

    # -- classification (reference disksWithAllParts,
    #    cmd/erasure-healing-common.go:161) --

    def _classify(self, bucket, obj, latest, shuffled_drives, shuffled_results,
                  scan_deep) -> list[str]:
        states: list[str] = []
        checks = []
        for pos, (drive, r) in enumerate(zip(shuffled_drives, shuffled_results)):
            if isinstance(r, (se.FileNotFound, se.FileVersionNotFound)):
                states.append(DRIVE_STATE_MISSING)
                checks.append(None)
            elif isinstance(r, (se.FileCorrupt, se.CorruptedFormat)):
                # Unreadable journal (CRC/decode failure) is damage to
                # heal, not an offline drive (reference disksWithAllParts
                # treats errFileCorrupt as heal-needing, never skips it).
                states.append(DRIVE_STATE_CORRUPT)
                checks.append(None)
            elif isinstance(r, Exception):
                states.append(DRIVE_STATE_OFFLINE)
                checks.append(None)
            elif not _same_version(r, latest):
                states.append(DRIVE_STATE_OUTDATED)
                checks.append(None)
            else:
                states.append(DRIVE_STATE_OK)
                if latest.inline_data:
                    checks.append(None)
                elif scan_deep:
                    checks.append(lambda d=drive: d.verify_file(bucket, obj, latest))
                else:
                    checks.append(lambda d=drive: d.check_parts(bucket, obj, latest))
        to_run = [(i, c) for i, c in enumerate(checks) if c is not None]
        outcomes = parallel_map([c for _, c in to_run],
                                deadline=self._data_deadline())
        for (i, _), out in zip(to_run, outcomes):
            if isinstance(out, Exception):
                states[i] = (
                    DRIVE_STATE_CORRUPT
                    if isinstance(out, (se.FileCorrupt, se.FileNotFound))
                    else DRIVE_STATE_OFFLINE
                )
        return states

    # -- reconstruction core --

    def _reconstruct_to_targets(self, bucket, obj, latest, shuffled_drives,
                                avail, targets) -> list[int]:
        """Rebuild every part's shards for the target positions; returns the
        positions successfully healed (committed via rename_data)."""
        k = latest.erasure.data_blocks
        m = latest.erasure.parity_blocks
        n = k + m
        codec = ErasureCodec(k, m, latest.erasure.block_size)
        shard_size = codec.shard_size()
        algo = next((c.algorithm for c in latest.erasure.checksums),
                    self.bitrot_algorithm)
        bitrot_algo = bitrot.get_algorithm(algo)
        sys_vol = ".mtpu.sys"

        # Unique per invocation: concurrent heals of the same object (MRF
        # worker + admin heal) must never share tmp files.
        heal_id = uuid.uuid4().hex
        tmp_dirs = {pos: f"tmp/heal-{heal_id}-{pos}" for pos in targets}
        pool = _ShardWriterPool(
            {pos: shuffled_drives[pos] for pos in targets}, sys_vol, tmp_dirs
        )

        chosen = avail[:k]
        native = self._native_rebuild(bucket, obj, latest, shuffled_drives,
                                      targets, algo, codec, sys_vol,
                                      tmp_dirs)
        if native is not None:
            for pos, err in native.items():
                pool.errs[pos] = err
            return self._commit_healed(bucket, obj, latest, shuffled_drives,
                                       targets, sys_vol, tmp_dirs, pool)
        use_fused = algo == "mxsum256"
        t_tuple = tuple(targets)
        # Batched data plane: a whole-set heal's reconstructs coalesce
        # onto the mixed-failure-pattern lanes (per-row decode matrices
        # ride as data), sharing launches with concurrent heals AND
        # degraded GETs instead of one dispatch per object; the
        # per-object codec path stays the fallback and the oracle.
        plane = dataplane.maybe_plane() if m else None

        def begin_rebuild(rows, block_lens):
            if (plane is not None and block_lens
                    and plane.accepts_recon_chunk(
                        -(-max(block_lens) // k))):
                try:
                    return plane.begin_reconstruct(
                        k, m, latest.erasure.block_size, rows,
                        block_lens, t_tuple, with_digests=use_fused)
                except se.OperationTimedOut:
                    pass  # plane saturated: per-object dispatch serves
            return codec.begin_reconstruct(rows, block_lens, t_tuple,
                                           with_digests=use_fused)

        try:
            for part in latest.parts:
                shard_data_size = latest.erasure.shard_file_size(part.size)
                rel = f"{obj}/{latest.data_dir}/part.{part.number}"
                readers = {}
                for pos in chosen:
                    f = shuffled_drives[pos].read_file_stream(bucket, rel)
                    readers[pos] = bitrot.BitrotReader(f, shard_data_size, shard_size, algo)
                pool.start_part(part.number)
                try:
                    # Dispatch-ahead rebuild pipeline (mirrors the put
                    # path's P2 shape): the host reads batch N+1's shards
                    # while the device rebuilds batch N; rebuilt chunks +
                    # their bitrot digests come out of ONE fused launch
                    # when the algorithm is the device checksum.
                    pending: list = []

                    def drain_one() -> None:
                        chunks_rows, dig_rows = pending.pop(0).wait()
                        for j, chunks in enumerate(chunks_rows):
                            for ti, pos in enumerate(t_tuple):
                                d = (dig_rows[j][ti] if dig_rows is not None
                                     else bitrot_algo.digest(chunks[ti]))
                                pool.put(pos, d + chunks[ti])

                    n_blocks = max(1, -(-part.size // latest.erasure.block_size))
                    bi = 0
                    while bi < n_blocks:
                        batch_ids = list(range(bi, min(bi + self.batch_blocks, n_blocks)))
                        block_lens = [
                            min(latest.erasure.block_size,
                                part.size - b * latest.erasure.block_size)
                            for b in batch_ids
                        ]
                        rows = []
                        for j, b in enumerate(batch_ids):
                            chunk_len = -(-block_lens[j] // k)
                            row: list[bytes | None] = [None] * n
                            for pos in chosen:
                                row[pos] = readers[pos].read_at(b * shard_size, chunk_len)
                            rows.append(row)
                        pending.append(begin_rebuild(rows, block_lens))
                        if len(pending) >= 2:
                            drain_one()
                        bi = batch_ids[-1] + 1
                    while pending:
                        drain_one()
                finally:
                    for r in readers.values():
                        try:
                            r.src.close()
                        except Exception:  # noqa: BLE001
                            pass
                    pool.finish_part()
        except Exception:
            for pos in targets:
                try:
                    shuffled_drives[pos].delete(sys_vol, tmp_dirs[pos], recursive=True)
                except se.StorageError:
                    pass
            raise

        return self._commit_healed(bucket, obj, latest, shuffled_drives,
                                   targets, sys_vol, tmp_dirs, pool)

    def _commit_healed(self, bucket, obj, latest, shuffled_drives, targets,
                       sys_vol, tmp_dirs, pool) -> list[int]:
        # Heal rewrites journals out from under any cached election.
        self._meta_invalidate(bucket, obj)
        healed = []
        for pos in targets:
            if pool.errs[pos] is not None:
                continue
            fi = _clone_fi(latest, pos + 1)
            try:
                shuffled_drives[pos].rename_data(sys_vol, tmp_dirs[pos], fi, bucket, obj)
                healed.append(pos)
            except se.StorageError:
                try:
                    shuffled_drives[pos].delete(sys_vol, tmp_dirs[pos], recursive=True)
                except se.StorageError:
                    pass
        return healed

    def _native_rebuild(self, bucket, obj, latest, shuffled_drives, targets,
                        algo, codec, sys_vol, tmp_dirs
                        ) -> dict[int, Exception | None] | None:
        """Native heal lane: the GET-path C decoder reads + bitrot-verifies
        + reconstructs each part windowed, and the PUT-path C encoder —
        with every HEALTHY drive pre-failed — re-frames and writes ONLY the
        target positions' shard files into the heal tmp dirs. Same commit
        (rename_data) as the Python lane. Returns per-target errors, or
        None to fall through when the topology/algorithm doesn't qualify
        (remote drives, device-fused digests, odd block size)."""
        from minio_tpu.erasure.objects import _local_shard_paths
        from minio_tpu.native import plane

        if (algo not in ("sip256", "highwayhash256")
                or not plane.available() or codec.block_size % 64):
            return None
        k, m = codec.k, codec.m
        n = k + m
        errs: dict[int, Exception | None] = {pos: None for pos in targets}
        # Small enough windows that the 1-deep pipeline genuinely
        # overlaps: with one giant window, decode and the encoder's
        # write-back serialize and heal runs at decode+write instead of
        # max(decode, write) (reference erasure-lowlevel-heal.go pipes
        # the decode straight into the encode).
        win = plane.pipeline_window_blocks(codec.block_size) \
            * codec.block_size
        from minio_tpu.storage.healthcheck import unwrap as _unwrap_drive

        for part in latest.parts:
            rel = f"{obj}/{latest.data_dir}/part.{part.number}"
            src_paths = _local_shard_paths(shuffled_drives, bucket, rel)
            if src_paths is None:
                return None
            dst_paths = []
            for pos in range(n):
                d = shuffled_drives[pos]
                base = _unwrap_drive(d)
                # Non-target positions are pre-failed below; the C writer
                # skips a failed drive before ever opening its path, so
                # the placeholder is never touched.
                dst_paths.append(base._file_path(
                    sys_vol, f"{tmp_dirs[pos]}/part.{part.number}")
                    if pos in errs else "/dev/null")
            try:
                enc = plane.PartEncoder(dst_paths, k, m, codec.block_size,
                                        algorithm=algo, compute_md5=False)
                for pos in range(n):
                    # Pre-fail non-targets AND targets already lost on an
                    # earlier part — no point re-framing onto a dead tmp.
                    if pos not in errs or errs[pos] is not None:
                        enc.fail_drive(pos)
                    else:
                        os.makedirs(os.path.dirname(dst_paths[pos]),
                                    exist_ok=True)
                if part.size == 0:
                    enc.feed(b"", final=True)
                # 1-deep pipeline: decode window N+1 while the encoder
                # writes window N (same overlap shape as the PUT lane).
                # Dead shards found by one window (<0 states) feed the
                # next window's skip set so they aren't re-read/re-hashed.
                from concurrent.futures import ThreadPoolExecutor

                dead: set[int] = set()
                with ThreadPoolExecutor(
                        1, thread_name_prefix="native-heal") as ex:
                    fut = None
                    off = 0
                    while off < part.size:
                        ln = min(win, part.size - off)
                        out, states = plane.decode_range(
                            src_paths, k, m, codec.block_size, part.size,
                            off, ln, algorithm=algo, skip=dead)
                        if out is None:
                            # Fewer than k shards served this window:
                            # the Python lane has finer-grained survivor
                            # fallback. Settle the in-flight write first.
                            if fut is not None:
                                fut.result()
                            return None
                        dead.update(
                            i for i, s in enumerate(states) if s < 0)
                        if fut is not None:
                            fut.result()
                        fut = ex.submit(obs.ctx_wrap(enc.feed), out,
                                        off + ln >= part.size)
                        off += ln
                    if fut is not None:
                        fut.result()
            except OSError:
                # Decode window failed (IO error mid-stream): let the
                # Python lane decide.
                return None
            for pos in errs:
                if enc.errors[pos]:
                    errs[pos] = se.FaultyDisk(
                        f"native heal write failed: {dst_paths[pos]}")
        return errs

    # -- metadata-only heals (delete markers, inline objects) --

    def _heal_metadata_only(self, bucket, obj, latest, results, dry_run) -> HealResultItem:
        res = HealResultItem(
            bucket=bucket, object=obj, version_id=latest.version_id,
            object_size=latest.size, disk_count=self.n, dry_run=dry_run,
        )
        targets = []
        for i, r in enumerate(results):
            if isinstance(r, FileInfo) and _same_version(r, latest):
                st = DRIVE_STATE_OK
            elif isinstance(r, (se.FileNotFound, se.FileVersionNotFound)) or isinstance(
                r, FileInfo
            ):
                st = DRIVE_STATE_MISSING
                targets.append(i)
            else:
                st = DRIVE_STATE_OFFLINE
            res.before.append(HealDriveState(self.drives[i].endpoint(), st))
        res.after = [HealDriveState(s.endpoint, s.state) for s in res.before]
        if dry_run:
            return res
        self._heal_write_metadata(bucket, obj, latest, self.drives, targets, res,
                                  positions_are_physical=True)
        return res

    def _heal_write_metadata(self, bucket, obj, latest, drives, targets, res,
                             positions_are_physical=False):
        self._meta_invalidate(bucket, obj)

        def write(pos):
            fi = _clone_fi(latest, 0 if positions_are_physical else pos + 1)
            if latest.deleted:
                drives[pos].delete_version(bucket, obj, fi)
            else:
                drives[pos].write_metadata(bucket, obj, fi)

        outcomes = parallel_map([lambda p=p: write(p) for p in targets],
                                deadline=self._meta_deadline())
        for pos, out in zip(targets, outcomes):
            if not isinstance(out, Exception):
                res.after[pos].state = DRIVE_STATE_OK

    def heal_objects(self, bucket: str, prefix: str = "", **kw):
        """Walk every object under prefix and heal it (reference HealObjects
        walk, cmd/erasure-server-pool.go:1500) — streamed, O(page) memory
        even over a multi-million-object bucket."""
        for name, _meta in self.stream_journals(bucket, prefix):
            try:
                yield self.heal_object(bucket, name, **kw)
            except se.ObjectError as e:
                yield e

    # -- dangling purge (reference purgeObjectDangling,
    #    cmd/erasure-healing.go:700) --

    def _purge_dangling(self, bucket: str, obj: str, latest: FileInfo) -> None:
        target = FileInfo(volume=bucket, name=obj, version_id=latest.version_id,
                          data_dir=latest.data_dir)
        parallel_map(
            [lambda d=d: d.delete_version(bucket, obj, target) for d in self.drives],
            deadline=self._meta_deadline(),
        )


MRF_RETRY_INTERVAL = float(os.environ.get("MTPU_MRF_RETRY_INTERVAL", "1.0"))
MRF_RETRY_MAX = int(os.environ.get("MTPU_MRF_RETRY_MAX", "600"))
MRF_RETRY_CAP = float(os.environ.get("MTPU_MRF_RETRY_CAP", "60.0"))

_MRF_REQUEUES = obs.counter(
    "minio_tpu_mrf_requeues_total",
    "MRF heals requeued because target drives were still offline")


class MRFHealer:
    """Most-recently-failed heal queue (reference mrfOpCh, cmd/erasure.go:41-75):
    partial writes and corrupt reads enqueue here; a background worker retries
    the heal out of band.

    Partition-aware: a heal attempted while the missing shards' drives are
    still unreachable (peer breaker OPEN / mid-partition) classifies them
    OFFLINE and rebuilds nothing — such entries are REQUEUED with an
    exponentially backed-off delay (base `MTPU_MRF_RETRY_INTERVAL`, cap
    `MTPU_MRF_RETRY_CAP`, at most `MTPU_MRF_RETRY_MAX` attempts) instead
    of retired, so a degraded write's missed shards reliably drain once
    the partition heals while a permanently dead drive cannot keep the
    drain thread busy-spinning. Unhealable states (object deleted) drop."""

    def __init__(self, er, maxsize: int = 10000):
        self.er = er
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._seen_lock = threading.Lock()
        # (bucket, obj, version_id) -> deep flag; a deep request upgrades
        # a pending shallow one in place (one heal pass, not two).
        self._pending: dict[tuple[str, str, str], bool] = {}
        self._attempts: dict[tuple[str, str, str], int] = {}
        # Key currently being healed. Kept OUT of _pending so an
        # add_partial racing the in-flight heal re-queues (the running
        # heal read its metadata before the new damage) — but still
        # counted by wait_idle.
        self._inflight: set[tuple[str, str, str]] = set()
        # Deferred re-heals: [(due_monotonic, key, deep)] — fed back to
        # _pending/queue at their due time; wait_idle blocks on them.
        self._retry: list[tuple[float, tuple[str, str, str], bool]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def add_partial(self, bucket: str, obj: str, version_id: str = "",
                    deep: bool = False) -> None:
        """deep=True when the caller OBSERVED bitrot (a corrupt read): the
        background heal then bitrot-verifies every shard, so in-place
        corruption is rebuilt rather than passed over by the presence-only
        normal scan."""
        key = (bucket, obj, version_id)
        with self._seen_lock:
            if key in self._pending:
                if deep:
                    self._pending[key] = True  # upgrade the queued heal
                return
            self._pending[key] = deep
        try:
            self.q.put_nowait(key)
        except queue.Full:
            with self._seen_lock:
                self._pending.pop(key, None)

    def _pump_due_retries(self) -> None:
        import time as _time

        now = _time.monotonic()
        with self._seen_lock:
            due = [(k, d) for t, k, d in self._retry if t <= now]
            self._retry = [e for e in self._retry if e[0] > now]
            # Re-enter through _pending so a racing add_partial
            # coalesces exactly as for a first-time enqueue; a retry
            # carrying deep=True UPGRADES an already-pending shallow
            # entry (the observed corruption must not be forgotten).
            to_queue = []
            for k, d in due:
                if k in self._pending:
                    if d:
                        self._pending[k] = True
                else:
                    self._pending[k] = d
                    to_queue.append((k, d))
            due = to_queue
        for key, _deep in due:
            try:
                self.q.put_nowait(key)
            except queue.Full:
                with self._seen_lock:
                    self._pending.pop(key, None)
                    self._attempts.pop(key, None)

    def _drain(self) -> None:
        import time as _time

        while not self._stop.is_set():
            self._pump_due_retries()
            try:
                key = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            bucket, obj, version_id = key
            # Pop-before-heal (so damage arriving DURING the heal
            # re-queues — this attempt read its metadata first), but
            # track the in-flight key so wait_idle keeps blocking.
            with self._seen_lock:
                deep = self._pending.pop(key, False)
                self._inflight.add(key)
            requeue = False
            try:
                res = self.er.heal_object(bucket, obj, version_id,
                                          scan_deep=deep)
                # Drives unreachable during the attempt (mid-partition /
                # OPEN peer breaker) classify OFFLINE and got nothing
                # rebuilt: the entry is NOT drained yet.
                requeue = any(s.state == DRIVE_STATE_OFFLINE
                              for s in (res.after or res.before or []))
            except (se.ObjectNotFound, se.FileNotFound,
                    se.FileVersionNotFound):
                pass  # deleted since: nothing left to heal
            except Exception:  # noqa: BLE001 - transient (quorum/transport)
                requeue = True
            with self._seen_lock:
                self._inflight.discard(key)
                self._attempts[key] = attempts = self._attempts.get(key, 0) + 1
                if (requeue and attempts < MRF_RETRY_MAX
                        and key not in self._pending):
                    # (a concurrent add_partial already re-queued it —
                    # that entry covers this retry.) Jittered exponential
                    # backoff: a partition drains at near-base cadence
                    # (few attempts), while a permanently dead drive —
                    # which keeps every heal of its set partial — settles
                    # to one cheap attempt per MRF_RETRY_CAP instead of
                    # hammering a full heal pass per object per interval.
                    delay = min(MRF_RETRY_INTERVAL * (2 ** (attempts - 1)),
                                max(MRF_RETRY_INTERVAL, MRF_RETRY_CAP))
                    self._retry.append(
                        (_time.monotonic() + delay, key, deep))
                    _MRF_REQUEUES.labels().inc()
                elif requeue and key in self._pending:
                    # A concurrent add_partial re-queued the key — that
                    # entry covers this retry, but it must not downgrade
                    # an observed-bitrot deep heal to shallow.
                    self._pending[key] = self._pending[key] or deep
                elif key not in self._pending:
                    # Episode over — drained, unhealable, or budget
                    # exhausted. Reset the counter either way so a
                    # FUTURE degraded write to this object gets a fresh
                    # retry budget (and the dict cannot grow unbounded).
                    self._attempts.pop(key, None)
            self.q.task_done()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Testing hook: block until the queue drains (in-flight and
        requeued entries count until their heal actually completes)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._seen_lock:
                if (not self._pending and not self._retry
                        and not self._inflight and self.q.empty()):
                    return True
            _time.sleep(0.01)
        return False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


def _clone_fi(fi: FileInfo, index: int) -> FileInfo:
    out = fi.clone()
    out.erasure.index = index
    return out
