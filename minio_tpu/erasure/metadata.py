"""Erasure metadata helpers: deterministic drive ordering + quorum election.

Reference: hashOrder (cmd/erasure-metadata-utils.go:100), readAllFileInfo
(:118), pickValidFileInfo / findFileInfoInQuorum (cmd/erasure-metadata.go),
listOnlineDisks modtime election (cmd/erasure-healing-common.go:103).
"""

from __future__ import annotations

import hashlib
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.utils import errors as se


def hash_order(key: str, card: int) -> list[int]:
    """Deterministic 1-based drive ordering for an object key: a rotation of
    [1..card] starting at a key-derived index. Same role as the reference's
    crc-based hashOrder (cmd/erasure-metadata-utils.go:100) — it fixes which
    drive holds shard 1, 2, ... so readers and writers agree without
    coordination. We key it with blake2b for better dispersion."""
    if card <= 0:
        return []
    seed = int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )
    start = seed % card
    return [(start + i) % card + 1 for i in range(card)]


def shuffle_by_distribution(items: Sequence, distribution: Sequence[int]) -> list:
    """Arrange items so result[shard_index-1] = the drive that holds that
    shard: distribution[i] is the 1-based shard index of physical drive i
    (cmd/erasure-metadata-utils.go:148-210)."""
    out = [None] * len(items)
    for physical, shard_idx in enumerate(distribution):
        out[shard_idx - 1] = items[physical]
    return out


_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = __import__("threading").Lock()


def _shared_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = ThreadPoolExecutor(max_workers=64,
                                           thread_name_prefix="mtpu-io")
    return _POOL


def parallel_map(fns: Sequence[Callable], max_workers: int | None = None,
                 serial: bool = False) -> list:
    """Run per-drive closures concurrently, capturing exceptions as values
    (the reference's errgroup-with-indexed-errors pattern, pkg/sync).

    Uses one process-wide pool: spawning a fresh ThreadPoolExecutor per call
    cost ~1-2 ms of thread create+join, which dominated the small-object
    request path. Nested calls can't deadlock on the shared pool because the
    caller steals any task the pool hasn't started (cancel-or-run-inline):
    the calling thread only ever blocks on closures already RUNNING in a
    worker, and the nesting structure is a tree, so some leaf always runs."""
    results: list = [None] * len(fns)

    def run(i):
        try:
            results[i] = fns[i]()
        except Exception as e:  # noqa: BLE001 - per-drive errors are data
            results[i] = e

    if serial or len(fns) <= 1:
        # Callers pass serial=True when every closure is a known-cheap
        # local operation (e.g. cached journal reads on an all-local set):
        # there the pool dispatch costs more than the work.
        for i in range(len(fns)):
            run(i)
        return results
    pool = _shared_pool()
    futs = [pool.submit(run, i) for i in range(len(fns))]
    for i, f in enumerate(futs):
        if f.cancel():
            run(i)
        else:
            f.result()
    return results


def election_sig(fi: FileInfo) -> tuple:
    """The quorum election signature: drives agreeing on this tuple hold
    the same logical version (findFileInfoInQuorum's comparison key,
    cmd/erasure-metadata.go:124-155). ONE definition — the serial
    early-exit read path and the full election must never diverge."""
    return (round(fi.mod_time, 6), fi.data_dir, fi.version_id, fi.deleted)


def find_fileinfo_in_quorum(fis: Sequence[object], quorum: int,
                            bucket: str, obj: str) -> FileInfo:
    """Elect the authoritative FileInfo: at least `quorum` drives must agree
    on (mod_time, data_dir, version). Reference findFileInfoInQuorum
    (cmd/erasure-metadata.go:124-155)."""
    sig = election_sig
    counter = Counter(sig(fi) for fi in fis if isinstance(fi, FileInfo))
    if counter:
        best, count = counter.most_common(1)[0]
        if count >= quorum:
            for fi in fis:
                if isinstance(fi, FileInfo) and sig(fi) == best:
                    return fi
    err, count = _dominant_error(fis)
    if err is not None and count >= quorum:
        raise err
    raise se.InsufficientReadQuorum(bucket, obj, f"metadata quorum {quorum} not met")


def _dominant_error(results: Sequence[object]):
    errs = [r for r in results if isinstance(r, Exception)]
    if not errs:
        return None, 0
    name, count = Counter(type(e).__name__ for e in errs).most_common(1)[0]
    for e in errs:
        if type(e).__name__ == name:
            return e, count
    return None, 0
