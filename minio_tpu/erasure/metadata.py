"""Erasure metadata helpers: deterministic drive ordering + quorum election.

Reference: hashOrder (cmd/erasure-metadata-utils.go:100), readAllFileInfo
(:118), pickValidFileInfo / findFileInfoInQuorum (cmd/erasure-metadata.go),
listOnlineDisks modtime election (cmd/erasure-healing-common.go:103).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Callable, Sequence

from minio_tpu import obs
from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.utils import errors as se


def hash_order(key: str, card: int) -> list[int]:
    """Deterministic 1-based drive ordering for an object key: a rotation of
    [1..card] starting at a key-derived index. Same role as the reference's
    crc-based hashOrder (cmd/erasure-metadata-utils.go:100) — it fixes which
    drive holds shard 1, 2, ... so readers and writers agree without
    coordination. We key it with blake2b for better dispersion."""
    if card <= 0:
        return []
    seed = int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )
    start = seed % card
    return [(start + i) % card + 1 for i in range(card)]


def shuffle_by_distribution(items: Sequence, distribution: Sequence[int]) -> list:
    """Arrange items so result[shard_index-1] = the drive that holds that
    shard: distribution[i] is the 1-based shard index of physical drive i
    (cmd/erasure-metadata-utils.go:148-210)."""
    out = [None] * len(items)
    for physical, shard_idx in enumerate(distribution):
        out[shard_idx - 1] = items[physical]
    return out


_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = threading.Lock()


def _shared_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = ThreadPoolExecutor(max_workers=64,
                                           thread_name_prefix="mtpu-io")
    return _POOL


_HUNG_WORKERS = obs.counter(
    "minio_tpu_hung_workers_total",
    "Worker threads abandoned on a hung drive op (pool capacity refilled)")


def note_leaked_worker(pool=None, fut=None) -> None:
    """Account a worker thread abandoned inside a hung drive op and, when
    the worker came from a pool, refill the pool's capacity so the leak
    never starves healthy drives of concurrency. The leaked thread stays
    blocked until the syscall returns (if ever); it is a daemon.

    Pass the abandoned future as `fut` so the refill is RETURNED when the
    straggler eventually finishes (its worker goes back to the pool) —
    without that, a persistently slow drive would ratchet the pool's
    concurrency cap upward forever."""
    _HUNG_WORKERS.labels().inc()
    if pool is None:
        return
    with _POOL_LOCK:
        try:
            pool._max_workers += 1
        except Exception:  # noqa: BLE001 - best-effort refill
            return
    if fut is not None:
        def _returned(_f, pool=pool):
            with _POOL_LOCK:
                try:
                    if pool._max_workers > 1:
                        pool._max_workers -= 1
                except Exception:  # noqa: BLE001
                    pass

        fut.add_done_callback(_returned)


def run_bounded(fn: Callable, deadline: float) -> bool:
    """Run fn() in a shared-pool worker and wait at most `deadline`
    seconds. True when it completed; False when it is still running (the
    worker is abandoned and accounted, the pool refilled) — callers fall
    back to a deadline'd parallel path. The escape hatch for serial
    fast-path loops that could otherwise wedge on one hung drive.

    Called FROM a shared-pool worker (nested fan-out), fn runs inline
    instead: stacking bounded futures from inside the pool could starve
    it under load, and the outer layer already carries a deadline."""
    if threading.current_thread().name.startswith("mtpu-io"):
        fn()
        return True
    pool = _shared_pool()
    fut = pool.submit(obs.ctx_wrap(fn))
    try:
        fut.result(timeout=deadline)
        return True
    except FutureTimeout:
        if not fut.running() and not fut.done():
            # Still queued: the pool is saturated, not the drive — one
            # bounded grace window before giving up (total 2x deadline).
            try:
                fut.result(timeout=deadline)
                return True
            except FutureTimeout:
                pass
        if not fut.cancel():
            note_leaked_worker(pool, fut)
        return False


def parallel_map(fns: Sequence[Callable], max_workers: int | None = None,
                 serial: bool = False, deadline: float | None = None) -> list:
    """Run per-drive closures concurrently, capturing exceptions as values
    (the reference's errgroup-with-indexed-errors pattern, pkg/sync).

    Uses one process-wide pool: spawning a fresh ThreadPoolExecutor per call
    cost ~1-2 ms of thread create+join, which dominated the small-object
    request path. Nested calls can't deadlock on the shared pool because the
    caller steals any task the pool hasn't started (cancel-or-run-inline):
    the calling thread only ever blocks on closures already RUNNING in a
    worker, and the nesting structure is a tree, so some leaf always runs.

    deadline: overall seconds for the WHOLE fan-out. Stragglers still
    running at the deadline become se.OperationTimedOut result values —
    the quorum reducers then treat a hung drive exactly like a failed one.
    The abandoned worker is accounted and the shared pool refilled until
    the straggler returns (note_leaked_worker); a straggler that finishes
    later can never overwrite its slot. Closures still QUEUED at the
    deadline (pool saturated by nested fan-outs, not a hung drive) get
    ONE bounded grace window — total wait 2x deadline — before they too
    are stamped timed out; an unbounded inline steal could wedge the
    caller on a drive that hung while its closure sat in the queue.
    With serial, the whole loop runs in one bounded worker."""
    results: list = [None] * len(fns)

    if serial or len(fns) <= 1:
        # Callers pass serial=True when every closure is a known-cheap
        # local operation (e.g. cached journal reads on an all-local set):
        # there the pool dispatch costs more than the work. With a
        # deadline the loop runs in ONE pool worker (a single dispatch,
        # not one per drive) so a hung closure can't wedge the caller:
        # slots the loop never filled are stamped OperationTimedOut.
        if deadline is None:
            for i in range(len(fns)):
                try:
                    results[i] = fns[i]()
                except Exception as e:  # noqa: BLE001 - per-drive data
                    results[i] = e
            return results
        mu = threading.Lock()
        filled = [False] * len(fns)

        def run_serial():
            for i in range(len(fns)):
                try:
                    r = fns[i]()
                except Exception as e:  # noqa: BLE001 - per-drive data
                    r = e
                with mu:
                    if filled[i]:
                        return  # caller stamped the loop dead: stop
                    results[i] = r
                    filled[i] = True

        pool = _shared_pool()
        fut = pool.submit(obs.ctx_wrap(run_serial))
        try:
            fut.result(timeout=deadline)
        except FutureTimeout:
            if not fut.running() and not fut.done():
                # Still queued: the pool is saturated by nested fan-outs,
                # not a hung drive — one bounded grace window (total 2x
                # deadline) instead of an unbounded inline steal, which
                # could wedge the caller on a drive that hung while
                # queued.
                try:
                    fut.result(timeout=deadline)
                    return results
                except FutureTimeout:
                    pass
            if not fut.cancel():
                note_leaked_worker(pool, fut)
            with mu:
                for i in range(len(fns)):
                    if not filled[i]:
                        filled[i] = True  # blocks a late write
                        results[i] = se.OperationTimedOut(
                            msg=f"drive op exceeded {deadline:.2f}s "
                                "deadline (serial fan-out)")
        return results

    pool = _shared_pool()

    if deadline is None:
        def run(i):
            try:
                results[i] = fns[i]()
            except Exception as e:  # noqa: BLE001 - per-drive errors are data
                results[i] = e

        # ctx_wrap per submission: pool workers don't inherit contextvars,
        # and the per-drive closures emit trace records that must keep the
        # caller's trace id (each wrap holds its own context copy, so the
        # futures can run concurrently).
        futs = [pool.submit(obs.ctx_wrap(run), i) for i in range(len(fns))]
        for i, f in enumerate(futs):
            if f.cancel():
                run(i)
            else:
                f.result()
        return results

    # Deadline'd fan-out: the abandon handshake must be raceless — once a
    # slot is stamped OperationTimedOut, the late-finishing closure drops
    # its result instead of mutating a list the reducers already read.
    mu = threading.Lock()
    abandoned = [False] * len(fns)

    def run_guarded(i):
        try:
            r = fns[i]()
        except Exception as e:  # noqa: BLE001 - per-drive errors are data
            r = e
        with mu:
            if not abandoned[i]:
                results[i] = r

    futs = [pool.submit(obs.ctx_wrap(run_guarded), i)
            for i in range(len(fns))]
    end = time.monotonic() + deadline
    # Closures still QUEUED at the deadline get one shared grace window
    # (total 2x deadline): a saturated pool is not a hung drive, but an
    # unbounded inline steal could wedge the caller on a drive that hung
    # while its closure sat in the queue.
    grace_end = end + deadline
    for i, f in enumerate(futs):
        try:
            f.result(timeout=max(0.0, end - time.monotonic()))
            continue
        except FutureTimeout:
            pass
        if not f.running() and not f.done():
            try:
                f.result(timeout=max(0.0, grace_end - time.monotonic()))
                continue
            except FutureTimeout:
                pass
        with mu:
            abandoned[i] = True
            results[i] = se.OperationTimedOut(
                msg=f"drive op exceeded {deadline:.2f}s deadline")
        if not f.cancel():
            note_leaked_worker(pool, f)
    return results


def election_sig(fi: FileInfo) -> tuple:
    """The quorum election signature: drives agreeing on this tuple hold
    the same logical version (findFileInfoInQuorum's comparison key,
    cmd/erasure-metadata.go:124-155). ONE definition — the serial
    early-exit read path and the full election must never diverge."""
    return (round(fi.mod_time, 6), fi.data_dir, fi.version_id, fi.deleted)


def find_fileinfo_in_quorum(fis: Sequence[object], quorum: int,
                            bucket: str, obj: str) -> FileInfo:
    """Elect the authoritative FileInfo: at least `quorum` drives must agree
    on (mod_time, data_dir, version). Reference findFileInfoInQuorum
    (cmd/erasure-metadata.go:124-155)."""
    sig = election_sig
    counter = Counter(sig(fi) for fi in fis if isinstance(fi, FileInfo))
    if counter:
        best, count = counter.most_common(1)[0]
        if count >= quorum:
            for fi in fis:
                if isinstance(fi, FileInfo) and sig(fi) == best:
                    return fi
    err, count = _dominant_error(fis)
    if err is not None and count >= quorum:
        raise err
    raise se.InsufficientReadQuorum(bucket, obj, f"metadata quorum {quorum} not met")


def _dominant_error(results: Sequence[object]):
    errs = [r for r in results if isinstance(r, Exception)]
    if not errs:
        return None, 0
    name, count = Counter(type(e).__name__ for e in errs).most_common(1)[0]
    for e in errs:
        if type(e).__name__ == name:
            return e, count
    return None, 0
