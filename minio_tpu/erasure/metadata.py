"""Erasure metadata helpers: deterministic drive ordering + quorum election.

Reference: hashOrder (cmd/erasure-metadata-utils.go:100), readAllFileInfo
(:118), pickValidFileInfo / findFileInfoInQuorum (cmd/erasure-metadata.go),
listOnlineDisks modtime election (cmd/erasure-healing-common.go:103).
"""

from __future__ import annotations

import hashlib
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.utils import errors as se


def hash_order(key: str, card: int) -> list[int]:
    """Deterministic 1-based drive ordering for an object key: a rotation of
    [1..card] starting at a key-derived index. Same role as the reference's
    crc-based hashOrder (cmd/erasure-metadata-utils.go:100) — it fixes which
    drive holds shard 1, 2, ... so readers and writers agree without
    coordination. We key it with blake2b for better dispersion."""
    if card <= 0:
        return []
    seed = int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )
    start = seed % card
    return [(start + i) % card + 1 for i in range(card)]


def shuffle_by_distribution(items: Sequence, distribution: Sequence[int]) -> list:
    """Arrange items so result[shard_index-1] = the drive that holds that
    shard: distribution[i] is the 1-based shard index of physical drive i
    (cmd/erasure-metadata-utils.go:148-210)."""
    out = [None] * len(items)
    for physical, shard_idx in enumerate(distribution):
        out[shard_idx - 1] = items[physical]
    return out


def parallel_map(fns: Sequence[Callable], max_workers: int | None = None) -> list:
    """Run per-drive closures concurrently, capturing exceptions as values
    (the reference's errgroup-with-indexed-errors pattern, pkg/sync)."""
    results: list = [None] * len(fns)

    def run(i):
        try:
            results[i] = fns[i]()
        except Exception as e:  # noqa: BLE001 - per-drive errors are data
            results[i] = e

    with ThreadPoolExecutor(max_workers=max_workers or max(4, len(fns))) as ex:
        list(ex.map(run, range(len(fns))))
    return results


def find_fileinfo_in_quorum(fis: Sequence[object], quorum: int,
                            bucket: str, obj: str) -> FileInfo:
    """Elect the authoritative FileInfo: at least `quorum` drives must agree
    on (mod_time, data_dir, version). Reference findFileInfoInQuorum
    (cmd/erasure-metadata.go:124-155)."""
    def sig(fi: FileInfo):
        return (round(fi.mod_time, 6), fi.data_dir, fi.version_id, fi.deleted)

    counter = Counter(sig(fi) for fi in fis if isinstance(fi, FileInfo))
    if counter:
        best, count = counter.most_common(1)[0]
        if count >= quorum:
            for fi in fis:
                if isinstance(fi, FileInfo) and sig(fi) == best:
                    return fi
    err, count = _dominant_error(fis)
    if err is not None and count >= quorum:
        raise err
    raise se.InsufficientReadQuorum(bucket, obj, f"metadata quorum {quorum} not met")


def _dominant_error(results: Sequence[object]):
    errs = [r for r in results if isinstance(r, Exception)]
    if not errs:
        return None, 0
    name, count = Counter(type(e).__name__ for e in errs).most_common(1)[0]
    for e in errs:
        if type(e).__name__ == name:
            return e, count
    return None, 0
