"""ErasureObjects — one erasure set: the core object engine.

Role-equivalent of erasureObjects (cmd/erasure.go:49, cmd/erasure-object.go):
PutObject streams blocks through the batched TPU codec and fans bitrot-framed
shards out to drives with write-quorum accounting; GetObject elects metadata
by quorum, reads any-k shards (data-first), and reconstructs through the
codec only when shards are missing; deletes and tagging follow the same
quorum discipline.

Differences from the reference are deliberate TPU-first design:
- blocks are encoded in batches (default 16 x 1 MiB per device launch,
  dispatch-ahead depth 3)
  rather than block-at-a-time (cmd/erasure-encode.go:80);
- reconstruction groups blocks by failure pattern into single batched
  launches (cmd/erasure-decode.go reconstructs per block);
- drive fan-out is a thread pool feeding streaming create_file generators
  (the io.Pipe + goroutine pattern, cmd/erasure-encode.go:36, collapsed
  into queues).
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
import uuid
from concurrent.futures import TimeoutError as _FutTimeout
from typing import BinaryIO, Iterator

from minio_tpu import dataplane, hottier, metaplane, obs
from minio_tpu.obs import flight
from minio_tpu.erasure.codec import DEFAULT_BLOCK_SIZE, ErasureCodec
from minio_tpu.erasure import listing
from minio_tpu.erasure.sysstore import SysConfigStore
from minio_tpu.erasure.healing import HealingMixin, MRFHealer
from minio_tpu.erasure.multipart import MultipartMixin
from minio_tpu.erasure.metadata import (
    election_sig,
    find_fileinfo_in_quorum,
    hash_order,
    note_leaked_worker,
    parallel_map,
    run_bounded,
    shuffle_by_distribution,
)
from minio_tpu.storage import healthcheck as _health
from minio_tpu.erasure.types import (
    BucketInfo,
    DeletedObject,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    ObjectOptions,
    ObjectToDelete,
)
from minio_tpu.ops import bitrot
from minio_tpu.storage.api import StorageAPI
from minio_tpu.storage.fileinfo import ChecksumInfo, ErasureInfo, FileInfo, PartInfo
from minio_tpu.storage.xlmeta import XLMeta
from minio_tpu.utils import errors as se
from minio_tpu.utils.quorum import reduce_write_quorum

_WRITE_SENTINEL = None

# Objects at or below this size are inlined into the journal instead of
# getting shard files (reference inlines small objects in xl.meta v2).
INLINE_DATA_LIMIT = 16 << 10

# Rolling erasure-encode throughput, EWMA over per-fan-out bytes/wall —
# the live counterpart of PERF.md's hand-run encode benchmarks.
_ENCODE_GIBPS = obs.gauge(
    "minio_tpu_encode_gibps",
    "Rolling erasure encode+fan-out throughput in GiB/s (EWMA)")

# Tail-latency hedging on shard reads (first-k-wins): launched spares and
# how many of them beat the straggler they covered for.
_HEDGED_READS = obs.counter(
    "minio_tpu_hedged_reads_total",
    "Spare shard reads launched after the hedge delay").labels()
_HEDGED_WINS = obs.counter(
    "minio_tpu_hedged_reads_won_total",
    "Hedged shard reads that made quorum before the straggler").labels()

# Shared with cache/disk.py (the registry dedupes by family name):
# latest-only caches — the disk cache and the HBM hot tier — bypass
# explicitly-versioned reads and account them here instead of
# miscounting them as misses (docs/METRICS.md).
_CACHE_BYPASS = obs.counter(
    "minio_tpu_cache_bypass_total",
    "Reads that bypassed a latest-only cache tier by contract",
    ("reason",))


def _read_full(data: BinaryIO, n: int) -> bytes:
    """Read exactly n bytes unless EOF — short read()s are legal for
    sockets/pipes and must not skew the fixed-block erasure layout.

    Fast path: most sources (BytesIO, spool files) satisfy the whole read
    in one call — return that buffer directly instead of paying two extra
    whole-segment copies (bytearray append + bytes()), which showed up as
    ~25% of large-PUT wall time. The slow path hands back its accumulator
    bytearray as-is: every consumer (md5, np.frombuffer, the native
    encoder's from_buffer borrow) takes any bytes-like buffer."""
    if n <= 0:
        return b""
    first = data.read(n)
    if not first:
        return b""
    if len(first) == n:
        return first
    buf = bytearray(first)
    while len(buf) < n:
        chunk = data.read(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def default_parity(n_drives: int) -> int:
    """Default parity per set width (reference storage-class defaults,
    cmd/config/storageclass/storage-class.go:234)."""
    if n_drives == 1:
        return 0
    if n_drives <= 3:
        return 1
    if n_drives <= 5:
        return 2
    if n_drives <= 7:
        return 3
    return 4


class ErasureObjects(HealingMixin, MultipartMixin, SysConfigStore):
    def __init__(
        self,
        drives: list[StorageAPI],
        parity: int | None = None,
        block_size: int = DEFAULT_BLOCK_SIZE,
        batch_blocks: int = 16,
        bitrot_algorithm: str | None = None,
        enable_mrf: bool = False,
        nslock=None,
    ):
        if not drives:
            raise ValueError("empty drive set")
        self.drives = drives
        # Per-(bucket,object) namespace lock around mutating commits —
        # in-process by default, dsync-quorum in distributed topologies
        # (reference NewNSLock, cmd/namespace-lock.go:48).
        if nslock is None:
            from minio_tpu.dist.nslock import NamespaceLockMap
            nslock = NamespaceLockMap()
        self.nslock = nslock
        self.n = len(drives)
        self.parity = default_parity(self.n) if parity is None else parity
        # Reference validateParity bound (parity <= drives/2): beyond it
        # data quorum k(+1) drops below a majority and two conflicting
        # partial writes could both claim success.
        if not 0 <= self.parity <= self.n // 2:
            raise ValueError(
                f"parity {self.parity} invalid for {self.n} drives "
                f"(bound: drives/2 = {self.n // 2})")
        self.block_size = block_size
        self.batch_blocks = batch_blocks
        # Default bitrot algorithm follows the backend: mxsum256 on
        # accelerators (fused into the codec launches), host-native hash on
        # CPU (reference default HH256S, cmd/xl-storage-format-v1.go:117).
        self.bitrot_algorithm = (bitrot_algorithm if bitrot_algorithm
                                 else bitrot.device_default_algorithm())
        self.mrf: MRFHealer | None = MRFHealer(self) if enable_mrf else None
        self._read_pool = None
        self._read_pool_mu = threading.Lock()
        # Bucket-existence TTL cache: put_object stats every drive for the
        # bucket otherwise, a pool dispatch per op. Reference keeps bucket
        # metadata fully in memory (BucketMetadataSys); a short TTL keeps
        # cross-node deletes visible within a bound instead of a broadcast.
        self._bucket_cache: dict[str, tuple[float, BucketInfo]] = {}
        self._bucket_cache_ttl = 2.0
        # Quorum metadata reads run serially when the set is small and
        # all-local: with the journal parse cache a per-drive read is ~10us,
        # below the shared-pool dispatch cost. Wide sets and any remote
        # drive keep the parallel fan-out (RPC/disk latency dominates there).
        self._serial_meta_reads = self.n <= 8 and self._drives_all_local()
        self._encode_gibps: float | None = None
        # Hedged shard reads: rolling EWMA of one shard's batch-read
        # latency feeds the hedge delay; hedge_delay pins it explicitly
        # (tests / operator override). None delay + no history = no hedge
        # before the hard data deadline.
        self._shard_lat: float | None = None
        self.hedge_delay: float | None = None
        # Set-level post-election FileInfo cache (docs/METAPLANE.md):
        # GET/HEAD revalidate one cached election with per-local-drive
        # journal signatures instead of paying the N-drive fan-out.
        # Gated with the group-commit plane; None = every read elects.
        self._setcache = None
        if metaplane.enabled():
            from minio_tpu.metaplane.setcache import SetFileInfoCache

            self._setcache = SetFileInfoCache(metaplane.cache_objects())

    def _meta_invalidate(self, bucket: str, obj: str) -> None:
        """Drop the set-level FileInfo cache entry after a mutating
        fan-out (delete, metadata write, multipart complete, heal).
        Signature validation would catch these anyway; eager
        invalidation keeps the common case from paying a miss probe.
        The HBM hot tier rides the same hook: the mutation drops (and,
        for a still-hot key, re-admits) its device residence — the
        serve-time identity check makes this advisory, never
        load-bearing (docs/HOTTIER.md)."""
        if self._setcache is not None:
            self._setcache.invalidate(bucket, obj)
        tier = hottier.maybe_tier()
        if tier is not None:
            tier.invalidate(bucket, obj)

    @property
    def fast_local_reads(self) -> bool:
        """True when a metadata read on this set is reliably cheap (~100us):
        small all-local set with measured-fast journal stores. The HTTP
        layer uses this to run small-object opens directly on the event
        loop instead of paying an executor round trip."""
        return self._serial_meta_reads and all(
            getattr(d, "fast_sync", False) for d in self.drives)

    def _drives_all_local(self) -> bool:
        from minio_tpu.storage.local import LocalDrive

        for d in self.drives:
            if type(_health.unwrap(d)) is not LocalDrive:
                return False
        return True

    def _meta_deadline(self) -> float:
        """Fan-out deadline for metadata-class quorum ops: the max of the
        drives' adaptive per-op deadlines (drive-resilience plane)."""
        return _health.fleet_deadlines(self.drives)[0]

    def _data_deadline(self) -> float:
        return _health.fleet_deadlines(self.drives)[1]

    def _walk_deadline(self) -> float:
        return _health.fleet_deadlines(self.drives)[2]

    def _drives_all_online(self) -> bool:
        for d in self.drives:
            if isinstance(d, _health.HealthChecker) and d.state != _health.ONLINE:
                return False
        return True

    def _shard_read_pool(self):
        """Long-lived per-instance pool for parallel shard reads — a fresh
        pool per GET stream would pay thread spawn on the hot read path."""
        from concurrent.futures import ThreadPoolExecutor

        with self._read_pool_mu:
            if self._read_pool is None:
                self._read_pool = ThreadPoolExecutor(
                    max_workers=max(self.n, 8),
                    thread_name_prefix="shard-read")
            return self._read_pool

    def close(self) -> None:
        if self.mrf is not None:
            self.mrf.close()
        with self._read_pool_mu:
            if self._read_pool is not None:
                # Keep the (shut-down) executor referenced: a racing GET
                # stream then gets RuntimeError from submit — converted to
                # a quorum error in _read_chunk_rows — rather than an
                # AttributeError from a nulled pool, and a late caller
                # can't silently spawn a leaked replacement pool.
                self._read_pool.shutdown(wait=False, cancel_futures=True)

    def all_drives(self) -> list[StorageAPI]:
        return list(self.drives)

    def health(self) -> dict:
        # Deadline'd fan-out: the readiness probe must answer even while
        # a drive is hanging (a hung disk_info counts as offline).
        results = parallel_map(
            [lambda d=d: d.disk_info() for d in self.drives],
            deadline=self._meta_deadline())
        online = sum(1 for r in results if not isinstance(r, Exception))
        quorum = self._write_quorum_data(self.parity)
        return {
            "healthy": online >= quorum,
            "sets": [{"online": online, "total": self.n, "write_quorum": quorum}],
        }

    # ------------------------------------------------------------------
    # buckets (cmd/erasure-bucket.go)
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str, opts: ObjectOptions | None = None) -> None:
        _validate_bucket_name(bucket)
        results = parallel_map([lambda d=d: d.make_vol(bucket) for d in self.drives],
                               deadline=self._meta_deadline())
        exists = sum(1 for r in results if isinstance(r, se.VolumeExists))
        if exists >= self._write_quorum_meta():
            raise se.BucketExists(bucket)
        # A minority of stale VolumeExists drives (e.g. a drive that missed a
        # prior delete_bucket) counts as success — the dir is simply reused.
        results = [None if isinstance(r, se.VolumeExists) else r for r in results]
        try:
            reduce_write_quorum(results, self._write_quorum_meta(), bucket)
        except se.InsufficientWriteQuorum:
            parallel_map([lambda d=d: d.delete_vol(bucket) for d in self.drives],
                         deadline=self._meta_deadline())
            raise

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        hit = self._bucket_cache.get(bucket)
        if hit is not None and hit[0] > time.monotonic():
            return hit[1]
        results = parallel_map([lambda d=d: d.stat_vol(bucket) for d in self.drives],
                               deadline=self._meta_deadline())
        for r in results:
            if not isinstance(r, Exception):
                info = BucketInfo(r.name, r.created)
                self._bucket_cache[bucket] = (
                    time.monotonic() + self._bucket_cache_ttl, info)
                return info
        self._bucket_cache.pop(bucket, None)
        if any(isinstance(r, se.VolumeNotFound) for r in results):
            raise se.BucketNotFound(bucket)
        raise se.BucketNotFound(bucket, "", "no drive answered")

    def list_buckets(self) -> list[BucketInfo]:
        results = parallel_map([lambda d=d: d.list_vols() for d in self.drives],
                               deadline=self._meta_deadline())
        seen: dict[str, BucketInfo] = {}
        for r in results:
            if isinstance(r, Exception):
                continue
            for v in r:
                if v.name not in seen:
                    seen[v.name] = BucketInfo(v.name, v.created)
        return sorted(seen.values(), key=lambda b: b.name)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._bucket_cache.pop(bucket, None)
        tier = hottier.maybe_tier()
        if tier is not None:
            tier.invalidate_bucket(bucket)
        # Data-class deadline: a forced delete rmtrees arbitrary trees.
        results = parallel_map(
            [lambda d=d: d.delete_vol(bucket, force=force) for d in self.drives],
            deadline=self._data_deadline(),
        )
        if any(isinstance(r, se.VolumeNotEmpty) for r in results):
            raise se.BucketNotEmpty(bucket)
        if all(isinstance(r, se.VolumeNotFound) for r in results):
            raise se.BucketNotFound(bucket)
        reduce_write_quorum(results, self._write_quorum_meta(), bucket)

    def parity_for_class(self, sc: str) -> int:
        """Parity for a storage class (reference GetParityForSC,
        cmd/config/storageclass/storage-class.go:234): the `storageclass`
        config subsystem ("EC:N") overrides per class when set on the set
        (sc_parity, applied live by the server); otherwise STANDARD uses
        the constructor parity and RRS drops two below it."""
        sc_map = getattr(self, "sc_parity", None) or {}
        if sc == "REDUCED_REDUNDANCY":
            m = sc_map.get("RRS")
            if m is not None:
                # CONFIGURED values clamp to the reference validateParity
                # bound (parity <= drives/2 — beyond it a sub-majority
                # write could claim quorum). Constructor-chosen defaults
                # pass through untouched: explicit geometries are the
                # operator's call, already validated at construction.
                return max(0, min(int(m), self.n // 2))
            return max(1, self.parity - 2) if self.n >= 4 else self.parity
        m = sc_map.get("STANDARD")
        if m is not None:
            return max(0, min(int(m), self.n // 2))
        return self.parity

    def _write_quorum_meta(self) -> int:
        return self.n // 2 + 1

    def _write_quorum_data(self, parity: int) -> int:
        """Data write quorum: k drives, +1 when k == m so two conflicting
        half-writes can't both claim quorum (cmd/erasure-object.go:639-642)."""
        k = self.n - parity
        return k + (1 if k == parity else 0)

    # ------------------------------------------------------------------
    # put object (cmd/erasure-object.go:606-810)
    # ------------------------------------------------------------------

    def put_object(
        self,
        bucket: str,
        obj: str,
        data: BinaryIO,
        size: int = -1,
        opts: ObjectOptions | None = None,
    ) -> ObjectInfo:
        opts = opts or ObjectOptions()
        _validate_object_name(obj)
        self.get_bucket_info(bucket)

        sc = opts.user_defined.get("x-amz-storage-class", "")
        m = self.parity_for_class(sc)
        k = self.n - m
        write_quorum = self._write_quorum_data(m)

        fi = FileInfo.new(bucket, obj)
        if opts.versioned:
            fi.version_id = opts.version_id or str(uuid.uuid4())
        fi.mod_time = opts.mod_time or time.time()
        fi.metadata = dict(opts.user_defined)
        dist = hash_order(f"{bucket}/{obj}", self.n)
        fi.erasure = ErasureInfo(
            data_blocks=k,
            parity_blocks=m,
            block_size=self.block_size,
            distribution=dist,
            checksums=[ChecksumInfo(1, self.bitrot_algorithm)],
        )

        codec = ErasureCodec(k, m, self.block_size)
        shuffled = shuffle_by_distribution(self.drives, dist)

        md5 = hashlib.md5()
        total = 0
        first_block = _read_full(
            data, min(self.block_size, size) if size >= 0 else self.block_size
        )
        # Timeline: request-body receive up to the first block boundary
        # (small objects: the whole body) is the rx_drain stage.
        flight.mark("rx_drain")

        # Small-object fast path: inline into the journal, no shard files —
        # one metadata write per drive instead of shard + rename.
        if len(first_block) <= INLINE_DATA_LIMIT and (
            size < 0 and len(first_block) < self.block_size or 0 <= size <= INLINE_DATA_LIMIT
        ):
            if 0 <= size != len(first_block):
                raise se.IncompleteBody(
                    bucket, obj, f"got {len(first_block)} of {size} bytes")
            md5.update(first_block)
            fi.size = len(first_block)
            # No defensive copy: the buffer is never mutated after this
            # point, and the journal serializer takes any bytes-like.
            fi.inline_data = first_block
            fi.data_dir = ""
            fi.metadata.setdefault("etag", md5.hexdigest())
            fi.parts = [PartInfo(1, fi.size, fi.size, fi.mod_time)]
            # Inline versions carry no shard files, so the per-drive shard
            # index is meaningless — writing index 0 on every drive makes
            # all journals byte-identical, letting the set share ONE
            # serialized journal (write_metadata_single) instead of four
            # load+merge+serialize rounds.
            fi.erasure.index = 0
            journal = XLMeta()
            journal.add_version(fi)
            raw = journal.serialize()
            # Serial fan-out when every drive's measured journal-store cost
            # is below the pool-dispatch cost (all-local fast-sync media);
            # slow-fsync drives keep the parallel write so the op pays
            # max(fsync) rather than sum(fsync). A non-ONLINE drive forces
            # the deadline-bounded parallel path (a hang must not wedge
            # the serial loop).
            serial_writes = self.fast_local_reads and self._drives_all_online()
            with self.nslock.lock(bucket, obj) as lease:
                self._check_put_precondition(bucket, obj, opts)
                with obs.span("commit", bucket=bucket, object=obj,
                              inline=True):
                    outcomes = None
                    if self._setcache is not None:
                        # Metaplane armed: two-phase group commit —
                        # submit to every drive's WAL from this thread,
                        # then await the shared fsyncs; no pool worker
                        # blocked per drive (docs/METAPLANE.md).
                        outcomes = self._inline_commit_fast(
                            shuffled, bucket, obj, fi, raw, journal)
                    if outcomes is None:
                        outcomes = parallel_map(
                            [
                                lambda d=d: d.write_metadata_single(
                                    bucket, obj, fi, raw, journal,
                                    defer_reclaim=True)
                                for d in shuffled
                            ],
                            serial=serial_writes,
                            deadline=self._meta_deadline(),
                        )

                def undo_inline():
                    # Same undo discipline as the streaming commit: an
                    # inline overwrite below quorum must restore the
                    # displaced generation on drives that committed.
                    self._meta_invalidate(bucket, obj)
                    undo_fi = FileInfo(volume=bucket, name=obj,
                                       version_id=fi.version_id)

                    def undo(i, d):
                        if not isinstance(outcomes[i], Exception):
                            d.undo_rename(bucket, obj, undo_fi,
                                          outcomes[i])

                    parallel_map([lambda i=i, d=d: undo(i, d)
                                  for i, d in enumerate(shuffled)],
                                 deadline=self._meta_deadline())

                try:
                    reduce_write_quorum(outcomes, write_quorum, bucket, obj)
                except Exception:
                    undo_inline()
                    raise
                if not lease.held:
                    # Lock quorum lost mid-commit (see the streaming
                    # path): roll back rather than complete unprotected.
                    undo_inline()
                    raise se.OperationTimedOut(
                        bucket, obj, "dsync lock quorum lost during "
                        "commit; write rolled back")
                toks = [o for o in outcomes
                        if o and not isinstance(o, Exception)]
                if toks:
                    parallel_map(
                        [lambda d=d, t=t: d.commit_rename(t)
                         for d, t in zip(shuffled, outcomes)
                         if t and not isinstance(t, Exception)],
                        deadline=self._meta_deadline())
                if self._setcache is not None:
                    # Write-through: the committed journal IS what an
                    # election would return (index 0 on every drive),
                    # so the first GET skips the fan-out outright.
                    self._setcache.populate(bucket, obj, "", fi, shuffled)
                tier = hottier.maybe_tier()
                if tier is not None:
                    # An inline overwrite displaces any shard-backed
                    # resident generation (the streaming path rides
                    # _meta_invalidate; inline commits skip it).
                    tier.invalidate(bucket, obj)
            flight.mark("commit", "metaplane")
            return self._fi_to_object_info(bucket, obj, fi)

        # Streaming erasure path.
        tmp_rel = f"tmp/{uuid.uuid4().hex}"
        sys_vol = ".mtpu.sys"

        def cleanup_tmp():
            parallel_map(
                [lambda d=d: d.delete(sys_vol, tmp_rel, recursive=True)
                 for d in shuffled],
                deadline=self._meta_deadline())

        try:
            with obs.span("encode", bucket=bucket, object=obj) as sp:
                total, md5_hex, errs = self._fan_out_encode(
                    shuffled, sys_vol, f"{tmp_rel}/part.1", data, size, codec,
                    write_quorum, bucket, obj, initial=first_block,
                )
                sp.set(bytes=total)
            flight.mark("encode", "dataplane")
        except (se.StorageError, se.ObjectError):
            # Quorum lost mid-encode (InsufficientWriteQuorum is an
            # ObjectError): the healthy drives' tmp staging must not
            # linger — every other failure path fans out this cleanup.
            cleanup_tmp()
            raise

        if size >= 0 and total != size:
            cleanup_tmp()
            raise se.IncompleteBody(bucket, obj, f"got {total} of {size} bytes")

        fi.size = total
        fi.metadata.setdefault("etag", md5_hex)
        fi.parts = [PartInfo(1, total, total, fi.mod_time)]

        tokens: list = [None] * len(shuffled)

        def commit(i: int, drive: StorageAPI):
            if errs[i] is not None:
                raise errs[i]
            tokens[i] = drive.rename_data(
                sys_vol, tmp_rel, _clone_for_drive(fi, i + 1), bucket, obj,
                defer_reclaim=True)

        # Commit under the namespace lock (the reference takes the dist
        # lock just before metadata write + rename, cmd/erasure-object.go:736).
        with self.nslock.lock(bucket, obj) as lease:
            try:
                self._check_put_precondition(bucket, obj, opts)
            except se.ObjectError:
                cleanup_tmp()
                raise
            with obs.span("commit", bucket=bucket, object=obj):
                outcomes = parallel_map(
                    [lambda i=i, d=d: commit(i, d)
                     for i, d in enumerate(shuffled)],
                    deadline=self._meta_deadline(),
                )

            def undo_commit():
                # UNDO everywhere — drives that failed still hold tmp
                # staging; drives that committed must drop the
                # just-written version AND restore whatever the commit
                # displaced (a replaced version's journal entry + data
                # dir), or listings (which union journals) would show an
                # object GET quorum-fails on, and an overwrite would
                # have destroyed the previous generation (reference
                # undo-rename discipline).
                self._meta_invalidate(bucket, obj)
                undo_fi = FileInfo(volume=bucket, name=obj,
                                   version_id=fi.version_id,
                                   data_dir=fi.data_dir)

                def undo(i, d):
                    if isinstance(outcomes[i], Exception):
                        d.delete(sys_vol, tmp_rel, recursive=True)
                    else:
                        d.undo_rename(bucket, obj, undo_fi, tokens[i])

                parallel_map([lambda i=i, d=d: undo(i, d)
                              for i, d in enumerate(shuffled)],
                             deadline=self._meta_deadline())

            try:
                reduce_write_quorum(outcomes, write_quorum, bucket, obj)
            except Exception:
                undo_commit()
                raise
            if not lease.held:
                # The dsync lock lost its refresh quorum mid-commit (a
                # partition isolated us from the locker majority): the
                # critical section is no longer protected, so a racing
                # writer on the other side may have committed too.
                # Completing would risk a silent split-brain overwrite —
                # roll back and fail typed instead.
                undo_commit()
                raise se.OperationTimedOut(
                    bucket, obj,
                    "dsync lock quorum lost during commit; write rolled back")
            # Quorum reached under a live lock: discard the displaced
            # state for good.
            if any(tokens):
                parallel_map([lambda d=d, t=t: d.commit_rename(t)
                              for d, t in zip(shuffled, tokens) if t],
                             deadline=self._meta_deadline())
            self._meta_invalidate(bucket, obj)
        flight.mark("commit", "metaplane")
        # Partial success: quorum met but some drive missed the write — queue
        # it for background heal (reference addPartial, cmd/erasure-object.go:1150).
        if self.mrf is not None and any(isinstance(o, Exception) for o in outcomes):
            self.mrf.add_partial(bucket, obj, fi.version_id)
        return self._fi_to_object_info(bucket, obj, fi)

    # ------------------------------------------------------------------
    # get object (cmd/erasure-object.go:137-358)
    # ------------------------------------------------------------------

    def get_object_info(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        # Same election-window read lock as get_object_reader.
        with self.nslock.rlock(bucket, obj):
            fi = self._read_quorum_fileinfo(bucket, obj, opts.version_id)
        if fi.deleted:
            if opts.version_id:
                return self._fi_to_object_info(bucket, obj, fi)
            raise se.ObjectNotFound(bucket, obj)
        return self._fi_to_object_info(bucket, obj, fi)

    def get_object_reader(
        self,
        bucket: str,
        obj: str,
        opts: ObjectOptions | None = None,
    ):
        """ONE quorum metadata read for info + data: returns
        (info, open_range) where open_range(offset, length) streams object
        bytes using the already-elected FileInfo. The HTTP GET path needs
        the info before it can choose the byte range (SSE/compression
        transforms); the two-call shape (get_object_info + get_object) paid
        the quorum read twice (reference folds this into a single
        GetObjectNInfo reader, cmd/erasure-object.go:137)."""
        opts = opts or ObjectOptions()
        # Read lock around the metadata election (reference GetObject
        # takes the namespace RLock, cmd/erasure-object.go:176): a
        # concurrent overwrite fans journals out drive by drive, and an
        # unlocked reader can catch the set split 50/50 with NEITHER
        # version reaching read quorum. Held for the election only —
        # inline objects are then fully consistent (payload rides the
        # elected journal); shard streams open after release, where the
        # per-record bitrot framing turns any later mutation into a
        # typed read error, never silent corruption.
        with self.nslock.rlock(bucket, obj):
            fi = self._read_quorum_fileinfo(bucket, obj, opts.version_id)
        # Timeline: quorum metadata election (the GET's metadata stage —
        # decode + transfer land in the trailing resp_drain segment).
        flight.mark("meta_elect", "metaplane")
        if fi.deleted:
            raise se.ObjectNotFound(bucket, obj)
        info = self._fi_to_object_info(bucket, obj, fi)
        pinned = bool(opts.version_id)

        def open_range(offset: int = 0, length: int = -1) -> Iterator[bytes]:
            return self._open_fi_range(bucket, obj, fi, offset, length,
                                       pinned=pinned)

        return info, open_range

    def get_object(
        self,
        bucket: str,
        obj: str,
        offset: int = 0,
        length: int = -1,
        opts: ObjectOptions | None = None,
    ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info, open_range = self.get_object_reader(bucket, obj, opts)
        return info, open_range(offset, length)

    def _open_fi_range(self, bucket: str, obj: str, fi: FileInfo,
                       offset: int, length: int,
                       pinned: bool = False) -> Iterator[bytes]:
        if length < 0:
            length = fi.size - offset
        if offset < 0 or length < 0 or offset + length > fi.size:
            raise se.InvalidRange(bucket, obj, f"[{offset}, {offset + length}) of {fi.size}")
        if fi.inline_data:
            payload = fi.inline_data[offset: offset + length]
            return iter([payload])
        tier_name = fi.metadata.get(
            "x-mtpu-internal-transition-tier") if fi.metadata else ""
        if not tier_name and fi.data_dir:
            hot = hottier.maybe_tier()
            if hot is not None:
                if pinned:
                    # Latest-only tier: an explicitly versioned read
                    # bypasses by contract — same accounting as the
                    # disk cache's versioned bypass (docs/METRICS.md).
                    _CACHE_BYPASS.labels(reason="hottier_versioned").inc()
                else:
                    served = hot.serve(bucket, obj, fi, offset, length)
                    if served is not None:
                        # Device-resident hit: one gather+digest launch
                        # + one DMA; zero drive opens.
                        return served
                    hot.note_miss(
                        bucket, obj, fi.size,
                        reader=lambda b=bucket, o=obj: self.get_object(
                            b, o),
                        grid=(fi.erasure.data_blocks,
                              fi.erasure.block_size))
        if tier_name and not fi.data_dir:
            # Transitioned version: data lives on the remote tier; stream
            # through transparently (reference transitioned-object reads,
            # cmd/bucket-lifecycle.go getTransitionedObjectReader). Parts
            # metadata survives transition, so multipart-SSE decryption
            # still sees its per-part layout.
            from minio_tpu.scanner import tiers as tiermod

            reg = tiermod.global_registry()
            key = fi.metadata.get("x-mtpu-internal-transition-key", "")
            try:
                if reg is None:
                    raise tiermod.TierError("no tier registry configured")
                tier = reg.get(tier_name)
                return tier.get(key, offset, length)
            except tiermod.TierError as e:
                # Typed, not a 500: the data's only copy is on a tier we
                # can't reach (e.g. tier deleted with force).
                raise se.ObjectNotFound(bucket, obj,
                                        f"tier {tier_name!r}: {e}") from e
        return self._stream_erasure(bucket, obj, fi, offset, length)

    def _stream_erasure(self, bucket: str, obj: str, fi: FileInfo,
                        offset: int, length: int) -> Iterator[bytes]:
        """Stream [offset, offset+length) across the object's parts — each
        part is an independent erasure stream with its own shard files
        (reference per-part decode loop, cmd/erasure-object.go:297-316)."""
        if length == 0:
            return
        part_off = 0
        for part in fi.parts:
            part_end = part_off + part.size
            if part_end <= offset:
                part_off = part_end
                continue
            if part_off >= offset + length:
                break
            lo = max(offset, part_off) - part_off
            hi = min(offset + length, part_end) - part_off
            yield from self._stream_one_part(bucket, obj, fi, part, lo, hi - lo)
            part_off = part_end

    def _stream_one_part(self, bucket: str, obj: str, fi: FileInfo, part,
                         offset: int, length: int) -> Iterator[bytes]:
        k = fi.erasure.data_blocks
        n = k + fi.erasure.parity_blocks
        codec = ErasureCodec(k, fi.erasure.parity_blocks, fi.erasure.block_size)
        shard_size = codec.shard_size()
        algo = next((c.algorithm for c in fi.erasure.checksums), self.bitrot_algorithm)
        shuffled = shuffle_by_distribution(self.drives, fi.erasure.distribution)
        rel = f"{obj}/{fi.data_dir}/part.{part.number}"
        shard_data_size = codec.shard_file_size(part.size)

        native = self._native_stream(bucket, obj, fi, part, algo, shuffled,
                                     rel, offset, length)
        if native is not None:
            yield from native
            return

        readers: list[bitrot.BitrotReader | None] = [None] * n

        def open_reader(i: int):
            f = shuffled[i].read_file_stream(bucket, rel)
            return bitrot.BitrotReader(f, shard_data_size, shard_size, algo)

        if length == 0:
            return
        first_block = offset // fi.erasure.block_size
        last_block = (offset + length - 1) // fi.erasure.block_size

        # Select shards data-first (parity only on demand) — the staggered
        # any-k read strategy (cmd/erasure-decode.go:120-188). Opening is
        # deferred into the pooled read tasks (_read_chunk_rows), so a
        # drive hanging at open() is hedged/deadlined exactly like one
        # hanging mid-read. Drives already known dead — health-OFFLINE
        # locals and OPEN-breaker peers — start excluded, so selection
        # jumps straight to reconstruction instead of paying a doomed
        # open per batch (the native lane has always done this).
        dead: set[int] = {i for i, d in enumerate(shuffled)
                          if not d.is_online()}
        corrupt: set[int] = set()  # the subset of dead that OBSERVED bitrot
        # Hedge losers: healthy-but-slow shards sidelined for this stream.
        # Never heal-triggering, and reclaimable when selection runs short
        # — a benched shard must not cost quorum on a real failure later.
        benched: set[int] = set()

        def ensure_readers() -> list[int]:
            chosen = [i for i in list(range(k)) + list(range(k, n))
                      if i not in dead and i not in benched][:k]
            if len(chosen) < k and benched:
                benched.clear()  # second chance: slow beats no quorum
                chosen = [i for i in list(range(k)) + list(range(k, n))
                          if i not in dead][:k]
            if len(chosen) < k:
                raise se.InsufficientReadQuorum(bucket, obj, "not enough live shards")
            return sorted(chosen)

        pool = self._shard_read_pool()
        batches: list[tuple[list[int], list[int]]] = []
        bi = first_block
        while bi <= last_block:
            ids = list(range(bi, min(bi + self.batch_blocks, last_block + 1)))
            batches.append((ids, [
                min(fi.erasure.block_size, part.size - b * fi.erasure.block_size)
                for b in ids
            ]))
            bi = ids[-1] + 1

        if len(batches) <= 1:
            # Single batch (small/ranged GET, the high-QPS case): nothing
            # to overlap — read inline, skip the producer thread entirely.
            try:
                for ids, lens in batches:
                    while True:
                        chosen = ensure_readers()
                        try:
                            rows = self._read_chunk_rows(
                                readers, chosen, ids, lens, codec, n,
                                dead, algo, pool=pool, corrupt=corrupt,
                                open_reader=open_reader, benched=benched)
                            break
                        except se.StorageError:
                            continue
                    decoded = self._decode_rows(codec, rows, lens)
                    for j, b in enumerate(ids):
                        blk_start = b * fi.erasure.block_size
                        lo = max(offset, blk_start) - blk_start
                        hi = min(offset + length,
                                 blk_start + lens[j]) - blk_start
                        if hi > lo:
                            yield from _yield_block_range(
                                decoded[j], lo, hi)
            finally:
                for r in readers:
                    if r is not None:
                        try:
                            r.src.close()
                        except Exception:  # noqa: BLE001
                            pass
                if dead and self.mrf is not None:
                    self.mrf.add_partial(bucket, obj, fi.version_id,
                                         deep=bool(corrupt))
            return

        # Read-ahead producer (the GET half of P2, SURVEY §2.4): one
        # dedicated thread reads batch N+1 while the consumer verifies,
        # decodes and sends batch N. Readers/dead/re-selection are touched
        # ONLY by the producer, so the existing retry semantics are
        # unchanged. A bounded queue + stop-checked puts guarantee the
        # producer exits promptly on early close.
        out_q: queue.Queue = queue.Queue(maxsize=2)
        stop = threading.Event()

        def _offer(item) -> bool:
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        cleanup_mu = threading.Lock()
        cleaned = [False]

        def _close_readers() -> None:
            # Exactly-once, from whichever side owns the readers last:
            # the consumer's finally (normal case) or the producer's exit
            # (the consumer's join timed out on a hung read).
            with cleanup_mu:
                if cleaned[0]:
                    return
                cleaned[0] = True
            for r in readers:
                if r is not None:
                    try:
                        r.src.close()
                    except Exception:  # noqa: BLE001
                        pass

        def producer_run() -> None:
            try:
                for ids, lens in batches:
                    if stop.is_set():
                        return
                    while not stop.is_set():
                        chosen = ensure_readers()
                        try:
                            rows = self._read_chunk_rows(
                                readers, chosen, ids, lens, codec, n,
                                dead, algo, pool=pool, corrupt=corrupt,
                                open_reader=open_reader, benched=benched,
                            )
                            break
                        except se.StorageError:
                            continue  # reader died; re-choose, retry batch
                    else:
                        return  # early close during a failing batch
                    if not _offer(("rows", ids, lens, rows)):
                        return
                _offer(("done", None, None, None))
            except BaseException as e:  # noqa: BLE001 - relay to consumer
                _offer(("err", e, None, None))
            finally:
                if stop.is_set():
                    # The consumer may already have run its finally (join
                    # timeout): the readers are ours to close.
                    _close_readers()

        prod = threading.Thread(target=obs.ctx_wrap(producer_run),
                                daemon=True, name="shard-readahead")
        prod.start()
        try:
            while True:
                tag, a, b_, c = out_q.get()
                if tag == "done":
                    break
                if tag == "err":
                    raise a
                batch_ids, block_lens, rows = a, b_, c
                decoded = self._decode_rows(codec, rows, block_lens)
                for j, b in enumerate(batch_ids):
                    blk_start = b * fi.erasure.block_size
                    lo = max(offset, blk_start) - blk_start
                    hi = min(offset + length, blk_start + block_lens[j]) - blk_start
                    if hi > lo:
                        yield from _yield_block_range(decoded[j], lo, hi)
        finally:
            # Runs on normal completion AND early close (GeneratorExit) —
            # callers that read exactly length bytes leave the generator
            # paused, so cleanup cannot live after the loop. (The shard
            # pool is instance-owned and outlives the stream.) Stop and
            # join the read-ahead producer BEFORE closing readers — it is
            # the only thread touching them.
            stop.set()
            while True:
                try:
                    out_q.get_nowait()
                except queue.Empty:
                    break
            prod.join(timeout=5.0)
            if not prod.is_alive():
                _close_readers()
            # else: producer is wedged in a slow read — closing the files
            # under it would corrupt its reads/retries; its own finally
            # closes the readers when it exits.
            # Served the read but some shard was dead/corrupt: one-shot heal
            # trigger (reference cmd/erasure-object.go:321-344).
            if dead and self.mrf is not None:
                self.mrf.add_partial(bucket, obj, fi.version_id,
                                     deep=bool(corrupt))

    def _native_stream(self, bucket: str, obj: str, fi: FileInfo, part,
                       algo: str, shuffled: list[StorageAPI], rel: str,
                       offset: int, length: int):
        """Native serving lane for GET: pread + sip256 verify + any-k
        reconstruct + block assembly in one GIL-released C++ call per
        window (native/mtpu_native.cc mtpu_decode_part — the reference's
        parallelReader + bitrot verify + ReconstructData,
        cmd/erasure-decode.go:120-205). Remote drives join the same
        window: their framed byte ranges prefetch over RPC (in parallel)
        and feed the decoder as in-memory shards — readers stay
        interface-uniform like the reference's (cmd/erasure-decode.go:
        120-188), so one remote drive no longer demotes the whole GET to
        the Python path. None -> Python/device path."""
        from minio_tpu.native import plane

        if (algo not in ("sip256", "highwayhash256") or length <= 0
                or not plane.available()):
            return None
        paths, remotes = _shard_paths_mixed(shuffled, bucket, rel)
        if paths is None:
            return None
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        bs = fi.erasure.block_size
        n = k + m

        def gen():
            from concurrent.futures import ThreadPoolExecutor

            corrupt_seen = False
            # Health-OFFLINE drives start dead (zero I/O on them); later
            # windows also never re-read a shard already known bad.
            dead: set[int] = {i for i, d in enumerate(shuffled)
                              if not d.is_online()}
            end = offset + length
            # One open stream per remote shard for the whole GET (stat +
            # open once, sequential ranged reads ride its readahead).
            streams: dict[int, object] = {}
            # Long-lived range streams (reference ReadFileStream shape):
            # the whole GET's framed extent rides ONE streamed request
            # per remote shard; windows read sequentially off it.
            rstreams: dict[int, tuple] = {}     # i -> (stream, next_off)
            lo_all, ln_all = plane.framed_range(k, bs, part.size, offset,
                                                length)

            def fetch_remote(i, lo, ln):
                ent = rstreams.pop(i, None)
                if ent is not None and ent[1] != lo:
                    try:
                        ent[0].close()
                    except Exception:  # noqa: BLE001
                        pass
                    ent = None
                if ent is None:
                    opener = getattr(remotes[i], "read_file_range_stream",
                                     None)
                    if opener is None:
                        # Fault injectors / exotic wrappers interpose on
                        # read_file_stream — keep their per-call hooks.
                        return _fetch_framed(remotes[i], bucket, rel, lo,
                                             ln, streams, i)
                    try:
                        ent = (opener(bucket, rel, lo,
                                      lo_all + ln_all - lo), lo)
                    except (se.StorageError, OSError):
                        return None
                st = ent[0]
                try:
                    buf = _read_exact(st, ln)
                except (se.StorageError, OSError, ValueError):
                    try:
                        st.close()
                    except Exception:  # noqa: BLE001
                        pass
                    return None
                rstreams[i] = (st, lo + ln)
                return buf

            # All-local GETs take one giant decode window (fewest C
            # calls); with remote shards the window shrinks so the
            # one-ahead pipeline genuinely overlaps window N+1's RPC
            # prefetch with window N's decode — a single 64 MiB window
            # would serialize the whole transfer before the first
            # decode byte.
            wb = plane.window_blocks(bs)
            if any(r is not None for r in remotes):
                wb = plane.pipeline_window_blocks(bs)

            def windows():
                pos = offset
                while pos < end:
                    wend = min(end, (pos // bs + wb) * bs)
                    yield pos, wend
                    pos = wend

            def decode_window(pos, wend):
                """One window with remote-shard escalation: start from the
                data-first k selection; remote shards the selection needs
                prefetch their framed range over RPC (in parallel); on
                failures the selection widens until served or < k left."""
                nonlocal corrupt_seen
                mem: dict[int, bytes] = {}
                lo, ln = plane.framed_range(k, bs, part.size, pos,
                                            wend - pos)
                while True:
                    alive = [i for i in range(n) if i not in dead]
                    if len(alive) < k:
                        raise se.InsufficientReadQuorum(
                            bucket, obj, "not enough live shards")
                    need = [i for i in alive[:k]
                            if remotes[i] is not None and i not in mem]
                    if need:
                        # Deadline'd: a hung remote/injected shard becomes
                        # a timeout value -> dead -> re-selection, instead
                        # of wedging the whole GET window.
                        fetches = parallel_map([
                            lambda i=i: fetch_remote(i, lo, ln)
                            for i in need],
                            deadline=self._data_deadline())
                        lost = False
                        for i, blob in zip(need, fetches):
                            if isinstance(blob, (bytes, bytearray)):
                                mem[i] = blob
                            else:
                                dead.add(i)
                                lost = True
                        if lost:
                            continue  # re-select around the dead fetch
                    skip = dead | {i for i in range(n)
                                   if remotes[i] is not None
                                   and i not in mem}
                    data, states = plane.decode_range(
                        paths, k, m, bs, part.size, pos, wend - pos,
                        skip=skip, algorithm=algo, mem=mem)
                    saw_fail = False
                    for i, s in enumerate(states):
                        if s < 0:
                            dead.add(i)
                            saw_fail = True
                        if s == -2:
                            corrupt_seen = True
                    if data is not None:
                        return data
                    if not saw_fail:
                        raise se.InsufficientReadQuorum(
                            bucket, obj, "not enough live shards")

            # One-window read-ahead: window N+1 decodes (GIL-released C
            # call) in a worker while window N streams to the client —
            # the GET half of P2 (the Python lane's read-ahead producer).
            with ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="native-decode") as ex:
                try:
                    fut = None
                    decode_ctx = obs.ctx_wrap(decode_window)
                    pending = windows()
                    nxt = next(pending, None)
                    while nxt is not None:
                        pos, wend = nxt
                        if fut is None:
                            fut = ex.submit(decode_ctx, pos, wend)
                        try:
                            # Bounded: a local pread hung inside the C
                            # call (NFS stall) must fail the GET typed
                            # and on time, never wedge it.
                            data = fut.result(
                                timeout=2.0 * self._data_deadline())
                        except _FutTimeout:
                            note_leaked_worker()
                            raise se.OperationTimedOut(
                                bucket, obj, "native decode window "
                                "exceeded the data deadline") from None
                        except OSError as e:
                            raise se.FaultyDisk(
                                f"native decode: {e}") from e
                        nxt = next(pending, None)
                        fut = (ex.submit(decode_ctx, nxt[0], nxt[1])
                               if nxt is not None else None)
                        yield data
                finally:
                    # An abandoned GET (client disconnect mid-stream) can
                    # leave window N+1 decoding in the worker; closing
                    # its streams under it would fail healthy shards and
                    # mark live nodes offline. Settle the future first
                    # (same discipline as the Python lane's
                    # producer-join before closing readers).
                    if fut is not None and not fut.cancel():
                        try:
                            fut.result(timeout=30)
                        except Exception:  # noqa: BLE001 — teardown only
                            pass
                    for f in streams.values():
                        try:
                            f.close()
                        except Exception:  # noqa: BLE001
                            pass
                    streams.clear()
                    for st, _off in rstreams.values():
                        try:
                            st.close()
                        except Exception:  # noqa: BLE001
                            pass
                    rstreams.clear()
                    # One-shot heal trigger on any dead/corrupt shard seen
                    # (reference cmd/erasure-object.go:321-344).
                    if dead and self.mrf is not None:
                        self.mrf.add_partial(bucket, obj, fi.version_id,
                                             deep=corrupt_seen)

        return gen()

    def _hedge_delay(self) -> float | None:
        """Seconds to wait on a shard-read straggler before launching a
        spare reader on an unused parity drive. Derived from the rolling
        shard-read latency EWMA unless pinned via self.hedge_delay; None
        (no history yet) defers to the hard data deadline."""
        if self.hedge_delay is not None:
            return self.hedge_delay
        e = self._shard_lat
        if e is None:
            return None
        return max(4.0 * e, 0.02)

    def _note_shard_latency(self, dur: float) -> None:
        e = self._shard_lat
        self._shard_lat = dur if e is None else 0.8 * e + 0.2 * dur

    def _abandon_shard(self, i: int, fut, readers, dead,
                       benched=None, failed=True) -> None:
        """A straggler lost the hedge (failed=False: sidelined in
        `benched`, reclaimable, never heal-triggering) or hit the data
        deadline (failed=True: marked dead like any failed drive):
        reclaim its reader when the read eventually returns; the pool
        worker it occupies is accounted and replaced until then.
        read_shard re-checks the exclusion sets after opening, so a late
        open can never resurrect the slot."""
        if failed or benched is None:
            dead.add(i)
        else:
            benched.add(i)
        rdr = readers[i]
        readers[i] = None

        def _cleanup(_f, rdr=rdr):
            if rdr is not None:
                try:
                    rdr.src.close()
                except Exception:  # noqa: BLE001 - teardown only
                    pass

        if fut.cancel():
            _cleanup(None)
            return
        note_leaked_worker(self._read_pool, fut)
        fut.add_done_callback(_cleanup)

    def _read_chunk_rows(self, readers, chosen, batch_ids, block_lens, codec,
                         n, dead, algo=None, pool=None, corrupt=None,
                         open_reader=None, benched=None):
        """Read one batch of chunk rows from the chosen shards; marks dead
        drives and raises StorageError to trigger re-selection.

        Shards read in PARALLEL (one worker per shard, each reading its
        batch sequentially — per-drive sequential I/O, cross-drive
        concurrency, the reference's parallelReader goroutine layout,
        cmd/erasure-decode.go:120-188); host hashing and preads release
        the GIL in native code. mxsum256 shard files verify in ONE device
        launch per batch (fused.verify_digests) instead of per-chunk host
        hashing — the TPU-native form of the reference's
        verify-every-ReadAt (cmd/bitrot-streaming.go:115-158).

        First-k-wins with hedging: after the hedge delay (rolling-latency
        derived) spare readers launch on unused parity shards, and the
        batch completes with the FIRST k shard results — a slow or hung
        drive degrades GET latency by one hedge delay, not one deadline.
        Stragglers still pending when k arrive (or at the hard data
        deadline) are abandoned, never awaited."""
        batched_verify = algo == "mxsum256"
        shard_size = codec.shard_size()
        chunk_lens = [-(-bl // codec.k) for bl in block_lens]

        def read_shard(i: int) -> list[tuple[bytes | None, bytes]]:
            r = readers[i]
            if r is None:
                if open_reader is None:
                    raise se.FaultyDisk(f"shard {i}: no reader")
                r = open_reader(i)
                if i in dead or (benched is not None and i in benched):
                    # Abandoned while the open was in flight: don't
                    # publish a zombie reader.
                    try:
                        r.src.close()
                    except Exception:  # noqa: BLE001
                        pass
                    raise se.FaultyDisk(f"shard {i}: abandoned")
                readers[i] = r
            out: list[tuple[bytes | None, bytes]] = []
            for j, b in enumerate(batch_ids):
                if batched_verify:
                    want, chunk = r.read_record(b)
                    if len(chunk) != chunk_lens[j]:
                        raise se.FileCorrupt(
                            f"chunk {b} length {len(chunk)} != "
                            f"{chunk_lens[j]}")
                    out.append((want, chunk))
                else:
                    out.append((None, r.read_at(
                        b * shard_size, chunk_lens[j])))
            return out

        from concurrent.futures import FIRST_COMPLETED, CancelledError
        from concurrent.futures import wait as _fwait

        _SHARD_ERRS = (se.StorageError, OSError, CancelledError, RuntimeError)
        results: dict[int, list] = {}
        first_err: tuple[int, Exception] | None = None
        need = len(chosen)

        def record_failure(i: int, e: Exception) -> None:
            nonlocal first_err
            dead.add(i)
            # FileCorrupt = observed bitrot/truncation -> the queued
            # heal must deep-verify; a plain open/read failure only
            # needs the presence scan.
            if isinstance(e, se.FileCorrupt) and corrupt is not None:
                corrupt.add(i)
            readers[i] = None
            if first_err is None:
                first_err = (i, e)

        if pool is not None:
            futures: dict = {}
            rev: dict = {}
            started: dict[int, float] = {}
            pool_down = False

            def submit(i: int) -> bool:
                try:
                    # ctx_wrap: shard reads run in pool workers but their
                    # storage/RPC trace records belong to this request.
                    f = pool.submit(obs.ctx_wrap(read_shard), i)
                except RuntimeError:
                    return False
                futures[i] = f
                rev[f] = i
                started[i] = time.monotonic()
                return True

            for i in chosen:
                if not submit(i):
                    pool_down = True
                    break
            if pool_down:
                # Pool shut down mid-submit (layer closing). Do NOT fall
                # back to inline reads: already-running futures share the
                # BitrotReaders' seek state, so a concurrent inline pass
                # could serve wrong chunks. Wait the started ones out,
                # mark every chosen shard dead, and degrade to a clean
                # quorum error.
                for f in futures.values():
                    f.cancel()
                for f in futures.values():
                    try:
                        f.result()
                    # CancelledError is a BaseException on stock
                    # CPython >= 3.8 — name it or the drain loop leaks it.
                    except (Exception, CancelledError):  # noqa: BLE001
                        pass
                for i in chosen:
                    dead.add(i)
                    readers[i] = None
                raise se.FileCorrupt("layer closing") from None

            t0 = time.monotonic()
            end = t0 + self._data_deadline()
            hd = self._hedge_delay()
            hedge_at = (t0 + hd) if hd is not None else None
            hedged: set[int] = set()
            pending = set(futures)
            while pending and len(results) < need:
                now = time.monotonic()
                if now >= end:
                    break
                timeout = end - now
                if hedge_at is not None:
                    timeout = min(timeout, max(0.0, hedge_at - now))
                done, _ = _fwait({futures[i] for i in pending},
                                 timeout=timeout,
                                 return_when=FIRST_COMPLETED)
                for f in done:
                    i = rev[f]
                    pending.discard(i)
                    try:
                        results[i] = f.result()
                        self._note_shard_latency(
                            time.monotonic() - started[i])
                        if (i in hedged and len(results) <= need
                                and any(j not in hedged for j in pending)):
                            _HEDGED_WINS.inc()
                    except _SHARD_ERRS as e:
                        record_failure(i, e)
                if (len(results) < need and pending and hedge_at is not None
                        and time.monotonic() >= hedge_at):
                    # One spare per straggler, parity-order, never
                    # reusing a shard already dead or in play.
                    hedge_at = None
                    spares = [s for s in range(n)
                              if s not in dead and s not in futures
                              and (benched is None or s not in benched)]
                    for s in spares[:len(pending)]:
                        if submit(s):
                            pending.add(s)
                            hedged.add(s)
                            _HEDGED_READS.inc()
            # Settle leftovers: harvest already-done stragglers for free,
            # abandon the rest (hedge losers / deadline breakers).
            deadline_hit = len(results) < need
            for i in list(pending):
                f = futures[i]
                if f.done():
                    try:
                        results[i] = f.result()
                        continue
                    except _SHARD_ERRS as e:
                        record_failure(i, e)
                        continue
                self._abandon_shard(i, f, readers, dead, benched,
                                    failed=deadline_hit)
                if deadline_hit and first_err is None:
                    first_err = (i, se.OperationTimedOut(
                        msg="shard read exceeded the data deadline"))
            if len(results) < need:
                i, e = first_err if first_err is not None else (
                    -1, se.FaultyDisk("no shard results"))
                raise se.FileCorrupt(f"shard {i}: {e}") from e
        else:
            for i in chosen:
                try:
                    results[i] = read_shard(i)
                except _SHARD_ERRS as e:
                    record_failure(i, e)
            if first_err is not None:
                i, e = first_err
                raise se.FileCorrupt(f"shard {i}: {e}") from e

        rows: list[list[bytes | None]] = []
        records: list[tuple[int, bytes, bytes]] = []  # (drive, want, chunk)
        for j, _b in enumerate(batch_ids):
            row: list[bytes | None] = [None] * n
            for i in sorted(results):
                want, chunk = results[i][j]
                row[i] = chunk
                if batched_verify:
                    records.append((i, want, chunk))
            rows.append(row)
        if records:
            self._verify_records(records, codec, readers, dead, corrupt)
        return rows

    def _decode_rows(self, codec: ErasureCodec, rows, lens):
        """GET-path reconstruction: through the batched plane when
        enabled (concurrent GETs with even DIFFERENT failure patterns
        share one launch — per-row decode matrices ride as data), else
        the per-object codec path."""
        plane = dataplane.maybe_plane() if codec.m else None
        if plane is not None and lens and plane.accepts_recon_chunk(
                -(-max(lens) // codec.k)):
            try:
                return plane.decode_blocks(codec.k, codec.m,
                                           codec.block_size, rows, lens)
            except se.OperationTimedOut:
                pass  # plane saturated: per-object dispatch still serves
        return codec.decode_blocks(rows, lens)

    def _verify_records(self, records, codec, readers, dead,
                        corrupt=None) -> None:
        """One batched mxsum256 launch over every chunk just read; a digest
        mismatch marks the drive dead and retriggers shard selection."""
        from minio_tpu.ops import fused

        plane = dataplane.maybe_plane()
        got = None
        if plane is not None and plane.accepts_chunk(codec.shard_size()):
            try:
                got = plane.digest_chunks([c for _i, _w, c in records],
                                          codec.shard_size())
            except se.OperationTimedOut:
                got = None  # plane saturated: per-object launch below
        if got is None:
            got = fused.digest_chunks_host([c for _i, _w, c in records],
                                           codec.shard_size())
        for ri, (i, want, _chunk) in enumerate(records):
            if got[ri] != want:
                dead.add(i)
                if corrupt is not None:
                    corrupt.add(i)
                readers[i] = None
                raise se.FileCorrupt(f"shard {i}: bitrot digest mismatch")

    # ------------------------------------------------------------------
    # delete (cmd/erasure-object.go:894-1031)
    # ------------------------------------------------------------------

    def delete_object(self, bucket: str, obj: str,
                      opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self.get_bucket_info(bucket)
        write_quorum = self._write_quorum_meta()

        if opts.versioned and not opts.version_id:
            # Versioned delete without a version: write a delete marker.
            marker = FileInfo(
                volume=bucket, name=obj, version_id=str(uuid.uuid4()),
                deleted=True, mod_time=time.time(),
            )
            with self.nslock.lock(bucket, obj):
                results = parallel_map(
                    [lambda d=d: d.delete_version(bucket, obj, marker) for d in self.drives],
                    deadline=self._meta_deadline(),
                )
                self._meta_invalidate(bucket, obj)
                reduce_write_quorum(results, write_quorum, bucket, obj)
            return ObjectInfo(bucket=bucket, name=obj, version_id=marker.version_id,
                              delete_marker=True, mod_time=marker.mod_time)

        with self.nslock.lock(bucket, obj):
            fi = self._read_quorum_fileinfo(bucket, obj, opts.version_id)
            target = FileInfo(volume=bucket, name=obj, version_id=opts.version_id,
                              data_dir=fi.data_dir)
            results = parallel_map(
                [lambda d=d: d.delete_version(bucket, obj, target) for d in self.drives],
                deadline=self._meta_deadline(),
            )
            self._meta_invalidate(bucket, obj)
            # A drive that never had the version is as good as deleted on it.
            results = [
                None if isinstance(r, (se.FileNotFound, se.FileVersionNotFound)) else r
                for r in results
            ]
            reduce_write_quorum(results, write_quorum, bucket, obj)
        return ObjectInfo(bucket=bucket, name=obj, version_id=opts.version_id,
                          delete_marker=fi.deleted)

    def delete_objects(self, bucket: str, objects: list[ObjectToDelete],
                       opts: ObjectOptions | None = None
                       ) -> list[DeletedObject | Exception]:
        return listing.bulk_delete(self.delete_object, bucket, objects, opts)

    # ------------------------------------------------------------------
    # listing (flat merge; the metacache system layers on top later)
    # ------------------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000) -> ListObjectsInfo:
        self.get_bucket_info(bucket)
        # Marker pushdown (subtree pruning, group-aware delimiter walks):
        # listing.pushdown_stream is the single policy shared by every
        # layer; paginate re-filters either way.
        return listing.paginate_objects(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter),
            lambda name, fi: self._fi_to_object_info(bucket, name, fi),
            prefix, marker, delimiter, max_keys,
        )

    def list_object_versions(self, bucket: str, prefix: str = "", marker: str = "",
                             version_marker: str = "", delimiter: str = "",
                             max_keys: int = 1000) -> ListObjectVersionsInfo:
        self.get_bucket_info(bucket)
        return listing.paginate_versions(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter, version_marker),
            lambda name, fi: self._fi_to_object_info(bucket, name, fi),
            prefix, marker, version_marker, delimiter, max_keys,
        )

    def stream_journals(self, bucket: str, prefix: str = "",
                        start_after: str = "") -> Iterator[tuple[str, XLMeta]]:
        """SORTED (name, elected-journal) stream: per-drive sorted walk_dir
        streams k-way merged with newest-journal election — O(drives)
        memory regardless of namespace size (the reference's metacache
        listPath walk, cmd/metacache-set.go:534 + metacache-entries.go:198;
        replaces the materialized merged_journals map on every hot path).
        Names at or before start_after are skipped WITHOUT parsing their
        journals (cheap resume for heal walks and list markers); each
        drive's walk runs behind a prefetch thread so per-drive I/O
        overlaps (the reference's per-drive WalkDir goroutines)."""
        def drive_stream(d: StorageAPI):
            try:
                # start_after pushes down into the walk (subtree pruning:
                # O(page) resume); the belt-and-braces re-check covers
                # implementations that only best-effort the marker.
                for e in d.walk_dir(bucket, prefix, start_after):
                    if start_after and e.name <= start_after:
                        continue
                    try:
                        meta = XLMeta.parse(e.meta)
                    except se.StorageError:
                        continue  # corrupt copy: other drives elect
                    yield e.name, meta
            except se.StorageError:
                return  # offline/unformatted drive: quorum covers it

        # Per-drive walk deadline: a drive that stalls mid-walk is dropped
        # from the merge (exactly like an offline drive) instead of
        # wedging the whole listing/heal sweep.
        walk_deadline = _health.fleet_deadlines(self.drives)[2]
        return listing.merge_journal_streams(
            [listing.prefetch_stream(drive_stream(d), deadline=walk_deadline)
             for d in self.drives])

    def merged_journals(self, bucket: str, prefix: str) -> dict[str, XLMeta]:
        """Materialized journal map — O(namespace) memory; only for small
        bounded uses (tests, sys buckets). Hot paths use stream_journals."""
        return dict(self.stream_journals(bucket, prefix))

    # ------------------------------------------------------------------
    # tagging (cmd/erasure-object.go:1158)
    # ------------------------------------------------------------------

    def put_object_tags(self, bucket: str, obj: str, tags: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.put_object_metadata(
            bucket, obj, {"x-amz-tagging": tags or None}, opts)

    def put_object_metadata(self, bucket: str, obj: str,
                            updates: dict[str, str | None],
                            opts: ObjectOptions | None = None) -> ObjectInfo:
        """Quorum metadata-only update of one version (reference
        PutObjectMetadata/PutObjectTags, cmd/erasure-object.go:1031,1158).
        A None value deletes the key."""
        opts = opts or ObjectOptions()
        fi = self._read_quorum_fileinfo(bucket, obj, opts.version_id)
        if fi.deleted:
            raise se.ObjectNotFound(bucket, obj)
        for k, v in updates.items():
            if v is None:
                fi.metadata.pop(k, None)
            else:
                fi.metadata[k] = v
        results = parallel_map(
            [
                lambda d=d, f=_clone_for_drive(fi, i + 1): d.write_metadata(bucket, obj, f)
                for i, d in enumerate(
                    shuffle_by_distribution(self.drives, fi.erasure.distribution)
                    if fi.erasure.distribution else self.drives
                )
            ],
            deadline=self._meta_deadline(),
        )
        self._meta_invalidate(bucket, obj)
        reduce_write_quorum(results, self._write_quorum_meta(), bucket, obj)
        return self._fi_to_object_info(bucket, obj, fi)

    def transition_version(self, bucket: str, obj: str, version_id: str,
                           tier_name: str, tier_key: str,
                           storage_class: str = "",
                           expect_mod_time: float | None = None) -> None:
        """Mark a version transitioned: metadata keeps size/etag/parts (the
        part layout drives multipart-SSE decryption on read-through) but
        data_dir empties and the shard data is reclaimed (write_metadata
        deletes the orphaned data dir on each drive) — reference transition
        state in xl.meta v2 + free of the data parts.

        expect_mod_time: abort if the version changed since the caller
        copied its data to the tier (the scanner's TOCTOU guard)."""
        with self.nslock.lock(bucket, obj):
            fi = self._read_quorum_fileinfo(bucket, obj, version_id)
            if fi.deleted:
                raise se.ObjectNotFound(bucket, obj)
            if fi.inline_data:
                raise se.ObjectError(
                    bucket, obj, "inline objects are too small to tier")
            if (expect_mod_time is not None
                    and abs(fi.mod_time - expect_mod_time) > 1e-6):
                raise se.ObjectError(
                    bucket, obj,
                    "object changed while its data was being tiered")
            fi.metadata["x-mtpu-internal-transition-tier"] = tier_name
            fi.metadata["x-mtpu-internal-transition-key"] = tier_key
            if storage_class:
                fi.metadata["x-amz-storage-class"] = storage_class
            fi.data_dir = ""
            results = parallel_map(
                [lambda d=d, f=_clone_for_drive(fi, i + 1):
                 d.write_metadata(bucket, obj, f)
                 for i, d in enumerate(
                     shuffle_by_distribution(self.drives, fi.erasure.distribution)
                     if fi.erasure.distribution else self.drives)],
                deadline=self._meta_deadline(),
            )
            self._meta_invalidate(bucket, obj)
            reduce_write_quorum(results, self._write_quorum_meta(), bucket, obj)

    def restore_transitioned(self, bucket: str, obj: str,
                             version_id: str = "") -> None:
        """Re-materialize a transitioned version's data from its tier
        (RestoreObject role): shards are rebuilt locally and the transition
        markers are dropped; the tier copy is removed. The conditional PUT
        (expect_mod_time, checked under the commit lock) guarantees a
        concurrent client write is never clobbered by stale tier data."""
        from minio_tpu.scanner import tiers as tiermod
        from minio_tpu.utils.streams import IterReader

        fi = self._read_quorum_fileinfo(bucket, obj, version_id)
        tier_name = fi.metadata.get("x-mtpu-internal-transition-tier", "")
        if not tier_name or fi.data_dir:
            return  # nothing to restore
        if len(fi.parts) > 1 and any(
                k.endswith("-sse") for k in fi.metadata):
            # Multipart SSE relies on the original per-part boundaries,
            # which a restore-as-single-part would destroy; reads already
            # stream through the tier, so refuse rather than corrupt.
            raise se.ObjectError(
                bucket, obj, "restore of multipart SSE objects is not "
                "supported; reads stream through the tier")
        reg = tiermod.global_registry()
        if reg is None:
            raise se.ObjectError(bucket, obj, "no tier registry configured")
        tier = reg.get(tier_name)
        key = fi.metadata.get("x-mtpu-internal-transition-key", "")

        meta = {k: v for k, v in fi.metadata.items()
                if not k.startswith("x-mtpu-internal-transition-")}
        opts = ObjectOptions(version_id=fi.version_id,
                             versioned=bool(fi.version_id),
                             user_defined=meta,
                             expect_mod_time=fi.mod_time)
        self.put_object(bucket, obj, IterReader(tier.get(key)), fi.size, opts)
        tier.remove(key)

    def get_object_tags(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> str:
        info = self.get_object_info(bucket, obj, opts)
        return info.user_defined.get("x-amz-tagging", "")

    def delete_object_tags(self, bucket: str, obj: str,
                           opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.put_object_tags(bucket, obj, "", opts)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _native_fan_out(
        self,
        shuffled: list[StorageAPI],
        vol: str,
        rel: str,
        data: BinaryIO,
        size: int,
        codec: ErasureCodec,
        write_quorum: int,
        bucket: str,
        obj: str,
        initial: bytes = b"",
    ) -> tuple[int, str, list[Exception | None]] | None:
        """Native serving lane for the PUT fan-out: the whole block→shard→
        bitrot-frame→per-drive-file pipeline runs in ONE GIL-released C++
        call per segment (native/mtpu_native.cc mtpu_encode_part — the
        reference's native Erasure.Encode + parallelWriter + hash.Reader
        path, cmd/erasure-encode.go:36-109, pkg/hash/reader.go:37).

        Engaged when the set hashes with host-native sip256 and every drive
        is local; returns None to fall through to the device-codec fan-out
        otherwise. The per-call disk-ID guard is deferred to the commit
        (rename_data IS guarded), matching the quorum outcome either way."""
        from minio_tpu.native import plane

        if (self.bitrot_algorithm not in ("sip256", "highwayhash256")
                or not plane.available()):
            return None
        if codec.block_size % 64:
            return None  # md5 segment chaining needs 64-byte alignment
        paths = _local_shard_paths(shuffled, vol, rel)
        if paths is None:
            return None
        import os as _os
        from concurrent.futures import ThreadPoolExecutor

        enc = plane.PartEncoder(paths, codec.k, codec.m, codec.block_size,
                                algorithm=self.bitrot_algorithm)
        for i, p in enumerate(paths):
            try:
                _os.makedirs(_os.path.dirname(p), exist_ok=True)
            except OSError:
                # One bad drive (read-only/full fs) degrades to quorum
                # accounting, exactly like a failed writer thread in the
                # Python lane — never aborts the whole PUT.
                enc.fail_drive(i)
        seg = plane.seg_blocks(codec.block_size) * codec.block_size
        total = 0
        buf = initial
        # One-segment pipeline: the GIL-released C call for segment N runs
        # in a worker thread while this thread reads segment N+1 from the
        # client — the native lane's form of the P2 read/encode overlap
        # (the Python lane's dispatch-ahead, cmd/erasure-encode.go:80-107).
        with ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="native-encode") as ex:
            fut = None
            while True:
                want = seg - len(buf)
                if size >= 0:
                    want = min(want, size - total - len(buf))
                got = _read_full(data, want) if want > 0 else b""
                # Only the first segment carries a caller-consumed prefix;
                # every later segment hands the read buffer to the C call
                # as-is (ctypes borrows bytes zero-copy) — the
                # unconditional append here was a whole-segment memcpy per
                # segment.
                chunk = buf + got if buf else got
                final = (len(got) < want
                         or (size >= 0 and total + len(chunk) >= size)
                         or (size < 0 and len(chunk) < seg))
                try:
                    if fut is not None:
                        fut.result()  # segment N-1 fully written
                    fut = ex.submit(obs.ctx_wrap(enc.feed), chunk, final)
                    if final:
                        fut.result()
                except OSError as e:
                    raise se.FaultyDisk(f"native encode: {e}") from e
                total += len(chunk)
                alive = sum(1 for lost in enc.errors if not lost)
                if alive < write_quorum:
                    raise se.InsufficientWriteQuorum(
                        bucket, obj, "write fan-out lost quorum")
                if final:
                    break
                buf = b""
        errs: list[Exception | None] = [
            se.FaultyDisk(f"native shard write failed: {paths[i]}")
            if lost else None
            for i, lost in enumerate(enc.errors)
        ]
        return total, enc.md5_hex, errs

    def _fan_out_encode(
        self,
        shuffled: list[StorageAPI],
        vol: str,
        rel: str,
        data: BinaryIO,
        size: int,
        codec: ErasureCodec,
        write_quorum: int,
        bucket: str,
        obj: str,
        initial: bytes = b"",
    ) -> tuple[int, str, list[Exception | None]]:
        """Stream `data` through the batched codec, fanning bitrot-framed
        shards to one create_file per drive (the io.Pipe + goroutine fan-out
        of cmd/erasure-encode.go:36-70, collapsed into queues). Returns
        (bytes consumed, md5 hex, per-drive errors). `initial` is a prefix
        the caller already consumed from `data`.

        The all-local sip256 configuration takes the native C++ lane
        instead (_native_fan_out); this Python/device path serves
        accelerator-fused digests and remote-drive topologies."""
        t_enc = time.perf_counter()
        native = self._native_fan_out(shuffled, vol, rel, data, size, codec,
                                      write_quorum, bucket, obj, initial)
        if native is not None:
            self._note_encode_rate(native[0], time.perf_counter() - t_enc)
            return native
        qs: list[queue.Queue] = [queue.Queue(maxsize=8) for _ in range(self.n)]
        errs: list[Exception | None] = [None] * self.n
        # A writer thread wedged inside a hung create_file stops draining
        # its queue; the producer notices the queue staying full past the
        # data deadline, marks the drive timed out, and stops feeding it —
        # the PUT then completes at quorum (the hung thread is a daemon,
        # accounted as leaked).
        gave_up = [False] * self.n
        put_timeout = self._data_deadline()

        def feed(i: int, item) -> None:
            if gave_up[i]:
                return
            try:
                qs[i].put(item, timeout=put_timeout)
            except queue.Full:
                gave_up[i] = True
                if errs[i] is None:
                    errs[i] = se.OperationTimedOut(
                        msg=f"drive shard write stalled > {put_timeout:.1f}s")
                note_leaked_worker()

        def writer(i: int, drive: StorageAPI):
            def gen():
                while True:
                    item = qs[i].get()
                    if item is _WRITE_SENTINEL:
                        return
                    digest, chunk = item  # [digest][chunk] record, unconcatenated
                    if digest is None:
                        # Host-hash algorithms digest HERE, in the per-drive
                        # thread (native call releases the GIL), not in the
                        # single producer thread — n drives hash in
                        # parallel, the reference's per-goroutine
                        # bitrot-writer layout (cmd/bitrot-streaming.go:46).
                        # Memoryview chunks pass straight through: every
                        # digest impl takes bytes-like buffers (the native
                        # kernels borrow writable views via from_buffer).
                        digest = bitrot_algo.digest(chunk)
                    yield digest
                    yield chunk

            try:
                drive.create_file(vol, rel, gen())
            except Exception as e:  # noqa: BLE001
                errs[i] = e
                # Drain so the producer never blocks on a dead drive.
                while qs[i].get() is not _WRITE_SENTINEL:
                    pass

        threads = [
            threading.Thread(target=obs.ctx_wrap(writer), args=(i, d),
                             daemon=True)
            for i, d in enumerate(shuffled)
        ]
        for t in threads:
            t.start()

        # Device-fused digests share the encode launch (ops/fused.py); any
        # other algorithm is hashed host-side per chunk.
        use_fused = self.bitrot_algorithm == "mxsum256"
        # Batched data plane (MTPU_BATCHED_DATAPLANE=1): concurrent PUTs
        # coalesce their encode launches; per-object dispatch is the
        # fallback (and the bit-exactness oracle). Parity-less
        # geometries stay per-object (nothing to coalesce but digests).
        plane = dataplane.maybe_plane() if codec.m else None

        def begin_encode(blocks: list[bytes]):
            if plane is not None and plane.accepts_chunk(
                    -(-max(len(b) for b in blocks) // codec.k)):
                return plane.begin_encode(codec.k, codec.m,
                                          codec.block_size, blocks,
                                          with_digests=use_fused)
            return codec.begin_encode(blocks, with_digests=use_fused)
        bitrot_algo = bitrot.get_algorithm(self.bitrot_algorithm)
        md5 = hashlib.md5()
        total = 0
        # Dispatch-ahead pipeline (P2, SURVEY §2.4): up to PIPELINE batches
        # are in flight on device while the host reads the next batch and
        # fans out completed ones — the reference's read/encode/write
        # overlap (cmd/erasure-encode.go:80-107) via JAX async dispatch.
        pipeline_depth = 3
        pending: list = []

        def drain_one() -> None:
            chunk_rows, dig_rows = pending.pop(0).wait()
            for bi, chunks in enumerate(chunk_rows):
                digs = dig_rows[bi] if dig_rows is not None else None
                for i in range(self.n):
                    # digest None -> the writer thread hashes the chunk.
                    feed(i, (digs[i] if digs is not None else None,
                             chunks[i]))
            alive = sum(1 for e in errs if e is None)
            if alive < write_quorum:
                raise se.InsufficientWriteQuorum(bucket, obj, "write fan-out lost quorum")

        try:
            bs = codec.block_size  # geometry travels with the codec, not self
            batch: list[bytes] = []
            block = initial or _read_full(
                data, min(bs, size) if size >= 0 else bs
            )
            while block:
                md5.update(block)
                total += len(block)
                batch.append(block)
                if len(batch) >= self.batch_blocks:
                    pending.append(begin_encode(batch))
                    batch = []
                    if len(pending) >= pipeline_depth:
                        drain_one()
                remaining = bs if size < 0 else min(bs, size - total)
                block = _read_full(data, remaining)
            if batch:
                pending.append(begin_encode(batch))
            while pending:
                drain_one()
        finally:
            for i, q in enumerate(qs):
                try:
                    q.put(_WRITE_SENTINEL,
                          timeout=0.1 if gave_up[i] else put_timeout)
                except queue.Full:
                    gave_up[i] = True
            # Bounded join: a healthy writer drains to its sentinel well
            # inside the deadline; a wedged one is declared timed out and
            # left behind (daemon) rather than blocking the PUT forever.
            join_end = time.monotonic() + put_timeout
            for i, t in enumerate(threads):
                t.join(timeout=0.1 if gave_up[i]
                       else max(0.1, join_end - time.monotonic()))
                if t.is_alive():
                    gave_up[i] = True
                    if errs[i] is None:
                        errs[i] = se.OperationTimedOut(
                            msg="drive shard writer did not finish")
                        note_leaked_worker()
        self._note_encode_rate(total, time.perf_counter() - t_enc)
        return total, md5.hexdigest(), errs

    def _note_encode_rate(self, nbytes: int, wall: float) -> None:
        """Rolling encode throughput: EWMA over per-fan-out bytes/wall —
        a regression in the codec or shard path shows up in the gauge
        without re-running bench.py."""
        if nbytes <= 0 or wall <= 0.0:
            return
        gibps = nbytes / wall / (1 << 30)
        e = self._encode_gibps
        self._encode_gibps = gibps if e is None else 0.7 * e + 0.3 * gibps
        _ENCODE_GIBPS.set(self._encode_gibps)

    def _inline_commit_fast(self, shuffled, bucket: str, obj: str,
                            fi: FileInfo, raw: bytes, journal):
        """Two-phase inline-PUT commit through the group-commit plane:
        submit the single-journal record to every drive's WAL
        (journal_commit_async — the call rides the full wrapper chain,
        so disk-ID checks, fault injection, and health deadlines all
        interpose), then await every shared-fsync future under the meta
        deadline. Outcomes mirror the sync fan-out: reclaim token or
        per-drive exception values for the quorum reducer.

        The submit side is PURE MEMORY on an unwrapped armed drive (the
        commit prework runs in the committer thread), so submits run
        inline with no pool hop. With the chaos drive wrap armed, an
        injected fault may block the call itself — there the submit
        loop runs under run_bounded, and a wedged loop falls back to
        the deadline'd parallel_map (a re-store after partial
        submission is idempotent: same key, same bytes).

        Returns None to fall back when any drive lacks the two-phase
        entry (remote / unarmed)."""
        fns = []
        for d in shuffled:
            fn = getattr(d, "journal_commit_async", None)
            if fn is None:
                return None
            fns.append(fn)
        futs: list = []

        def submit_all():
            for fn in fns:
                try:
                    f = fn(bucket, obj, fi, raw, meta=journal,
                           defer_reclaim=True)
                except Exception as e:  # noqa: BLE001 - per-drive data
                    futs.append(e)
                    continue
                if f is None:
                    futs.append(None)  # drive not armed: abort fast path
                    return
                futs.append(f)

        from minio_tpu.erasure.sysstore import submits_may_block

        if submits_may_block():
            if not run_bounded(submit_all, self._meta_deadline()):
                return None  # injected hang mid-submit: bounded fallback
        else:
            submit_all()
        if any(f is None for f in futs):
            return None
        deadline = time.monotonic() + self._meta_deadline()
        outcomes: list = []
        for f in futs:
            if isinstance(f, Exception):
                outcomes.append(f)
                continue
            try:
                outcomes.append(
                    f.result(timeout=max(0.0, deadline - time.monotonic())))
            except se.StorageError as e:
                outcomes.append(e)
            except _FutTimeout:
                outcomes.append(se.OperationTimedOut(
                    bucket, obj, "wal group commit exceeded deadline"))
            except Exception as e:  # noqa: BLE001 - per-drive data
                outcomes.append(e)
        return outcomes

    def _check_put_precondition(self, bucket: str, obj: str,
                                opts: ObjectOptions) -> None:
        """Conditional-PUT guard, called INSIDE the commit lock: abort the
        write if the latest (or named) version's mod_time moved since the
        caller observed it (tier restore's lost-update protection)."""
        if opts.expect_mod_time is None:
            return
        try:
            cur = self._read_quorum_fileinfo(bucket, obj, opts.version_id)
        except (se.ObjectNotFound, se.VersionNotFound):
            raise se.ObjectError(
                bucket, obj, "precondition failed: object vanished") from None
        if abs(cur.mod_time - opts.expect_mod_time) > 1e-6:
            raise se.ObjectError(
                bucket, obj, "precondition failed: object changed")

    def _read_quorum_fileinfo(self, bucket: str, obj: str,
                              version_id: str) -> FileInfo:
        sc = self._setcache
        pre_sigs = None
        if sc is not None:
            fi = sc.lookup(bucket, obj, version_id)
            if fi is not None:
                # Signature-validated post-election hit: the N-drive
                # fan-out + election is skipped entirely.
                return fi
            # Signatures BEFORE the election: a mutation racing the
            # fan-out read leaves these stale, so the entry self-
            # invalidates at the next lookup instead of serving the
            # pre-mutation election under post-mutation signatures.
            pre_sigs = sc.snapshot_sigs(bucket, obj, self.drives)
        with obs.span("quorum-read", bucket=bucket, object=obj):
            fi = self._read_quorum_fileinfo_inner(bucket, obj, version_id)
        if sc is not None:
            sc.populate(bucket, obj, version_id, fi, self.drives,
                        sigs=pre_sigs)
        return fi

    def _read_quorum_fileinfo_inner(self, bucket: str, obj: str,
                                    version_id: str) -> FileInfo:
        # Serial reads only while every drive is ONLINE; the loop itself
        # runs in ONE bounded pool worker (run_bounded) so the FIRST hang
        # on an all-local set frees the caller at the deadline and falls
        # back to the deadline'd parallel fan-out — a hung drive there
        # becomes a timeout value the quorum reducers count as failed.
        serial_done = False
        if self._serial_meta_reads and self._drives_all_online():
            # All-local cached journal reads run sequentially; once a
            # strict majority agrees on (mod_time, data_dir, version),
            # the remaining drives cannot change the election — skip
            # them (the shards they hold are addressed by the elected
            # distribution, not by these metadata reads).
            out: dict = {"fi": None, "results": None}

            def serial_election():
                need = self.n // 2 + 1
                results = []
                tally: dict = {}
                for d in self.drives:
                    try:
                        r = d.read_version(bucket, obj, version_id)
                    except Exception as e:  # noqa: BLE001 — per-drive data
                        r = e
                    results.append(r)
                    # Early exit only for live versions: a delete marker's
                    # read quorum depends on the geometry of the NON-deleted
                    # versions other drives may hold, which a partial read
                    # cannot know — markers always take the full election.
                    if isinstance(r, FileInfo) and not r.deleted:
                        s = election_sig(r)
                        tally[s] = tally.get(s, 0) + 1
                        # The read quorum is this geometry's data_blocks,
                        # which can exceed a bare majority (k > n/2+1 at low
                        # parity) — stop only when both are satisfied.
                        k = r.erasure.data_blocks or 0
                        if tally[s] >= max(need, k):
                            # This fi IS the quorum election — re-counting
                            # through find_fileinfo_in_quorum adds nothing.
                            out["fi"] = r
                            return
                out["results"] = results

            if run_bounded(serial_election, self._meta_deadline()):
                if out["fi"] is not None:
                    return out["fi"]
                results = out["results"]
                serial_done = True
        if not serial_done:
            results = parallel_map(
                [lambda d=d: d.read_version(bucket, obj, version_id)
                 for d in self.drives],
                deadline=self._meta_deadline(),
            )
        if all(isinstance(r, se.FileNotFound) for r in results):
            raise se.ObjectNotFound(bucket, obj)
        if any(isinstance(r, se.FileVersionNotFound) for r in results) and not any(
            isinstance(r, FileInfo) for r in results
        ):
            raise se.VersionNotFound(bucket, obj)
        # Geometry majority decides the read quorum.
        ks = [r.erasure.data_blocks for r in results
              if isinstance(r, FileInfo) and not r.deleted and r.erasure.data_blocks]
        read_quorum = max(set(ks), key=ks.count) if ks else self.n // 2
        return find_fileinfo_in_quorum(results, max(1, read_quorum), bucket, obj)

    def latest_fileinfo(self, bucket: str, obj: str,
                        version_id: str = "") -> FileInfo:
        """Quorum-elected FileInfo including delete markers — the existence
        probe pool routing needs (a key whose latest version is a delete
        marker still *lives* here; reference getPoolIdxExisting,
        cmd/erasure-server-pool.go:252)."""
        return self._read_quorum_fileinfo(bucket, obj, version_id)

    def _fi_to_object_info(self, bucket: str, obj: str, fi: FileInfo) -> ObjectInfo:
        return listing.fi_to_object_info(bucket, obj, fi)


def _local_shard_paths(drives: list[StorageAPI], vol: str,
                       rel: str) -> list[str] | None:
    """Absolute shard-file paths when EVERY drive is local (unwrapping
    ONLY the disk-ID decorator); None if any drive is remote or otherwise
    wrapped — the native WRITE lanes (PUT fan-out, heal rebuild) need
    direct file access on all n drives. The GET lane uses the mixed form
    below instead."""
    paths, remotes = _shard_paths_mixed(drives, vol, rel)
    if paths is None or any(r is not None for r in remotes):
        return None
    return paths


def _shard_paths_mixed(drives: list[StorageAPI], vol: str, rel: str
                       ) -> tuple[list[str] | None, list[StorageAPI | None]]:
    """(paths, remotes) for the mixed native GET lane: paths[i] is the
    absolute shard path for a local drive ("" otherwise); remotes[i] is
    the drive object for every NON-local position — those shards prefetch
    their framed ranges through the drive's own read_file_stream, so any
    wrapper (remote client, fault injector) keeps its per-call
    interposition. (None, _) only when a local drive can't map the path
    (invalid name)."""
    from minio_tpu.storage.local import LocalDrive

    paths: list[str] = []
    remotes: list[StorageAPI | None] = []
    for d in drives:
        base = _health.unwrap(d)
        if isinstance(base, LocalDrive):
            try:
                paths.append(base._file_path(vol, rel))
                remotes.append(None)
                continue
            except se.StorageError:
                return None, []
        paths.append("")
        remotes.append(d)
    return paths, remotes


def _yield_block_range(chunks, lo: int, hi: int):
    """Yield [lo, hi) of a decoded block as memoryview slices of its k
    data chunks — the zero-copy replacement for joining the chunks into
    one fresh block buffer and slicing that (two full passes over the
    payload per block on the GET hot path). Trailing shard padding
    falls away because hi is capped at the block's real length."""
    pos = 0
    for c in chunks:
        if pos >= hi:
            return
        end = pos + len(c)
        a = max(lo, pos)
        b = min(hi, end)
        if b > a:
            yield memoryview(c)[a - pos:b - pos]
        pos = end


def _read_exact(f, n: int) -> bytes:
    """Read exactly n bytes from a stream; OSError on early EOF — the
    ONE short-read rule every remote shard reader shares. Returns the
    accumulator bytearray as-is (single-read fast path returns the
    stream's own buffer): consumers take any bytes-like, including the
    native decoder's mem shards (ctypes borrows writable buffers)."""
    first = f.read(n)
    if first and len(first) == n:
        return first
    buf = bytearray(first or b"")
    while len(buf) < n:
        c = f.read(n - len(buf))
        if not c:
            raise OSError("short read")
        buf += c
    return buf


def _fetch_framed(drive: StorageAPI, vol: str, rel: str, lo: int,
                  ln: int, streams: dict | None = None,
                  key: int | None = None) -> bytes | None:
    """Fetch [lo, lo+ln) of a shard file through the drive's stream API
    (ranged RPC for remote drives). None on any failure or short read —
    the caller marks the shard dead and re-selects. When `streams` is
    given, the open stream is cached under `key` across windows (one
    stat/open per shard per GET instead of per window); a failed stream
    is closed and evicted."""
    f = streams.get(key) if streams is not None else None
    opened = f is None
    if f is None:
        try:
            f = drive.read_file_stream(vol, rel)
        except (se.StorageError, OSError):
            return None
        if streams is not None:
            streams[key] = f
    try:
        f.seek(lo)
        buf = _read_exact(f, ln)
        if streams is None:
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
        return buf
    except (se.StorageError, OSError, ValueError):
        if streams is not None:
            streams.pop(key, None)
        try:
            f.close()
        except Exception:  # noqa: BLE001
            pass
        return None


def _clone_for_drive(fi: FileInfo, index: int) -> FileInfo:
    out = fi.clone()
    out.erasure.index = index
    return out


def _validate_bucket_name(bucket: str) -> None:
    if not (3 <= len(bucket) <= 63) or bucket != bucket.lower() or "/" in bucket:
        raise se.BucketNameInvalid(bucket)
    if bucket.startswith(".") or bucket.startswith("-") or bucket.endswith("-"):
        raise se.BucketNameInvalid(bucket)
    if not all(c.isalnum() or c in ".-" for c in bucket):
        raise se.BucketNameInvalid(bucket)


def _validate_object_name(obj: str) -> None:
    if not obj or len(obj) > 1024 or obj.startswith("/"):
        raise se.ObjectNameInvalid("", obj)
    parts = obj.split("/")
    if any(p in ("..", "") for p in parts[:-1]) or parts[-1] == "..":
        raise se.ObjectNameInvalid("", obj)
