"""Quorum-replicated system-config store.

Role-equivalent of MinIO storing its own state as objects under the
reserved `.minio.sys` bucket (SURVEY §5.4 — config, IAM, bucket metadata
all live *inside* the system so node loss loses nothing). Small configs
don't need erasure striping: each document is mirrored to every drive of
the first set via write_all, and reads elect content by majority, so
config survives the same drive losses the data path does.
"""

from __future__ import annotations

import hashlib
import os
import time
from concurrent.futures import TimeoutError as _FutTimeout

from minio_tpu.erasure.metadata import parallel_map, run_bounded
from minio_tpu.utils import errors as se
from minio_tpu.utils.quorum import reduce_write_quorum

SYS_VOL = ".mtpu.sys"
CONFIG_PREFIX = "config"


def submits_may_block() -> bool:
    """True when a two-phase group-commit SUBMIT can block the calling
    thread: a fault injector sits in the drive chain (the chaos wrap,
    or any directly-constructed NaughtyDisk in this process). Plain
    drives keep the pure-memory inline submit."""
    if os.environ.get("MTPU_CHAOS_DRIVE_WRAP", "") == "1":
        return True
    from minio_tpu.chaos import naughty

    return naughty.any_present()


def mirror_write_all(drives, vol: str, rel: str, data: bytes,
                     deadline: float) -> list:
    """Mirrored small-file write across a drive set through the WAL
    blob lane when available: submit to every armed drive's group
    commit (pure memory — the ack rides ONE shared fsync per drive per
    batch), then await all futures under the deadline; drives without
    the two-phase entry (remote, unarmed) take the classic parallel
    write_all fan-out with its per-file fsync. Returns per-drive
    outcomes (None | Exception) for the caller's quorum reducer — the
    metaplane's answer to sys-file traffic (multipart part journals,
    scanner checkpoints, config docs) competing with foreground acks
    for fsyncs."""
    n = len(drives)
    futs: list = [None] * n
    sync_idx: list[int] = []

    def submit_all():
        for i, d in enumerate(drives):
            fn = getattr(d, "write_all_async", None)
            if fn is None:
                sync_idx.append(i)
                continue
            try:
                f = fn(vol, rel, data)
            except Exception as e:  # noqa: BLE001 - per-drive data
                futs[i] = e
                continue
            if f is None:
                sync_idx.append(i)  # drive not armed: sync fan-out
            else:
                futs[i] = f

    if submits_may_block():
        # An injected fault may hang the submit call itself: bound the
        # loop; a wedged loop degrades every drive to the deadline'd
        # sync fan-out (a duplicate store is idempotent — same bytes).
        if not run_bounded(submit_all, deadline):
            futs = [None] * n
            sync_idx = list(range(n))
    else:
        submit_all()

    outcomes: list = [None] * n
    if sync_idx:
        sync_out = parallel_map(
            [lambda d=drives[i]: d.write_all(vol, rel, data)
             for i in sync_idx],
            deadline=deadline)
        for i, out in zip(sync_idx, sync_out):
            outcomes[i] = out
    end = time.monotonic() + deadline
    for i, f in enumerate(futs):
        if f is None:
            continue
        if isinstance(f, Exception):
            outcomes[i] = f
            continue
        try:
            f.result(timeout=max(0.0, end - time.monotonic()))
        except _FutTimeout:
            outcomes[i] = se.OperationTimedOut(
                msg="wal blob commit exceeded deadline")
        except Exception as e:  # noqa: BLE001 - per-drive data
            outcomes[i] = e
    return outcomes


class SysConfigStore:
    """Mirrored key→bytes store over one drive group (mixin host provides
    `drives` and `_write_quorum_meta()`)."""

    def read_sys_config(self, path: str) -> bytes:
        """Majority-elected content (drives can hold stale generations
        after missing a write), with read-repair: drives whose copy is
        missing or diverges from the elected content get it rewritten
        in-line, so config converges the way object heal converges shards
        (the reference heals `.minio.sys` through the regular object-heal
        path; this store's analogue is repair-on-read)."""
        rel = f"{CONFIG_PREFIX}/{path}"
        results = parallel_map(
            [lambda d=d: d.read_all(SYS_VOL, rel) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        tally: dict[bytes, tuple[int, bytes]] = {}
        for r in results:
            if isinstance(r, (bytes, bytearray)):
                h = hashlib.sha256(r).digest()
                n, _ = tally.get(h, (0, b""))
                tally[h] = (n + 1, bytes(r))
        if not tally:
            if all(isinstance(r, se.FileNotFound) for r in results):
                raise se.FileNotFound(path)
            raise se.InsufficientReadQuorum("", path, "no readable config copy")
        (count, data) = max(tally.values(), key=lambda v: v[0])
        # Repair ONLY when the elected content holds a true write-quorum
        # majority — a plurality elected among a minority of visible
        # drives may be the OLD generation, and overwriting the newer
        # copies with it would roll back an acknowledged write. Below the
        # floor the read stays best-effort and repair waits for a
        # healthier view.
        #
        # Racing a concurrent writer is safe under this floor: every
        # drive this repair touches returned the NEW bytes, i.e. was read
        # AFTER the writer reached it, and every drive backing the old
        # election gets the writer's bytes after our read — so the new
        # generation always keeps >= quorum copies (the repair set is
        # bounded by n - quorum). Known narrow window: a read overlapping
        # a concurrent delete_sys_config can re-create a just-deleted
        # minority copy (no tombstones in this store); sys-config deletes
        # are rare admin operations and the next delete sweeps it.
        if count >= self._write_quorum_meta():
            lag = [d for d, r in zip(self.drives, results)
                   if not (isinstance(r, (bytes, bytearray))
                           and bytes(r) == data)
                   and not isinstance(r, se.DiskNotFound)]
            if lag:
                # Best-effort: a drive that fails the repair write stays
                # divergent and is retried on the next read.
                parallel_map([lambda d=d: d.write_all(SYS_VOL, rel, data)
                              for d in lag],
                             deadline=self._meta_deadline())
        return data

    def write_sys_config(self, path: str, data: bytes) -> None:
        # Blob lane: scanner checkpoints / usage docs / config rides
        # the per-drive group commit when armed — background churn
        # shares the WAL's batched fsync instead of adding a foreground
        # per-file fsync per drive.
        rel = f"{CONFIG_PREFIX}/{path}"
        results = mirror_write_all(self.drives, SYS_VOL, rel, data,
                                   self._meta_deadline())
        reduce_write_quorum(results, self._write_quorum_meta(), SYS_VOL, path)

    def delete_sys_config(self, path: str) -> None:
        rel = f"{CONFIG_PREFIX}/{path}"
        results = parallel_map(
            [lambda d=d: d.delete(SYS_VOL, rel) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        results = [None if isinstance(r, se.FileNotFound) else r
                   for r in results]
        reduce_write_quorum(results, self._write_quorum_meta(), SYS_VOL, path)

    def list_sys_config(self, prefix: str = "") -> list[str]:
        """Merged, sorted keys under prefix (union across drives — a key
        exists if any drive has it; stale deletes resolve on read)."""
        rel = f"{CONFIG_PREFIX}/{prefix}".rstrip("/")
        names: set[str] = set()
        results = parallel_map(
            [lambda d=d: _walk_names(d, rel) for d in self.drives],
            deadline=self._walk_deadline(),
        )
        for r in results:
            if isinstance(r, set):
                names |= r
        strip = len(CONFIG_PREFIX) + 1
        return sorted(n[strip:] for n in names)


def _walk_names(drive, rel: str) -> set:
    out = set()
    try:
        stack = [rel]
        while stack:
            d = stack.pop()
            for name in drive.list_dir(SYS_VOL, d):
                full = f"{d}/{name}" if d else name
                if name.endswith("/"):
                    stack.append(full.rstrip("/"))
                else:
                    out.add(full)
    except (se.FileNotFound, se.VolumeNotFound):
        pass
    return out
