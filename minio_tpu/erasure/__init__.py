"""The erasure-coded object layer (reference L2+L3).

ErasureObjects stripes each object across a set of drives as k data + m
parity shards computed by the TPU codec (ops/rs_xla.py), with streaming
bitrot framing. The layer contracts mirror the reference:
Erasure codec surface (cmd/erasure-coding.go:28), erasureObjects
(cmd/erasure.go:49, cmd/erasure-object.go).
"""

from minio_tpu.erasure.codec import ErasureCodec  # noqa: F401
from minio_tpu.erasure.objects import ErasureObjects  # noqa: F401
from minio_tpu.erasure.pools import ErasureServerPools  # noqa: F401
from minio_tpu.erasure.sets import ErasureSets  # noqa: F401
