"""Shared listing pagination over merged journal maps.

Every layer (one set, a sets group, a pools group) produces the same shape —
object name → version journal, merged by modtime — and pages it with
identical S3 semantics (prefix/marker/delimiter/max-keys). Centralizing the
pagination here is what lets sets and pools reuse one implementation
(the reference's equivalent merge lives in cmd/metacache-entries.go /
cmd/metacache-set.go; the streamed metacache layer can replace the
materialized map later without touching callers).
"""

from __future__ import annotations

from typing import Callable, Iterator

from minio_tpu.erasure.types import (
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
)
from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.storage.xlmeta import XLMeta
from minio_tpu.utils import errors as se


def fi_to_object_info(bucket: str, obj: str, fi: FileInfo) -> ObjectInfo:
    """FileInfo -> ObjectInfo (reference fileInfo.ToObjectInfo,
    cmd/erasure-metadata.go:44). Pure conversion, shared by every layer."""
    return ObjectInfo(
        bucket=bucket,
        name=obj,
        mod_time=fi.mod_time,
        size=fi.size,
        etag=fi.metadata.get("etag", ""),
        version_id=fi.version_id,
        is_latest=fi.is_latest,
        delete_marker=fi.deleted,
        content_type=fi.metadata.get("content-type", ""),
        user_defined={k: v for k, v in fi.metadata.items()
                      if k not in ("etag", "content-type")},
        parity_blocks=fi.erasure.parity_blocks,
        data_blocks=fi.erasure.data_blocks,
        num_versions=fi.num_versions,
        parts=[(p.number, p.size) for p in fi.parts],
    )


def bulk_delete(delete_object, bucket, objects, opts=None):
    """Per-key delete loop shared by every layer (reference DeleteObjects,
    cmd/erasure-server-pool.go): each key resolves independently; errors are
    returned as values, not raised."""
    from minio_tpu.erasure.types import DeletedObject, ObjectOptions

    out = []
    for o in objects:
        per = ObjectOptions(version_id=o.version_id,
                            versioned=(opts.versioned if opts else False))
        try:
            info = delete_object(bucket, o.object_name, per)
            out.append(DeletedObject(
                object_name=o.object_name, version_id=o.version_id,
                delete_marker=info.delete_marker,
                delete_marker_version_id=info.version_id if info.delete_marker else "",
            ))
        except Exception as e:  # noqa: BLE001 - per-key results
            out.append(e)
    return out


def merge_journal_maps(maps: list[dict[str, XLMeta]]) -> dict[str, XLMeta]:
    """Merge per-source journal maps, newest journal wins per object."""
    merged: dict[str, XLMeta] = {}
    for m in maps:
        for name, meta in m.items():
            cur = merged.get(name)
            if cur is None or journal_newer(meta, cur):
                merged[name] = meta
    return merged


def merge_journal_streams(streams: list) -> "Iterator[tuple[str, XLMeta]]":
    """K-way merge of SORTED (name, XLMeta) streams, newest journal wins
    per name — the cross-set/cross-pool layer of the streamed listing
    (reference merges per-set metacache streams the same way,
    cmd/metacache-server-pool.go:59 / metacache-entries.go:198). Pulls
    lazily: memory is O(streams), not O(namespace)."""
    import heapq

    merged = heapq.merge(*streams, key=lambda t: t[0])
    cur_name: str | None = None
    cur_meta: XLMeta | None = None
    for name, meta in merged:
        if name != cur_name:
            if cur_meta is not None:
                yield cur_name, cur_meta
            cur_name, cur_meta = name, meta
        elif journal_newer(meta, cur_meta):
            cur_meta = meta
    if cur_meta is not None:
        yield cur_name, cur_meta


def grouped_journal_stream(make_stream, prefix: str, start_after: str,
                           delimiter: str):
    """Delimiter-aware journal stream: yields at most ONE member per
    CommonPrefix group. The restart (start_after = group +
    MARKER_GROUP_PAD, pruning the group's whole subtree) fires only when a
    SECOND member of the same group surfaces — single-member groups cost
    nothing extra, so a bucket of 50k one-object "directories" still
    streams in one pass, while a 100k-object group is skipped after two
    reads (reference forward-past behavior, cmd/metacache-entries.go
    filterPrefixes role). Paginate rolls the one yielded member into the
    prefix row exactly as it would the first of thousands. Non-grouped
    names stream through unchanged. `make_stream(start_after)` builds a
    fresh sorted (name, journal) stream."""
    from minio_tpu.storage.api import MARKER_GROUP_PAD

    plen = len(prefix)
    cur_group = None
    while True:
        stream = make_stream(start_after)
        restart = None
        try:
            for name, meta in stream:
                i = name.find(delimiter, plen)
                group = name[: i + len(delimiter)] if i >= 0 else None
                if group is not None and group == cur_group:
                    # Second member of the group: skip the rest of it.
                    restart = group + MARKER_GROUP_PAD
                    break
                cur_group = group
                yield name, meta
        finally:
            close = getattr(stream, "close", None)
            if close is not None:
                close()
        if restart is None:
            return
        start_after = restart


def pushdown_stream(self_stream, prefix: str, marker: str, delimiter: str,
                    version_marker: str = ""):
    """The one marker-pushdown policy every listing layer shares:
    - version_marker set: no pushdown (the key-marker object's remaining
      versions must still stream);
    - delimiter: group-aware stream resuming past whole CommonPrefix
      groups;
    - plain: marker as start_after (subtree pruning in the walk).
    `self_stream(start_after)` builds the layer's sorted journal stream."""
    from minio_tpu.storage.api import group_start_after

    if version_marker:
        return self_stream("")
    if delimiter:
        return grouped_journal_stream(
            self_stream, prefix, group_start_after(marker, delimiter),
            delimiter)
    return self_stream(marker)


def prefetch_stream(gen, depth: int = 32, deadline: float | None = None):
    """Run `gen` in a producer thread behind a bounded queue: the k-way
    listing merge then overlaps every drive's walk I/O instead of pulling
    one drive at a time (the reference's per-drive WalkDir goroutines,
    cmd/metacache-walk.go). Abandoning the wrapper (early page end) stops
    the producer promptly — no thread leaks, no unbounded buffering.

    deadline: max seconds to wait for the NEXT item. A producer stalled
    past it (hung drive mid-walk) ends this stream early — the k-way
    merge then lists at quorum from the remaining drives, exactly as if
    the drive were offline. The stalled producer thread is told to stop
    and leaks only until its blocking read returns."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    DONE = object()

    def pump():
        try:
            for item in gen:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        finally:
            while not stop.is_set():
                try:
                    q.put(DONE, timeout=0.1)
                    return
                except queue.Full:
                    continue

    t = threading.Thread(target=pump, daemon=True, name="walk-prefetch")
    t.start()
    try:
        while True:
            if deadline is None:
                item = q.get()
            else:
                try:
                    item = q.get(timeout=deadline)
                except queue.Empty:
                    return  # producer stalled past the walk deadline
            if item is DONE:
                return
            yield item
    finally:
        stop.set()


def _as_sorted_items(journals) -> "Iterator[tuple[str, XLMeta]]":
    """Paginators accept either a journal map (legacy, materialized) or an
    already-sorted lazy (name, XLMeta) stream — the streamed form is what
    keeps listing at O(page) memory."""
    if isinstance(journals, dict):
        return ((n, journals[n]) for n in sorted(journals))
    return iter(journals)


def journal_newer(a: XLMeta, b: XLMeta) -> bool:
    # Envelope accessors: the quorum comparator runs once per (object,
    # drive) during every listing merge and must not materialize bodies.
    amt, bmt = a.latest_mt, b.latest_mt
    if amt != bmt:
        return amt > bmt
    return a.version_count > b.version_count


def paginate_objects(
    journals,
    to_info: Callable[[str, FileInfo], object],
    prefix: str = "",
    marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
) -> ListObjectsInfo:
    """S3 pagination over a journal map or sorted (name, XLMeta) stream;
    a stream is consumed only up to the page boundary (O(page) work)."""
    objects = []
    prefixes: list[str] = []
    seen_prefix: set[str] = set()
    truncated = False
    next_marker = ""
    for name, meta in _as_sorted_items(journals):
        if _skip_for_marker(name, marker, delimiter):
            continue
        if delimiter:
            rest = name[len(prefix):]
            d = rest.find(delimiter)
            if d >= 0:
                cp = prefix + rest[: d + len(delimiter)]
                if cp not in seen_prefix:
                    if len(objects) + len(seen_prefix) >= max_keys:
                        truncated = True
                        break
                    seen_prefix.add(cp)
                    prefixes.append(cp)
                    next_marker = cp
                continue
        try:
            fi = meta.to_fileinfo("", name, None)
        except se.StorageError:
            continue
        if fi.deleted:
            continue
        if len(objects) + len(seen_prefix) >= max_keys:
            truncated = True
            break
        objects.append(to_info(name, fi))
        next_marker = name
    return ListObjectsInfo(is_truncated=truncated,
                           next_marker=next_marker if truncated else "",
                           objects=objects, prefixes=prefixes)


def iter_entries_from_journals(journals, to_info):
    """Lazy form of entries_from_journals — the metacache block renderer
    consumes this incrementally (O(block) memory, cmd/metacache-stream.go
    progressive-write role)."""
    for name, meta in _as_sorted_items(journals):
        try:
            fi = meta.to_fileinfo("", name, None)
        except se.StorageError:
            continue
        if fi.deleted:
            continue
        yield name, to_info(name, fi)


def iter_version_entries_from_journals(journals, to_info):
    """Lazy version-stream form (delete markers included)."""
    for name, meta in _as_sorted_items(journals):
        try:
            infos = [to_info(name, fi)
                     for fi in meta.list_versions("", name)]
        except se.StorageError:
            continue
        if infos:
            yield name, infos



def paginate_cached(
    entries: list[tuple[str, object]],
    prefix: str = "",
    marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
) -> ListObjectsInfo:
    """paginate_objects over a pre-rendered metacache entry stream —
    continuation pages pay a seek, not a namespace walk."""
    objects = []
    prefixes: list[str] = []
    seen_prefix: set[str] = set()
    truncated = False
    next_marker = ""
    for name, info in entries:
        if not name.startswith(prefix):
            continue
        if _skip_for_marker(name, marker, delimiter):
            continue
        if delimiter:
            rest = name[len(prefix):]
            d = rest.find(delimiter)
            if d >= 0:
                cp = prefix + rest[: d + len(delimiter)]
                if cp not in seen_prefix:
                    if len(objects) + len(seen_prefix) >= max_keys:
                        truncated = True
                        break
                    seen_prefix.add(cp)
                    prefixes.append(cp)
                    next_marker = cp
                continue
        if len(objects) + len(seen_prefix) >= max_keys:
            truncated = True
            break
        objects.append(info)
        next_marker = name
    return ListObjectsInfo(is_truncated=truncated,
                           next_marker=next_marker if truncated else "",
                           objects=objects, prefixes=prefixes)



def paginate_versions_cached(
    entries: list[tuple[str, list]],
    prefix: str = "",
    marker: str = "",
    version_marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
) -> ListObjectVersionsInfo:
    """paginate_versions over a pre-rendered metacache version stream."""
    out = ListObjectVersionsInfo()
    seen_prefix: set[str] = set()
    count = 0
    for name, infos in entries:
        if not name.startswith(prefix):
            continue
        if name == marker and version_marker:
            pass  # resume mid-object below
        elif _skip_for_marker(name, marker, delimiter) or name == marker:
            continue
        if delimiter:
            rest = name[len(prefix):]
            d = rest.find(delimiter)
            if d >= 0:
                cp = prefix + rest[: d + len(delimiter)]
                if cp not in seen_prefix:
                    if count + len(seen_prefix) >= max_keys:
                        out.is_truncated = True
                        return out
                    seen_prefix.add(cp)
                    out.prefixes.append(cp)
                    out.next_marker = cp
                    out.next_version_id_marker = ""
                continue
        skipping = name == marker and bool(version_marker)
        for info in infos:
            if skipping:
                if info.version_id == version_marker:
                    skipping = False
                continue
            if count + len(seen_prefix) >= max_keys:
                out.is_truncated = True
                return out
            out.objects.append(info)
            out.next_marker = name
            out.next_version_id_marker = info.version_id
            count += 1
    out.next_marker = ""
    out.next_version_id_marker = ""
    return out


def _skip_for_marker(name: str, marker: str, delimiter: str) -> bool:
    """Resume semantics: skip names at or before the marker; a marker that
    names a common prefix also skips everything under it (so NextMarker may
    be a CommonPrefix, as in S3)."""
    if not marker:
        return False
    if name <= marker:
        return True
    return bool(delimiter) and marker.endswith(delimiter) and name.startswith(marker)


def paginate_versions(
    journals,
    to_info: Callable[[str, FileInfo], object],
    prefix: str = "",
    marker: str = "",
    version_marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
) -> ListObjectVersionsInfo:
    out = ListObjectVersionsInfo()
    seen_prefix: set[str] = set()
    count = 0
    for name, meta in _as_sorted_items(journals):
        if name == marker and version_marker:
            pass  # resume mid-object below
        elif _skip_for_marker(name, marker, delimiter) or name == marker:
            continue
        if delimiter:
            rest = name[len(prefix):]
            d = rest.find(delimiter)
            if d >= 0:
                cp = prefix + rest[: d + len(delimiter)]
                if cp not in seen_prefix:
                    if count + len(seen_prefix) >= max_keys:
                        out.is_truncated = True
                        return out
                    seen_prefix.add(cp)
                    out.prefixes.append(cp)
                    out.next_marker = cp
                    out.next_version_id_marker = ""
                continue
        resuming = name == marker and bool(version_marker)
        skipping = resuming  # drop versions up to and incl. version_marker
        for fi in meta.list_versions("", name):
            if skipping:
                if fi.version_id == version_marker:
                    skipping = False
                continue
            if count + len(seen_prefix) >= max_keys:
                # Markers already name the last emitted item; resume skips
                # through it. Prefixes count against max_keys like versions
                # do (S3 bounds keys + common prefixes together).
                out.is_truncated = True
                return out
            out.objects.append(to_info(name, fi))
            out.next_marker = name
            out.next_version_id_marker = fi.version_id
            count += 1
    out.next_marker = ""
    out.next_version_id_marker = ""
    return out
