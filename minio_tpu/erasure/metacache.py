"""Metacache — persisted listing streams for resumable pagination.

Role-equivalent of cmd/metacache-stream.go:57 / metacache-bucket.go:43 /
metacache-set.go: the first page of a large listing walks the drives once,
and the merged, sorted result is persisted as a msgpack stream object under
the system bucket; every continuation page then seeks into the persisted
stream instead of re-walking the namespace. Caches are keyed by
(bucket, prefix), expire by TTL, and are rebuilt transparently whenever a
continuation misses (the token is the S3 marker, so a rebuilt cache
resumes exactly where the client stopped — no wire-format coupling).

Unlike the reference's per-set .metacache files + bucket cache manager +
cross-peer coordination, the stream persists through the same replicated
sys-store the config/IAM already use — one mechanism, cluster-visible,
quorum-durable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time

from minio_tpu.dist.rpc import pack, unpack
from minio_tpu.erasure.types import ObjectInfo
from minio_tpu.utils import errors as se

DEFAULT_TTL = 60.0
_PREFIX = "buckets"


class Metacache:
    def __init__(self, store, ttl: float = DEFAULT_TTL):
        """store: read/write/delete_sys_config provider (the pools)."""
        self._store = store
        self.ttl = ttl
        self.hits = 0
        self.misses = 0
        self._saved_at: dict[tuple[str, str], float] = {}
        self._dirty_at: dict[str, float] = {}

    def mark_dirty(self, bucket: str) -> None:
        """A mutation touched the bucket: cached streams written before
        this instant stop being served (the role the reference's bloom
        cycle plays for metacache invalidation)."""
        self._dirty_at[bucket] = time.time()
        if len(self._dirty_at) > 4096:
            self._dirty_at.clear()

    def _stale(self, bucket: str, created: float) -> bool:
        return created <= self._dirty_at.get(bucket, 0)

    def recently_saved(self, bucket: str, prefix: str,
                       kind: str = "o") -> bool:
        """True while this node wrote the cache within ttl/2 and nothing
        mutated the bucket since — lets the pools skip re-rendering +
        re-persisting the stream on every truncated page-1 request of a
        hot bucket."""
        saved = self._saved_at.get((bucket, prefix, kind), 0)
        return (time.time() - saved < self.ttl / 2
                and not self._stale(bucket, saved))

    def _path(self, bucket: str, prefix: str, kind: str = "o") -> str:
        h = hashlib.sha1(prefix.encode()).hexdigest()[:16]
        return f"{_PREFIX}/{bucket}/metacache/{kind}-{h}"

    # One save/load pair serves both stream kinds; only the entry shape
    # differs ("o": (name, info), "v": (name, [infos])).

    def _encode_entries(self, kind: str, entries: list) -> list:
        if kind == "v":
            return [(n, [dataclasses.asdict(oi) for oi in infos])
                    for n, infos in entries]
        return [(n, dataclasses.asdict(oi)) for n, oi in entries]

    def _decode_entries(self, kind: str, raw_entries: list) -> list:
        if kind == "v":
            return [(n, [ObjectInfo(**d) for d in infos])
                    for n, infos in raw_entries]
        return [(n, ObjectInfo(**d)) for n, d in raw_entries]

    def _save(self, bucket: str, prefix: str, entries: list,
              kind: str, end: str = "") -> None:
        """end != "": the stream was rendered up to a cap — the cache
        covers names <= end only (O(page)-bounded memory; a continuation
        past `end` misses and falls back to the streamed walk)."""
        doc = {
            "v": 1, "bucket": bucket, "prefix": prefix,
            "created": time.time(), "end": end,
            "entries": self._encode_entries(kind, entries),
        }
        try:
            self._store.write_sys_config(
                self._path(bucket, prefix, kind), pack(doc))
            self._saved_at[(bucket, prefix, kind)] = time.time()
            if len(self._saved_at) > 4096:
                self._saved_at.clear()
        except se.StorageError:
            pass  # cache is an optimization; never fail the listing

    def _load(self, bucket: str, prefix: str, kind: str,
              marker: str = "") -> list | None:
        try:
            raw = self._store.read_sys_config(
                self._path(bucket, prefix, kind))
        except se.StorageError:
            self.misses += 1
            return None
        try:
            doc = unpack(raw)
            if (doc.get("v") != 1 or doc.get("bucket") != bucket
                    or doc.get("prefix") != prefix):
                self.misses += 1
                return None
            created = doc.get("created", 0)
            if time.time() - created > self.ttl or self._stale(bucket, created):
                self.drop(bucket, prefix, kind)
                self.misses += 1
                return None
            end = doc.get("end", "")
            if end and marker >= end:
                # Partial stream exhausted: the continuation must walk.
                self.misses += 1
                return None
            out = self._decode_entries(kind, doc["entries"])
        except (ValueError, TypeError, KeyError):
            self.misses += 1
            return None
        self.hits += 1
        return out, end

    def drop(self, bucket: str, prefix: str = "", kind: str = "o") -> None:
        try:
            self._store.delete_sys_config(self._path(bucket, prefix, kind))
        except se.StorageError:
            pass

    # -- public surface --

    def save(self, bucket: str, prefix: str,
             entries: list[tuple[str, ObjectInfo]], end: str = "") -> None:
        self._save(bucket, prefix, entries, "o", end)

    def load(self, bucket: str, prefix: str, marker: str = ""
             ) -> tuple[list, str] | None:
        """-> (entries, end) or None; end != "" marks a partial stream —
        a page that drains the entries without filling up must fall back
        to the walk (names past `end` exist but aren't cached)."""
        return self._load(bucket, prefix, "o", marker)

    def save_versions(self, bucket: str, prefix: str,
                      entries: list[tuple[str, list]], end: str = "") -> None:
        self._save(bucket, prefix, entries, "v", end)

    def load_versions(self, bucket: str, prefix: str, marker: str = ""
                      ) -> tuple[list, str] | None:
        return self._load(bucket, prefix, "v", marker)

    def recently_saved_versions(self, bucket: str, prefix: str) -> bool:
        return self.recently_saved(bucket, prefix, "v")
