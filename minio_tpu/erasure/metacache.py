"""Metacache — persisted block-listing streams for resumable pagination.

Role-equivalent of cmd/metacache-stream.go:57 / metacache-bucket.go:43 /
metacache-set.go: the first page of a large listing walks the drives once
and persists the merged, sorted result; every continuation page then
SEEKS into the persisted stream instead of re-walking the namespace.

The stream is stored the way the reference stores it — in blocks, written
progressively while the walk advances — so both sides stay O(block):

    {sys}/buckets/{b}/metacache/{kind}-{h}/idx      block index
    {sys}/buckets/{b}/metacache/{kind}-{h}/blk{i}   ~BLOCK entries each

Page-1 renders the first SYNC_CAP entries synchronously (bounding page-1
latency exactly like the previous single-window design), then a daemon
thread keeps walking and appending blocks up to the stream cap, updating
the index as it goes — a sequential client's continuations ride blocks
the renderer has already written, falling back to the marker-pushdown
walk only when they outrun it. Decoded blocks are memoized in-process, so
a block hit costs a bisect + slice, not a 10k-entry msgpack decode.

Caches are keyed by (bucket, prefix), expire by TTL, and are invalidated
by local mutations (mark_dirty); a renderer that observes its bucket
going dirty abandons the stream without publishing. Cross-node: blocks
travel through the same replicated sys-store as config/IAM; a peer's
re-render is picked up when the local index memo expires (<= TTL) — the
same staleness bound the listing itself has.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import threading
import time
import uuid
from collections import OrderedDict

from minio_tpu.dist.rpc import pack, unpack
from minio_tpu.erasure.types import ObjectInfo
from minio_tpu.utils import errors as se

DEFAULT_TTL = 60.0
_PREFIX = "buckets"
BLOCK = 2000            # entries per persisted block
_IDX_EVERY = 4          # async renderer republishes the index every N blocks
_MEMO_BLOCKS = 48       # decoded-block memo bound (O(blocks), not namespace)


class CacheGone(Exception):
    """A block vanished/changed generation mid-page: caller re-walks."""


class Metacache:
    def __init__(self, store, ttl: float = DEFAULT_TTL):
        """store: read/write/delete_sys_config provider (the pools)."""
        self._store = store
        self.ttl = ttl
        self.hits = 0
        self.misses = 0
        self._saved_at: dict[tuple, float] = {}
        self._dirty_at: dict[str, float] = {}
        self._memo: "OrderedDict[str, tuple[float, object]]" = OrderedDict()
        self._memo_lock = threading.Lock()
        self._rendering: set[tuple] = set()
        self._render_lock = threading.Lock()
        self._last_read: dict[tuple, float] = {}
        self._closed = False
        # Stamped into every published idx: only the node that rendered a
        # generation may reclaim its replicated-store docs on expiry —
        # another node's clock/TTL view must never delete blocks a peer
        # is mid-publish on (its _rendering set is invisible here).
        self._owner = uuid.uuid4().hex[:16]

    # Background rendering continues only while someone keeps reading the
    # stream (the reference's metacache likewise stops feeding listings
    # nobody consumes); a page-1-only client costs one sync render, not a
    # full-namespace walk.
    RENDER_IDLE_ABANDON = 10.0

    def close(self) -> None:
        self._closed = True

    # -- invalidation ------------------------------------------------------

    def mark_dirty(self, bucket: str) -> None:
        """A mutation touched the bucket: streams rendered before this
        instant stop being served (the role the reference's bloom cycle
        plays for metacache invalidation)."""
        self._dirty_at[bucket] = time.time()
        if len(self._dirty_at) > 4096:
            self._dirty_at.clear()

    def _stale(self, bucket: str, created: float) -> bool:
        return created <= self._dirty_at.get(bucket, 0)

    def recently_saved(self, bucket: str, prefix: str,
                       kind: str = "o") -> bool:
        """True while this node rendered the stream within ttl/2 and
        nothing mutated the bucket since — page-1 requests of a hot
        bucket skip re-rendering."""
        saved = self._saved_at.get((bucket, prefix, kind), 0)
        return (time.time() - saved < self.ttl / 2
                and not self._stale(bucket, saved))

    def recently_saved_versions(self, bucket: str, prefix: str) -> bool:
        return self.recently_saved(bucket, prefix, "v")

    # -- paths / codec -----------------------------------------------------

    def _base(self, bucket: str, prefix: str, kind: str) -> str:
        h = hashlib.sha1(prefix.encode()).hexdigest()[:16]
        return f"{_PREFIX}/{bucket}/metacache/{kind}-{h}"

    def _encode_entries(self, kind: str, entries: list) -> list:
        if kind == "v":
            return [(n, [dataclasses.asdict(oi) for oi in infos])
                    for n, infos in entries]
        return [(n, dataclasses.asdict(oi)) for n, oi in entries]

    def _decode_entries(self, kind: str, raw_entries: list) -> list:
        if kind == "v":
            return [(n, [ObjectInfo(**d) for d in infos])
                    for n, infos in raw_entries]
        return [(n, ObjectInfo(**d)) for n, d in raw_entries]

    # -- memoized sys-store docs ------------------------------------------

    def _memo_get(self, path: str, created: float):
        with self._memo_lock:
            hit = self._memo.get(path)
            if hit is not None and hit[0] == created:
                self._memo.move_to_end(path)
                return hit[1]
        return None

    def _memo_put(self, path: str, created: float, value) -> None:
        with self._memo_lock:
            self._memo[path] = (created, value)
            self._memo.move_to_end(path)
            while len(self._memo) > _MEMO_BLOCKS:
                self._memo.popitem(last=False)

    def _memo_drop_prefix(self, base: str) -> None:
        with self._memo_lock:
            for k in [k for k in self._memo if k.startswith(base)]:
                del self._memo[k]

    # -- render ------------------------------------------------------------

    def render(self, bucket: str, prefix: str, entry_stream, kind: str = "o",
               sync_cap: int = 10_000, stream_cap: int = 1_000_000) -> None:
        """Persist `entry_stream` (sorted (name, info) iterator) as a
        block stream. The first sync_cap entries are written before this
        returns; a daemon thread continues up to stream_cap. A renderer
        is already running or recently finished -> no-op."""
        key = (bucket, prefix, kind)
        with self._render_lock:
            if self._rendering and key in self._rendering:
                return
            self._rendering.add(key)
        created = time.time()
        base = self._base(bucket, prefix, kind)
        # A previous generation may have more blocks than this render
        # will produce — remember how many so the final publish can sweep
        # the stale tail (a shrunken namespace must not leave orphans).
        old_blocks = 0
        with self._memo_lock:
            prev = self._memo.get(f"{base}/idx")
        if prev is not None:
            old_blocks = int(prev[1].get("blocks", 0))
        else:
            try:
                old = unpack(self._store.read_sys_config(f"{base}/idx"))
                old_blocks = int(old.get("blocks", 0))
            except (se.StorageError, ValueError, TypeError):
                pass
        state = {"starts": [], "blocks": 0, "count": 0,
                 "old_blocks": old_blocks}
        try:
            done = self._render_some(bucket, base, kind, created,
                                     entry_stream, state,
                                     limit=min(sync_cap, stream_cap))
            finished = done or state["count"] >= stream_cap
            self._publish_idx(base, created, state, complete=done,
                              final=finished)
            self._saved_at[key] = time.time()
            if len(self._saved_at) > 4096:
                self._saved_at.clear()
            if finished:
                with self._render_lock:
                    self._rendering.discard(key)
                return
        except Exception:   # noqa: BLE001 — cache is an optimization
            with self._render_lock:
                self._rendering.discard(key)
            return

        self._last_read.setdefault(key, time.time())

        def bg():
            finished = False
            try:
                while not self._closed:
                    if self._stale(bucket, created):
                        return      # bucket mutated: abandon silently
                    if time.time() - created > self.ttl:
                        return      # generation expired: unservable
                    if (time.time() - self._last_read.get(key, 0)
                            > self.RENDER_IDLE_ABANDON):
                        return      # no readers: stop walking
                    done = self._render_some(
                        bucket, base, kind, created, entry_stream, state,
                        limit=min(_IDX_EVERY * BLOCK,
                                  stream_cap - state["count"]))
                    finished = done or state["count"] >= stream_cap
                    self._publish_idx(base, created, state, complete=done,
                                      final=finished)
                    if finished:
                        return
            except Exception:   # noqa: BLE001 — drives may be closing
                pass
            finally:
                if not finished and not self._closed:
                    # Abandoned mid-stream: the final sweep never ran, so
                    # reclaim the previous generation's tail now — those
                    # blocks are beyond this idx's range and would
                    # otherwise leak in the replicated store forever.
                    for i in range(state["blocks"],
                                   state.get("old_blocks", 0)):
                        try:
                            self._store.delete_sys_config(f"{base}/blk{i}")
                        except se.StorageError:
                            pass
                with self._render_lock:
                    self._rendering.discard(key)

        threading.Thread(target=bg, daemon=True,
                         name=f"metacache-{bucket}").start()

    def _render_some(self, bucket, base, kind, created, entry_stream,
                     state, limit: int) -> bool:
        """Consume up to `limit` entries into blocks; True when the
        stream ended."""
        taken = 0
        buf: list = []
        for entry in entry_stream:
            buf.append(entry)
            taken += 1
            if len(buf) >= BLOCK:
                self._write_block(base, kind, created, state, buf)
                buf = []
            if taken >= limit:
                if buf:
                    self._write_block(base, kind, created, state, buf)
                return False
        if buf:
            self._write_block(base, kind, created, state, buf)
        return True

    def _write_block(self, base, kind, created, state, buf) -> None:
        i = state["blocks"]
        path = f"{base}/blk{i}"
        doc = {"v": 2, "created": created,
               "entries": self._encode_entries(kind, buf)}
        self._store.write_sys_config(path, pack(doc))
        self._memo_put(path, created, list(buf))
        state["starts"].append(buf[0][0])
        state["blocks"] += 1
        state["count"] += len(buf)

    def _publish_idx(self, base, created, state, complete: bool,
                     final: bool = False) -> None:
        doc = {"v": 2, "created": created, "starts": list(state["starts"]),
               "blocks": state["blocks"], "complete": complete,
               "owner": self._owner}
        self._store.write_sys_config(f"{base}/idx", pack(doc))
        self._memo_put(f"{base}/idx", created, doc)
        if final:
            # Sweep blocks of the previous (longer) generation.
            for i in range(state["blocks"], state.get("old_blocks", 0)):
                try:
                    self._store.delete_sys_config(f"{base}/blk{i}")
                except se.StorageError:
                    pass

    # -- page reads --------------------------------------------------------

    def _load_idx(self, bucket: str, prefix: str, kind: str):
        self._last_read[(bucket, prefix, kind)] = time.time()
        if len(self._last_read) > 4096:
            # Evict the oldest half — a blanket clear() would zero the
            # read clocks of every in-flight renderer and idle-abandon
            # them all at once.
            for k, _ in sorted(self._last_read.items(),
                               key=lambda kv: kv[1])[:2048]:
                self._last_read.pop(k, None)
        base = self._base(bucket, prefix, kind)
        # Any memoized generation within ttl and not dirty serves; a
        # peer's newer render is picked up when this expires.
        with self._memo_lock:
            hit = self._memo.get(f"{base}/idx")
        if hit is not None:
            created, doc = hit
            if (time.time() - created <= self.ttl
                    and not self._stale(bucket, created)):
                return doc
        try:
            raw = self._store.read_sys_config(f"{base}/idx")
            doc = unpack(raw)
        except (se.StorageError, ValueError, TypeError):
            return None
        created = doc.get("created", 0)
        if (doc.get("v") != 2 or time.time() - created > self.ttl
                or self._stale(bucket, created)):
            # Expired/stale generation: always reclaim the in-memory memo;
            # the REPLICATED docs are deleted only by the node that
            # rendered them (owner stamp) and only while no local renderer
            # is mid-publish of a new generation — a peer's expiry view
            # must not delete blocks another node just published under a
            # fresh idx (per-node _rendering/_dirty_at are invisible
            # cross-node; generation checks keep correctness, but the
            # deletes would degrade its continuations to full walks).
            # Hard-expired generations (owner restarted/died: its uuid is
            # gone forever) are fair game for ANY node — no peer can be
            # mid-render of something 10 TTLs old, and without this
            # escape hatch a dead owner's blocks would leak in the
            # replicated store indefinitely.
            self._memo_drop_prefix(base)
            with self._render_lock:
                rendering = (bucket, prefix, kind) in self._rendering
            hard_expired = time.time() - created > 10 * self.ttl
            if not rendering and (doc.get("owner") == self._owner
                                  or hard_expired):
                self.drop(bucket, prefix, kind)
            return None
        self._memo_put(f"{base}/idx", created, doc)
        return doc

    def _load_block(self, base: str, i: int, created: float, kind: str):
        path = f"{base}/blk{i}"
        hit = self._memo_get(path, created)
        if hit is not None:
            return hit
        try:
            doc = unpack(self._store.read_sys_config(path))
        except (se.StorageError, ValueError, TypeError):
            raise CacheGone(path) from None
        if doc.get("created") != created:
            raise CacheGone(path)
        entries = self._decode_entries(kind, doc["entries"])
        self._memo_put(path, created, entries)
        return entries

    def entries_from(self, bucket: str, prefix: str, marker: str = "",
                     kind: str = "o"):
        """-> (iterator over (name, info) from the block containing
        `marker`, complete: bool) or None. The iterator raises CacheGone
        if a block vanished/changed generation mid-page; `complete` False
        means the stream was capped — a page that drains the iterator
        without filling must fall back to the walk."""
        idx = self._load_idx(bucket, prefix, kind)
        if idx is None or not idx.get("blocks"):
            self.misses += 1
            return None
        starts = idx["starts"]
        # Rightmost block whose first name <= marker. A marker past the
        # rendered range lands in the final block and filters to empty;
        # complete=False then routes the caller to the walk, so a capped
        # stream can never masquerade as end-of-bucket.
        b0 = max(0, bisect.bisect_right(starts, marker) - 1) if marker else 0
        base = self._base(bucket, prefix, kind)
        created = idx["created"]

        def gen():
            for bi in range(b0, idx["blocks"]):
                for item in self._load_block(base, bi, created, kind):
                    yield item

        self.hits += 1
        return gen(), bool(idx["complete"])

    def stream_complete(self, bucket: str, prefix: str = "",
                        kind: str = "o") -> bool:
        """Public completeness probe: does a live (unexpired, non-stale)
        stream cover the whole namespace? Benchmarks and operators poll
        this instead of reaching into _load_idx."""
        idx = self._load_idx(bucket, prefix, kind)
        return bool(idx and idx.get("complete"))

    # -- drop --------------------------------------------------------------

    def drop(self, bucket: str, prefix: str = "", kind: str = "o") -> None:
        base = self._base(bucket, prefix, kind)
        idx = None
        try:
            idx = unpack(self._store.read_sys_config(f"{base}/idx"))
        except (se.StorageError, ValueError, TypeError):
            pass
        try:
            self._store.delete_sys_config(f"{base}/idx")
        except se.StorageError:
            pass
        for i in range(int(idx.get("blocks", 0)) if idx else 0):
            try:
                self._store.delete_sys_config(f"{base}/blk{i}")
            except se.StorageError:
                pass
        self._memo_drop_prefix(base)
