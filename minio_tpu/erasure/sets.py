"""ErasureSets — N independent erasure stripes behind one object namespace.

Role-equivalent of erasureSets (cmd/erasure-sets.go:55): objects are routed
to a set by sipHashMod(key, setCount, deploymentID) (:697-736), bucket
operations fan out to every set, listings are a merged view across sets.
Each set is a full ErasureObjects engine — quorums, healing and multipart
stay per-set, exactly the reference's layering.
"""

from __future__ import annotations

from typing import BinaryIO, Iterator

from minio_tpu.erasure import listing
from minio_tpu.erasure.format import FormatInfo, init_format_erasure
from minio_tpu.erasure.healing import HealResultItem
from minio_tpu.erasure.metadata import parallel_map
from minio_tpu.erasure.objects import ErasureObjects
from minio_tpu.erasure.types import (
    BucketInfo,
    CompletePart,
    DeletedObject,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    MultipartInfo,
    ObjectInfo,
    ObjectOptions,
    ObjectToDelete,
    PartInfoResult,
)
from minio_tpu.storage.api import StorageAPI
from minio_tpu.storage.xlmeta import XLMeta
from minio_tpu.utils.siphash import sip_hash_mod


class ErasureSets:
    def __init__(
        self,
        drives: list[StorageAPI],
        set_drive_count: int | None = None,
        parity: int | None = None,
        fmt: FormatInfo | None = None,
        enable_mrf: bool = False,
        can_format_fresh: bool = True,
        **set_kwargs,
    ):
        set_drive_count = set_drive_count or len(drives)
        if fmt is None:
            fmt = init_format_erasure(drives, set_drive_count,
                                      can_format_fresh=can_format_fresh)
            # Bind each drive to its slot UUID: a swapped/replugged disk
            # surfaces as DiskNotFound on the next guarded call
            # (cmd/xl-storage-disk-id-check.go:64 role) — then stack the
            # drive-resilience plane on top: per-op deadlines, the
            # ONLINE/FAULTY/OFFLINE state machine, and the offline probe
            # whose restore drops a healing tracker for the AutoHealer.
            from minio_tpu.storage.healthcheck import wrap_with_healthcheck
            from minio_tpu.storage.idcheck import wrap_with_id_check

            drives = wrap_with_id_check(drives, fmt)
            # Composed chaos plane (docs/CHAOS.md): with
            # MTPU_CHAOS_DRIVE_WRAP=1 each LOCAL drive gets an inert
            # NaughtyDisk between the ID check and the health checker,
            # programmable at runtime through the guarded admin faults
            # endpoint — injected hangs then exercise the real
            # ONLINE→FAULTY→OFFLINE machinery and the sentinel probe.
            from minio_tpu.chaos import naughty as _chaos_naughty

            if _chaos_naughty.wrap_enabled():
                drives = _chaos_naughty.wrap_drives(drives)
            drives = wrap_with_healthcheck(drives, fmt)
        self.format = fmt
        self.deployment_id = fmt.deployment_id
        self.set_count = len(drives) // set_drive_count
        self.set_drive_count = set_drive_count
        self.sets: list[ErasureObjects] = [
            ErasureObjects(
                drives[i * set_drive_count:(i + 1) * set_drive_count],
                parity=parity, enable_mrf=enable_mrf, **set_kwargs,
            )
            for i in range(self.set_count)
        ]
        self.drives = drives

    def close(self) -> None:
        for s in self.sets:
            s.close()

    def _layer_deadline(self, cls: str = "meta") -> float:
        """Envelope for a fan-out over whole sets: each inner drive
        fan-out resolves its stragglers within ~2x its own adaptive
        deadline (deadline + queued-grace), and a bucket op does at most
        a couple of sequential drive hops per set — 4x the slowest set's
        deadline bounds that without racing healthy-but-busy sets. `cls`
        must match the inner op's deadline class (delete_bucket rmtrees
        under the data deadline; metadata ops under meta)."""
        per_set = {"meta": lambda s: s._meta_deadline(),
                   "data": lambda s: s._data_deadline()}[cls]
        return 4.0 * max(per_set(s) for s in self.sets)

    # -- routing (cmd/erasure-sets.go:716-736) --

    def get_hashed_set(self, obj: str) -> ErasureObjects:
        return self.sets[sip_hash_mod(obj, self.set_count, self.deployment_id)]

    # -- buckets: fan out to every set --

    def make_bucket(self, bucket: str, opts: ObjectOptions | None = None) -> None:
        outcomes = parallel_map([lambda s=s: s.make_bucket(bucket, opts)
                                 for s in self.sets],
                                deadline=self._layer_deadline())
        for o in outcomes:
            if isinstance(o, Exception):
                raise o

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.sets[0].get_bucket_info(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        return self.sets[0].list_buckets()

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        outcomes = parallel_map(
            [lambda s=s: s.delete_bucket(bucket, force=force) for s in self.sets],
            deadline=self._layer_deadline("data"),
        )
        for o in outcomes:
            if isinstance(o, Exception):
                raise o

    # -- objects: route by hash --

    def put_object(self, bucket: str, obj: str, data: BinaryIO, size: int = -1,
                   opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).put_object(bucket, obj, data, size, opts)

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, opts: ObjectOptions | None = None):
        return self.get_hashed_set(obj).get_object(bucket, obj, offset, length, opts)

    def get_object_reader(self, bucket: str, obj: str,
                          opts: ObjectOptions | None = None):
        return self.get_hashed_set(obj).get_object_reader(bucket, obj, opts)

    @property
    def fast_local_reads(self) -> bool:
        return all(getattr(s, "fast_local_reads", False) for s in self.sets)

    def get_object_info(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).get_object_info(bucket, obj, opts)

    def delete_object(self, bucket: str, obj: str,
                      opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).delete_object(bucket, obj, opts)

    def delete_objects(self, bucket: str, objects: list[ObjectToDelete],
                       opts: ObjectOptions | None = None
                       ) -> list[DeletedObject | Exception]:
        return listing.bulk_delete(self.delete_object, bucket, objects, opts)

    def put_object_tags(self, bucket: str, obj: str, tags: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).put_object_tags(bucket, obj, tags, opts)

    def put_object_metadata(self, bucket: str, obj: str, updates,
                            opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).put_object_metadata(
            bucket, obj, updates, opts)

    def get_object_tags(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> str:
        return self.get_hashed_set(obj).get_object_tags(bucket, obj, opts)

    def delete_object_tags(self, bucket: str, obj: str,
                           opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).delete_object_tags(bucket, obj, opts)

    def latest_fileinfo(self, bucket: str, obj: str, version_id: str = ""):
        return self.get_hashed_set(obj).latest_fileinfo(bucket, obj, version_id)

    def transition_version(self, bucket: str, obj: str, version_id: str,
                           tier_name: str, tier_key: str,
                           storage_class: str = "",
                           expect_mod_time: float | None = None) -> None:
        return self.get_hashed_set(obj).transition_version(
            bucket, obj, version_id, tier_name, tier_key, storage_class,
            expect_mod_time)

    def restore_transitioned(self, bucket: str, obj: str,
                             version_id: str = "") -> None:
        return self.get_hashed_set(obj).restore_transitioned(
            bucket, obj, version_id)

    # -- multipart: route by hash --

    def new_multipart_upload(self, bucket: str, obj: str,
                             opts: ObjectOptions | None = None) -> str:
        return self.get_hashed_set(obj).new_multipart_upload(bucket, obj, opts)

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: BinaryIO, size: int = -1,
                        opts: ObjectOptions | None = None) -> PartInfoResult:
        return self.get_hashed_set(obj).put_object_part(
            bucket, obj, upload_id, part_number, data, size, opts)

    def get_multipart_info(self, bucket: str, obj: str, upload_id: str):
        return self.get_hashed_set(obj).get_multipart_info(
            bucket, obj, upload_id)

    def list_parts(self, bucket: str, obj: str, upload_id: str,
                   part_marker: int = 0, max_parts: int = 1000):
        return self.get_hashed_set(obj).list_parts(
            bucket, obj, upload_id, part_marker, max_parts)

    def list_multipart_uploads(self, bucket: str, prefix: str = "",
                               max_uploads: int = 1000) -> list[MultipartInfo]:
        # mtpu: allow(MTPU001) - no fixed envelope fits: the inner op is
        # O(active sessions) sequential meta fan-outs, each already
        # deadline-bounded at the drive layer, so the whole call
        # terminates; an outer deadline sized for a few hops would stamp
        # busy sets OperationTimedOut and silently truncate the listing.
        results = parallel_map(
            [lambda s=s: s.list_multipart_uploads(bucket, prefix, max_uploads)
             for s in self.sets],
        )
        if all(isinstance(r, Exception) for r in results):
            raise results[0]
        out: list[MultipartInfo] = []
        for r in results:
            if isinstance(r, Exception):
                continue
            out.extend(r)
        return sorted(out, key=lambda u: (u.object, u.initiated))[:max_uploads]

    def abort_multipart_upload(self, bucket: str, obj: str, upload_id: str) -> None:
        return self.get_hashed_set(obj).abort_multipart_upload(bucket, obj, upload_id)

    def complete_multipart_upload(self, bucket: str, obj: str, upload_id: str,
                                  parts: list[CompletePart],
                                  opts: ObjectOptions | None = None) -> ObjectInfo:
        return self.get_hashed_set(obj).complete_multipart_upload(
            bucket, obj, upload_id, parts, opts)

    # -- listing: merged view across sets --

    def all_drives(self):
        return list(self.drives)

    # Sys-config store lives on set 0 (small mirrored docs need no
    # sharding; reference routes .minio.sys through the same hashing but
    # pins config to deterministic names).
    def read_sys_config(self, path: str) -> bytes:
        return self.sets[0].read_sys_config(path)

    def write_sys_config(self, path: str, data: bytes) -> None:
        self.sets[0].write_sys_config(path, data)

    def delete_sys_config(self, path: str) -> None:
        self.sets[0].delete_sys_config(path)

    def list_sys_config(self, prefix: str = "") -> list[str]:
        return self.sets[0].list_sys_config(prefix)

    def stream_journals(self, bucket: str, prefix: str = "",
                        start_after: str = ""):
        """Sorted (name, journal) stream across every set — each set's
        drive-merged stream k-way merged again (objects route to exactly
        one set, so dupes only arise from topology changes; newest wins).
        O(sets x drives) memory (reference pool-level metacache merge,
        cmd/metacache-server-pool.go:59)."""
        return listing.merge_journal_streams(
            [s.stream_journals(bucket, prefix, start_after)
             for s in self.sets])

    def merged_journals(self, bucket: str, prefix: str) -> dict[str, XLMeta]:
        return dict(self.stream_journals(bucket, prefix))

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000) -> ListObjectsInfo:
        self.get_bucket_info(bucket)
        return listing.paginate_objects(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter),
            lambda name, fi: listing.fi_to_object_info(bucket, name, fi),
            prefix, marker, delimiter, max_keys,
        )

    def list_object_versions(self, bucket: str, prefix: str = "", marker: str = "",
                             version_marker: str = "", delimiter: str = "",
                             max_keys: int = 1000) -> ListObjectVersionsInfo:
        self.get_bucket_info(bucket)
        return listing.paginate_versions(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter, version_marker),
            lambda name, fi: listing.fi_to_object_info(bucket, name, fi),
            prefix, marker, version_marker, delimiter, max_keys,
        )

    # -- healing --

    def heal_bucket(self, bucket: str, dry_run: bool = False) -> HealResultItem:
        results = [s.heal_bucket(bucket, dry_run) for s in self.sets]
        out = results[0]
        for r in results[1:]:
            out.before.extend(r.before)
            out.after.extend(r.after)
            out.disk_count += r.disk_count
        return out

    def heal_object(self, bucket: str, obj: str, version_id: str = "",
                    **kw) -> HealResultItem:
        return self.get_hashed_set(obj).heal_object(bucket, obj, version_id, **kw)

    def heal_objects(self, bucket: str, prefix: str = "",
                     **kw) -> Iterator[HealResultItem]:
        """Walk every object (all sets) and heal it — the bucket-wide heal
        sequence (reference HealObjects, cmd/erasure-server-pool.go:1500)."""
        for s in self.sets:
            yield from s.heal_objects(bucket, prefix, **kw)

    # -- health --

    def health(self) -> dict:
        """Per-set drive health: online counts vs write quorum (reference
        Health, cmd/erasure-server-pool.go)."""
        per_set = [s.health() for s in self.sets]
        return {
            "healthy": all(h["healthy"] for h in per_set),
            "sets": [h["sets"][0] for h in per_set],
        }
