"""Multipart uploads: per-part erasure streams composed at complete time.

Role-equivalent of cmd/erasure-multipart.go: an upload session lives under
the sys volume at multipart/<key-hash>/<upload-id>/ on every drive of the
set; each part is an independent erasure+bitrot stream (PutObjectPart
:379); CompleteMultipartUpload validates the client's part list against the
stored part metadata, moves the part shard files into a fresh data dir and
commits the final version journal with the same rename discipline as
PutObject (:727). Parts keep their client-assigned numbers end to end; the
GET path walks fi.parts in order, so sparse numbering is fine.

TPU note: every part reuses the batched codec fan-out (_fan_out_encode), so
concurrent part uploads become independent batched device streams — the P9
axis in SURVEY.md §2.4.
"""

from __future__ import annotations

import hashlib
import json
import time
import uuid
from typing import BinaryIO

from minio_tpu.erasure.codec import ErasureCodec
from minio_tpu.erasure.metadata import hash_order, parallel_map, shuffle_by_distribution
from minio_tpu.erasure.sysstore import mirror_write_all
from minio_tpu.erasure.types import (
    CompletePart,
    MultipartInfo,
    ObjectInfo,
    ObjectOptions,
    PartInfoResult,
)
from minio_tpu.storage.fileinfo import ChecksumInfo, ErasureInfo, FileInfo, PartInfo
from minio_tpu.utils import errors as se
from minio_tpu.utils.quorum import reduce_write_quorum

SYS_VOL = ".mtpu.sys"
MP_ROOT = "multipart"
MIN_PART_SIZE = 5 << 20  # S3 minimum for all but the last part
MAX_PARTS = 10_000


def _key_hash(bucket: str, obj: str) -> str:
    return hashlib.sha256(f"{bucket}/{obj}".encode()).hexdigest()[:32]


def multipart_etag(part_etags: list[str]) -> str:
    """S3 multipart ETag: md5 over the binary concatenation of part MD5s,
    suffixed with the part count."""
    md5 = hashlib.md5()
    for e in part_etags:
        md5.update(bytes.fromhex(e))
    return f"{md5.hexdigest()}-{len(part_etags)}"


class MultipartMixin:
    """Multipart entry points for ErasureObjects."""

    # -- session helpers --

    def _mp_dir(self, bucket: str, obj: str, upload_id: str) -> str:
        return f"{MP_ROOT}/{_key_hash(bucket, obj)}/{upload_id}"

    def _elect_json(self, rel: str) -> dict | None:
        """Read a small JSON doc from every drive and elect the majority
        payload; ties break toward the newer mod_time. Guards against a
        drive that missed a rewrite within write tolerance serving stale
        state."""
        results = parallel_map(
            [lambda d=d: d.read_all(SYS_VOL, rel) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        # Digest-keyed tally (any bytes-like copy counts without being
        # materialized as a hashable key — the sysstore election shape).
        tally: dict[bytes, tuple[int, bytes]] = {}
        for r in results:
            if isinstance(r, (bytes, bytearray)):
                h = hashlib.sha256(r).digest()
                n, _ = tally.get(h, (0, b""))
                tally[h] = (n + 1, r)
        if not tally:
            return None

        def rank(entry: tuple[int, bytes]):
            count, raw = entry
            try:
                mt = json.loads(raw).get("mod_time", 0.0)
            except ValueError:
                return (-1, 0.0)
            return (count, mt)

        _count, best = max(tally.values(), key=rank)
        try:
            return json.loads(best)
        except ValueError:
            return None

    def _read_mp_meta(self, bucket: str, obj: str, upload_id: str) -> dict:
        mp = self._mp_dir(bucket, obj, upload_id)
        meta = self._elect_json(f"{mp}/upload.json")
        if meta is not None and meta.get("bucket") == bucket \
                and meta.get("object") == obj:
            return meta
        raise se.InvalidUploadID(bucket, obj, f"upload {upload_id} not found")

    # -- API --

    def new_multipart_upload(self, bucket: str, obj: str,
                             opts: ObjectOptions | None = None) -> str:
        opts = opts or ObjectOptions()
        self.get_bucket_info(bucket)
        upload_id = uuid.uuid4().hex
        dist = hash_order(f"{bucket}/{obj}", self.n)

        sc = opts.user_defined.get("x-amz-storage-class", "")
        m = self.parity_for_class(sc)

        meta = {
            "bucket": bucket,
            "object": obj,
            "upload_id": upload_id,
            "initiated": time.time(),
            "user_defined": dict(opts.user_defined),
            "distribution": dist,
            "parity": m,
            "block_size": self.block_size,
            "bitrot": self.bitrot_algorithm,
        }
        raw = json.dumps(meta).encode()
        mp = self._mp_dir(bucket, obj, upload_id)
        # Session journal rides the WAL blob lane (one shared fsync per
        # drive per batch) — concurrent upload creations group-commit.
        results = mirror_write_all(self.drives, SYS_VOL,
                                   f"{mp}/upload.json", raw,
                                   self._meta_deadline())
        reduce_write_quorum(results, self._write_quorum_meta(), bucket, obj)
        return upload_id

    def get_multipart_info(self, bucket: str, obj: str,
                           upload_id: str) -> MultipartInfo:
        """Session metadata for a live upload (reference GetMultipartInfo,
        cmd/erasure-multipart.go:339) — the S3 layer reads the sealed SSE
        key from user_defined to encrypt each part under it."""
        meta = self._read_mp_meta(bucket, obj, upload_id)
        return MultipartInfo(bucket, obj, upload_id,
                             meta.get("initiated", 0.0),
                             meta.get("user_defined", {}))

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: BinaryIO, size: int = -1,
                        opts: ObjectOptions | None = None) -> PartInfoResult:
        if not 1 <= part_number <= MAX_PARTS:
            raise se.InvalidPart(bucket, obj, f"part number {part_number}")
        meta = self._read_mp_meta(bucket, obj, upload_id)
        k = self.n - meta["parity"]
        write_quorum = self._write_quorum_data(meta["parity"])
        codec = ErasureCodec(k, meta["parity"], meta["block_size"])
        shuffled = shuffle_by_distribution(self.drives, meta["distribution"])
        mp = self._mp_dir(bucket, obj, upload_id)

        # Encode into a tmp name, then atomically rename into the session so
        # a re-upload of the same part number can never interleave shards.
        tmp_rel = f"{mp}/tmp-{uuid.uuid4().hex}"
        total, md5_hex, errs = self._fan_out_encode(
            shuffled, SYS_VOL, tmp_rel, data, size, codec, write_quorum,
            bucket, obj,
        )
        if size >= 0 and total != size:
            parallel_map([lambda d=d: d.delete(SYS_VOL, tmp_rel)
                          for d in shuffled],
                         deadline=self._meta_deadline())
            raise se.IncompleteBody(bucket, obj, f"got {total} of {size} bytes")

        mod_time = time.time()

        def commit(i, drive):
            if errs[i] is not None:
                raise errs[i]
            drive.rename_file(SYS_VOL, tmp_rel, SYS_VOL, f"{mp}/part.{part_number}")

        # mtpu: allow(MTPU001) - no outer envelope: each commit is a
        # drive-deadline-bounded rename, so every task terminates with a
        # typed outcome; stamping one OperationTimedOut would leave the
        # abandoned worker racing the quorum-failure cleanup below
        # (renaming tmp_rel into part.N AFTER the cleanup deleted
        # tmp_rel — an orphan part shard on a failed op).
        outcomes = parallel_map(
            [lambda i=i, d=d: commit(i, d) for i, d in enumerate(shuffled)],
        )
        # Part journal rides the WAL blob lane AFTER the shard rename
        # (a part.json must never elect without its shard data): one
        # shared fsync per drive per batch, so concurrent part uploads
        # from many clients group-commit instead of paying a per-part
        # fsync per drive. Only drives whose rename landed get the
        # journal — same publish-after-data order as the old in-closure
        # write_all.
        pj_raw = json.dumps({"size": total, "etag": md5_hex,
                             "mod_time": mod_time}).encode()
        ok_idx = [i for i, o in enumerate(outcomes)
                  if not isinstance(o, Exception)]
        pj_out = mirror_write_all(
            [shuffled[i] for i in ok_idx], SYS_VOL,
            f"{mp}/part.{part_number}.json", pj_raw,
            self._meta_deadline())
        for i, o in zip(ok_idx, pj_out):
            if isinstance(o, Exception):
                outcomes[i] = o
        try:
            reduce_write_quorum(outcomes, write_quorum, bucket, obj)
        except Exception:
            parallel_map([lambda d=d: d.delete(SYS_VOL, tmp_rel)
                          for d in shuffled],
                         deadline=self._meta_deadline())
            raise
        return PartInfoResult(part_number, md5_hex, total, total, mod_time)

    def list_parts(self, bucket: str, obj: str, upload_id: str,
                   part_marker: int = 0, max_parts: int = 1000) -> list[PartInfoResult]:
        mp = self._mp_dir(bucket, obj, upload_id)
        self._read_mp_meta(bucket, obj, upload_id)
        # Union of part numbers across drives — a single drive may have
        # missed a part write within quorum tolerance.
        listings = parallel_map(
            [lambda d=d: d.list_dir(SYS_VOL, mp) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        numbers: set[int] = set()
        for names in listings:
            if isinstance(names, Exception):
                continue
            numbers.update(
                int(n[5:-5]) for n in names
                if n.startswith("part.") and n.endswith(".json")
            )
        out: list[PartInfoResult] = []
        for num in sorted(numbers):
            if num <= part_marker or len(out) >= max_parts:
                continue
            pj = self._elect_json(f"{mp}/part.{num}.json")
            if pj is None:
                continue
            out.append(PartInfoResult(num, pj["etag"], pj["size"],
                                      pj["size"], pj["mod_time"]))
        return out

    def list_multipart_uploads(self, bucket: str, prefix: str = "",
                               max_uploads: int = 1000) -> list[MultipartInfo]:
        self.get_bucket_info(bucket)
        # Union of session dirs across all drives, then quorum-read each.
        sessions: set[str] = set()
        listings = parallel_map(
            [lambda d=d: d.list_dir(SYS_VOL, MP_ROOT) for d in self.drives],
            deadline=self._meta_deadline(),
        )
        for i, hash_dirs in enumerate(listings):
            if isinstance(hash_dirs, Exception):
                continue
            for hd in hash_dirs:
                hd = hd.rstrip("/")
                try:
                    uploads = self.drives[i].list_dir(SYS_VOL, f"{MP_ROOT}/{hd}")
                except se.StorageError:
                    continue
                sessions.update(f"{MP_ROOT}/{hd}/{u.rstrip('/')}" for u in uploads)
        out: list[MultipartInfo] = []
        for sess in sorted(sessions):
            meta = self._elect_json(f"{sess}/upload.json")
            if meta is None or meta.get("bucket") != bucket:
                continue
            if prefix and not meta.get("object", "").startswith(prefix):
                continue
            out.append(MultipartInfo(
                bucket, meta["object"], meta["upload_id"],
                meta.get("initiated", 0.0), meta.get("user_defined", {}),
            ))
            if len(out) >= max_uploads:
                break
        return sorted(out, key=lambda u: (u.object, u.initiated))

    def abort_multipart_upload(self, bucket: str, obj: str, upload_id: str) -> None:
        self._read_mp_meta(bucket, obj, upload_id)
        mp = self._mp_dir(bucket, obj, upload_id)
        # Data-class deadline: a session rmtree is O(parts) of I/O.
        parallel_map(
            [lambda d=d: d.delete(SYS_VOL, mp, recursive=True)
             for d in self.drives],
            deadline=self._data_deadline(),
        )

    def complete_multipart_upload(
        self,
        bucket: str,
        obj: str,
        upload_id: str,
        parts: list[CompletePart],
        opts: ObjectOptions | None = None,
    ) -> ObjectInfo:
        opts = opts or ObjectOptions()
        meta = self._read_mp_meta(bucket, obj, upload_id)
        if not parts:
            raise se.InvalidPart(bucket, obj, "empty part list")
        numbers = [p.part_number for p in parts]
        if numbers != sorted(numbers) or len(set(numbers)) != len(numbers):
            raise se.InvalidPart(bucket, obj, "parts out of order")

        k = self.n - meta["parity"]
        write_quorum = self._write_quorum_data(meta["parity"])
        mp = self._mp_dir(bucket, obj, upload_id)
        shuffled = shuffle_by_distribution(self.drives, meta["distribution"])

        # Validate against stored part metadata (majority-elected).
        stored: dict[int, dict] = {}
        for p in parts:
            pj = self._elect_json(f"{mp}/part.{p.part_number}.json")
            if pj is None:
                raise se.InvalidPart(bucket, obj, f"part {p.part_number} not uploaded")
            if pj["etag"] != p.etag.strip('"'):
                raise se.InvalidPart(bucket, obj, f"part {p.part_number} etag mismatch")
            stored[p.part_number] = pj
        for i, p in enumerate(parts[:-1]):
            if stored[p.part_number]["size"] < MIN_PART_SIZE:
                raise se.PartTooSmall(bucket, obj, f"part {p.part_number}")

        mod_time = opts.mod_time or time.time()
        fi = FileInfo.new(bucket, obj)
        if opts.versioned:
            fi.version_id = opts.version_id or str(uuid.uuid4())
        fi.mod_time = mod_time
        fi.metadata = dict(meta.get("user_defined", {}))
        fi.metadata["etag"] = multipart_etag([p.etag.strip('"') for p in parts])
        fi.size = sum(stored[p.part_number]["size"] for p in parts)
        fi.parts = [
            PartInfo(p.part_number, stored[p.part_number]["size"],
                     stored[p.part_number]["size"], stored[p.part_number]["mod_time"],
                     stored[p.part_number]["etag"])
            for p in parts
        ]
        fi.erasure = ErasureInfo(
            data_blocks=k,
            parity_blocks=meta["parity"],
            block_size=meta["block_size"],
            distribution=meta["distribution"],
            checksums=[ChecksumInfo(p.part_number, meta.get("bitrot", self.bitrot_algorithm))
                       for p in parts],
        )

        tmp_rel = f"tmp/{uuid.uuid4().hex}"
        tokens: list = [None] * len(shuffled)

        def commit(i, drive):
            for p in parts:
                drive.rename_file(SYS_VOL, f"{mp}/part.{p.part_number}",
                                  SYS_VOL, f"{tmp_rel}/part.{p.part_number}")
            f = fi.clone()
            f.erasure.index = i + 1
            tokens[i] = drive.rename_data(SYS_VOL, tmp_rel, f, bucket, obj,
                                          defer_reclaim=True)

        # Commit under the per-object namespace lock — INCLUDING the
        # quorum decision and any undo: the undo mutates the live object
        # namespace (undo_rename, pulling parts back out of the object's
        # data dir), and a concurrent PUT landing between commit and
        # undo must never have its acknowledged version destroyed
        # (reference takes the dist lock around CompleteMultipartUpload's
        # whole rename commit).
        with self.nslock.lock(bucket, obj) as lease:
            # mtpu: allow(MTPU001) - no outer envelope: commit is
            # O(parts) sequential renames, each already deadline-bounded
            # at the drive layer, so every task terminates with a typed
            # outcome; stamping a commit OperationTimedOut would leave
            # the abandoned worker racing restore_session's rollback
            # (rename_data landing after restore pulled the parts back).
            outcomes = parallel_map(
                [lambda i=i, d=d: commit(i, d) for i, d in enumerate(shuffled)],
            )
            # The commit rewrote the object's journals (success or not,
            # some drives moved): any cached election is stale.
            self._meta_invalidate(bucket, obj)

            def restore_session():
                # Move parts BACK into the session so the client can
                # retry Complete — uploaded part data must never be
                # destroyed by a transient failure. Drives whose commit
                # SUCCEEDED hold the parts inside the new object data
                # dir; pull them back out, then undo the rename (dropping
                # the new journal entry and restoring whatever it
                # displaced), so listings never show a below-quorum
                # object.
                undo_fi = fi.clone()

                def restore(i, drive):
                    src = (f"{obj}/{fi.data_dir}"
                           if outcomes[i] is None else tmp_rel)
                    src_vol = bucket if outcomes[i] is None else SYS_VOL
                    for p in parts:
                        try:
                            drive.rename_file(
                                src_vol, f"{src}/part.{p.part_number}",
                                SYS_VOL, f"{mp}/part.{p.part_number}")
                        except se.StorageError:
                            pass
                    if outcomes[i] is None:
                        try:
                            drive.undo_rename(bucket, obj, undo_fi,
                                              tokens[i])
                        except se.StorageError:
                            pass
                    try:
                        drive.delete(SYS_VOL, tmp_rel, recursive=True)
                    except se.StorageError:
                        pass

                # mtpu: allow(MTPU001) - the rollback must run to
                # completion on every drive (abandoning it mid-flight
                # strands a half-restored session the client's retry
                # then sees as InvalidPart); inner ops are drive-bounded.
                parallel_map([lambda i=i, d=d: restore(i, d)
                              for i, d in enumerate(shuffled)])

            try:
                reduce_write_quorum(outcomes, write_quorum, bucket, obj)
            except Exception:
                restore_session()
                raise
            if not lease.held:
                # The dsync lock lost its refresh quorum during the
                # commit fan-out: finishing would complete an unprotected
                # rename a racing writer may have crossed. Put the
                # session back (the client retries Complete) and fail
                # typed — same contract as the put_object commit.
                restore_session()
                raise se.OperationTimedOut(
                    bucket, obj,
                    "dsync lock quorum lost during commit; multipart "
                    "complete rolled back")

        # Success: discard displaced state; reclaim tmp leftovers on
        # drives whose commit failed midway (exceptions are captured as
        # values by parallel_map).
        def post_commit(i, drive):
            if isinstance(outcomes[i], Exception):
                drive.delete(SYS_VOL, tmp_rel, recursive=True)
            elif tokens[i]:
                drive.commit_rename(tokens[i])

        # Data-class deadlines: both reclaim O(parts) trees (tmp
        # leftovers / the session dir).
        parallel_map([lambda i=i, d=d: post_commit(i, d)
                      for i, d in enumerate(shuffled)],
                     deadline=self._data_deadline())
        parallel_map(
            [lambda d=d: d.delete(SYS_VOL, mp, recursive=True)
             for d in self.drives],
            deadline=self._data_deadline(),
        )
        if self.mrf is not None and any(isinstance(o, Exception) for o in outcomes):
            self.mrf.add_partial(bucket, obj, fi.version_id)
        return self._fi_to_object_info(bucket, obj, fi)
