"""format.json — per-drive identity and cluster layout.

Role-equivalent of cmd/format-erasure.go (formatErasureV3 :110,
waitForFormatErasure): every drive carries a format document naming the
deployment, its own UUID, and the full sets×drives UUID matrix, so any
subset of drives can prove (by quorum) what the layout is and a swapped or
fresh drive is detected and healed.

Document (our own v1 — not byte-compatible with the reference's):

    {"version": 1, "format": "erasure", "id": "<deployment uuid>",
     "erasure": {"this": "<drive uuid>",
                 "sets": [["<uuid>", ...], ...],
                 "distribution_algo": "sipmod"}}
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass

from minio_tpu.erasure.metadata import parallel_map
from minio_tpu.storage.api import StorageAPI
from minio_tpu.storage.healthcheck import fleet_deadlines
from minio_tpu.utils import errors as se

FORMAT_ERASURE = "erasure"
DISTRIBUTION_ALGO = "sipmod"


@dataclass
class FormatInfo:
    deployment_id: str
    sets: list[list[str]]           # sets × drives UUID matrix
    this: str = ""                  # the drive's own UUID

    def to_doc(self, this: str) -> dict:
        return {
            "version": 1,
            "format": FORMAT_ERASURE,
            "id": self.deployment_id,
            "erasure": {
                "this": this,
                "sets": self.sets,
                "distribution_algo": DISTRIBUTION_ALGO,
            },
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "FormatInfo":
        if doc.get("version") != 1 or doc.get("format") != FORMAT_ERASURE:
            raise se.CorruptedFormat(f"unrecognized format doc {doc.get('version')}")
        ec = doc.get("erasure", {})
        return cls(deployment_id=doc["id"], sets=ec["sets"], this=ec.get("this", ""))


def init_format_erasure(
    drives: list[StorageAPI], set_drive_count: int,
    can_format_fresh: bool = True,
) -> FormatInfo:
    """Read-or-create formats across all drives (reference
    waitForFormatErasure): fresh drives are formatted into the layout,
    existing formats are quorum-verified, and a minority of blank/replaced
    drives is healed in place. Returns the elected FormatInfo.

    can_format_fresh: in a multi-node boot only the first-endpoint node
    may mint a deployment id on an all-blank cluster; every other node
    waits for the leader's format to appear (reference
    waitForFormatErasure firstDisk gating, cmd/format-erasure.go —
    concurrent minting would split the deployment identity)."""
    n = len(drives)
    if n % set_drive_count:
        raise ValueError(f"{n} drives not divisible into sets of {set_drive_count}")
    set_count = n // set_drive_count

    results = parallel_map([lambda d=d: d.read_format() for d in drives],
                           deadline=fleet_deadlines(drives)[0])
    existing = [
        (i, FormatInfo.from_doc(r))
        for i, r in enumerate(results)
        if isinstance(r, dict)
    ]

    if not existing:
        if not can_format_fresh:
            raise se.OperationTimedOut(
                "", "", "fresh cluster: waiting for the first node to "
                "write the format")
        # Fresh cluster: mint deployment + drive UUIDs.
        fmt = FormatInfo(
            deployment_id=str(uuid.uuid4()),
            sets=[[str(uuid.uuid4()) for _ in range(set_drive_count)]
                  for _ in range(set_count)],
        )
        def write(i, d):
            this = fmt.sets[i // set_drive_count][i % set_drive_count]
            d.write_format(fmt.to_doc(this))
            d.set_disk_id(this)
        outcomes = parallel_map(
            [lambda i=i, d=d: write(i, d) for i, d in enumerate(drives)],
            deadline=fleet_deadlines(drives)[0],
        )
        bad = [o for o in outcomes if isinstance(o, Exception)]
        if bad:
            raise bad[0]
        return fmt

    # Elect the reference format by quorum on (deployment, layout).
    tally: dict[tuple, int] = {}
    for _, f in existing:
        key = (f.deployment_id, tuple(tuple(s) for s in f.sets))
        tally[key] = tally.get(key, 0) + 1
    (dep_id, sets_key), count = max(tally.items(), key=lambda kv: kv[1])
    if count <= len(existing) // 2:
        if not can_format_fresh:
            # Follower racing the leader's parallel format writes: the
            # half-written layout is transient, not corruption. Retry.
            raise se.OperationTimedOut(
                "", "", "format quorum not yet visible; waiting")
        raise se.CorruptedFormat("no format quorum across drives")
    ref = FormatInfo(deployment_id=dep_id, sets=[list(s) for s in sets_key])
    if len(ref.sets) != set_count or any(
        len(s) != set_drive_count for s in ref.sets
    ):
        raise se.CorruptedFormat(
            f"on-disk layout {len(ref.sets)}x{len(ref.sets[0])} does not match "
            f"requested {set_count}x{set_drive_count}"
        )

    # Place every formatted drive at the slot its own UUID names — the
    # reference orders disks by format content, not command-line position,
    # so permuting the drive arguments across restarts must not scramble the
    # set layout. Blank/replaced drives then fill the remaining slots and
    # are healed with that slot's UUID. A drive carrying a format for a
    # DIFFERENT deployment is someone else's data — refuse to touch it (the
    # reference errors on deployment-ID mismatch rather than reformatting).
    uuid_to_slot = {
        u: si * set_drive_count + di
        for si, s in enumerate(ref.sets)
        for di, u in enumerate(s)
    }
    ordered: list[StorageAPI | None] = [None] * n
    blank: list[int] = []     # UnformattedDisk: provably fresh, safe to heal
    unreadable: list[int] = []  # IO error: may carry a format we can't see
    for i, r in enumerate(results):
        if isinstance(r, dict):
            f = FormatInfo.from_doc(r)
            if f.deployment_id != dep_id:
                raise se.CorruptedFormat(
                    f"drive {i} belongs to deployment {f.deployment_id}, "
                    f"not {dep_id} — refusing to reformat a foreign drive"
                )
            slot = uuid_to_slot.get(f.this)
            if slot is not None and ordered[slot] is None:
                ordered[slot] = drives[i]
                drives[i].set_disk_id(f.this)
                continue
            blank.append(i)  # stale/unknown UUID in this deployment: reclaim
        elif isinstance(r, se.UnformattedDisk):
            blank.append(i)
        else:
            unreadable.append(i)
    # Only provably-blank drives are healed with a slot UUID. An unreadable
    # drive may still hold a slot's format — writing that slot's UUID to a
    # blank drive would mint a duplicate identity that destroys data on a
    # later boot (reference heals only errUnformattedDisk,
    # cmd/format-erasure.go). So while any drive is unreadable, blanks are
    # placed but left unformatted; a later boot (or heal_format) fixes them.
    heal_blanks = not unreadable
    for slot in range(n):
        if ordered[slot] is not None:
            continue
        i = blank.pop(0) if blank else unreadable.pop(0)
        drive = drives[i]
        ordered[slot] = drive
        if not (heal_blanks and isinstance(results[i], (dict, se.UnformattedDisk))):
            continue
        slot_uuid = ref.sets[slot // set_drive_count][slot % set_drive_count]
        # Boot init classified this drive against the FULL drive set, so
        # a placed-but-duplicate UUID here is a real duplicate to
        # reclaim, not a concurrent claim.
        _claim_slot(drive, ref, slot_uuid, allow_placed_reclaim=True)
    drives[:] = ordered  # callers consume the UUID-ordered layout
    return ref


def _claim_slot(drive: StorageAPI, fmt: "FormatInfo",
                slot_uuid: str, allow_placed_reclaim: bool = False) -> bool:
    """Format a provably-blank drive into a slot: write its format.json,
    rebind the disk-ID guard, and leave a healing tracker so the
    background auto-healer rebuilds its shards and resumes across
    restarts (reference healFreshDisk,
    cmd/background-newdisks-heal-ops.go:139). Shared by boot-time init
    and the live heal_format monitor — the claim ritual must not
    diverge between them."""
    from minio_tpu.erasure.autoheal import mark_drive_healing
    from minio_tpu.storage.healthcheck import unwrap as _unwrap_drive

    try:
        # Re-probe at claim time: the drive must STILL be provably blank
        # and mounted. An unmounted root reads FaultyDisk — writing the
        # tracker there would recreate the root on the parent filesystem
        # and route the format (and every healed shard) onto it, the
        # exact case the local drive's root guards defend against.
        base = _unwrap_drive(drive)
        cur = None
        try:
            cur = base.read_format()
        except se.UnformattedDisk:
            pass            # provably blank and mounted — claimable
        except se.StorageError:
            # Unmounted/dying OR unparseable doc: both refuse — a
            # corrupt document may be a FOREIGN drive's damaged format
            # (never reformat over it; operator decision).
            return False
        if cur is not None:
            try:
                f = FormatInfo.from_doc(cur)
            except (se.StorageError, KeyError, TypeError, ValueError):
                return False    # malformed doc: same refusal as corrupt
            if f.deployment_id != fmt.deployment_id:
                return False    # foreign drive: never reformat
            if f.this == slot_uuid:
                return False    # claimed concurrently for this slot
            if any(f.this in s for s in fmt.sets) \
                    and not allow_placed_reclaim:
                # A validly placed UUID means another actor claimed the
                # drive for a different slot — overwriting would mint a
                # duplicate identity. Boot init opts in (it classified
                # against the full set, so "placed" there means a real
                # duplicate to reclaim).
                return False
            # Same deployment, stale UNPLACED UUID: reclaimable — the
            # boot path's "stale UUID in this deployment" case, which
            # MUST reformat.
        # Tracker BEFORE identity: the instant the drive carries a valid
        # slot format it must already be marked healing — an observer (or
        # a crash) between the two writes must never see a formatted,
        # tracker-less, shard-empty drive and call it healthy. The
        # tracker write goes through the bare drive: the identity guard
        # would (correctly) refuse a blank disk.
        mark_drive_healing(base, slot_uuid)
        drive.write_format(fmt.to_doc(slot_uuid))
        drive.set_disk_id(slot_uuid)
        return True
    except se.StorageError:
        return False  # still dying; retried on the next pass/boot


def heal_format(es_sets) -> int:
    """Live drive-replacement recovery (reference HealFormat,
    cmd/erasure-server-pool.go:1366 + monitorAndConnectEndpoints,
    cmd/erasure-sets.go:271): probe every slot of a RUNNING ErasureSets
    and, when the slot's drive reports UnformattedDisk (wiped in place or
    swapped for a blank one), rewrite its format.json with the slot's
    UUID, rebind the disk-ID guard, and leave a healing tracker so the
    background auto-healer rebuilds its shards — no restart needed.

    Conservative by design, like boot-time init: a drive carrying a
    FOREIGN deployment's format or a corrupt/unreadable format document
    is never reformatted (that is an operator decision). Claimable:
    provably blank drives, and SAME-deployment drives whose slot UUID is
    stale — not this slot's and not validly placed anywhere in the
    layout (boot-time init reclaims exactly those; the live monitor must
    not strand them until a restart). Returns slots reformatted."""
    fmt: FormatInfo = es_sets.format
    sdc = es_sets.set_drive_count
    placed = {u for s in fmt.sets for u in s}
    healed = 0
    for slot, drive in enumerate(es_sets.drives):
        slot_uuid = fmt.sets[slot // sdc][slot % sdc]
        try:
            cur = drive.read_format()
            f = FormatInfo.from_doc(cur)
            if (f.deployment_id != fmt.deployment_id
                    or f.this == slot_uuid or f.this in placed):
                continue  # foreign / correct / placed: the guard rules
            # Same deployment, stale unplaced UUID: reclaim live.
        except se.UnformattedDisk:
            pass
        except (se.StorageError, KeyError, TypeError, ValueError):
            continue  # unreadable/corrupt/malformed: refuse to claim it
        if _claim_slot(drive, fmt, slot_uuid):
            healed += 1
    return healed
