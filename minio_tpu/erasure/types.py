"""Object-layer data types (reference cmd/object-api-datatypes.go)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BucketInfo:
    name: str
    created: float


@dataclass
class ObjectInfo:
    bucket: str
    name: str
    mod_time: float = 0.0
    size: int = 0
    etag: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    content_type: str = ""
    user_defined: dict[str, str] = field(default_factory=dict)
    parity_blocks: int = 0
    data_blocks: int = 0
    num_versions: int = 0
    is_dir: bool = False
    actual_size: int | None = None
    # (part_number, stored_size) pairs for multipart objects; empty for
    # single-PUT objects (reference ObjectInfo.Parts). Needed by the SSE
    # GET path: multipart parts are independently encrypted streams.
    parts: list = field(default_factory=list)

    @property
    def storage_class(self) -> str:
        return self.user_defined.get("x-amz-storage-class", "STANDARD")


@dataclass
class ObjectOptions:
    """Per-call options (reference cmd/object-api-interface.go:44-63)."""

    version_id: str = ""
    versioned: bool = False
    version_suspended: bool = False
    user_defined: dict[str, str] = field(default_factory=dict)
    mod_time: float = 0.0
    part_number: int = 0
    delete_prefix: bool = False
    no_lock: bool = False
    # Conditional PUT: commit only while the current latest version's
    # mod_time still matches (tier restore's lost-update guard).
    expect_mod_time: float | None = None


@dataclass
class ListObjectsInfo:
    is_truncated: bool = False
    next_marker: str = ""
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)


@dataclass
class ListObjectVersionsInfo:
    is_truncated: bool = False
    next_marker: str = ""
    next_version_id_marker: str = ""
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)


@dataclass
class ObjectToDelete:
    object_name: str
    version_id: str = ""


@dataclass
class DeletedObject:
    object_name: str = ""
    version_id: str = ""
    delete_marker: bool = False
    delete_marker_version_id: str = ""


@dataclass
class MultipartInfo:
    bucket: str
    object: str
    upload_id: str
    initiated: float = 0.0
    user_defined: dict[str, str] = field(default_factory=dict)


@dataclass
class CompletePart:
    part_number: int
    etag: str


@dataclass
class PartInfoResult:
    part_number: int
    etag: str
    size: int
    actual_size: int
    last_modified: float = 0.0
