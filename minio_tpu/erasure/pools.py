"""ErasureServerPools — capacity-routed pools of erasure sets; the top-level
ObjectLayer.

Role-equivalent of erasureServerPools (cmd/erasure-server-pool.go:41): writes
land in the pool chosen by free-capacity weighting unless the object already
exists in some pool (:176-293); reads/deletes fan out across pools and the
owning pool answers; listings and healing merge across pools. With one pool
this adds a thin pass-through — the common single-pool deployment costs
nothing.
"""

from __future__ import annotations

from typing import BinaryIO, Iterator

from minio_tpu.erasure import listing
from minio_tpu.erasure import metacache as metacache_mod
from minio_tpu.erasure.healing import HealResultItem
from minio_tpu.erasure.metadata import parallel_map
from minio_tpu.erasure.sets import ErasureSets
from minio_tpu.erasure.types import (
    BucketInfo,
    CompletePart,
    DeletedObject,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    MultipartInfo,
    ObjectInfo,
    ObjectOptions,
    ObjectToDelete,
    PartInfoResult,
)
from minio_tpu.storage.fileinfo import FileInfo
from minio_tpu.storage.xlmeta import XLMeta
from minio_tpu.utils import errors as se


class ErasureServerPools:
    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("no pools")
        self.pools = pools
        self.metacache = metacache_mod.Metacache(self)

    def close(self) -> None:
        self.metacache.close()
        for p in self.pools:
            p.close()

    def _layer_deadline(self, cls: str = "meta") -> float:
        """Envelope for a fan-out over whole pools: one hop above the
        set-layer envelope (each pool op is a deadline-bounded set
        fan-out that resolves within ~2x its own deadline). `cls` must
        match the inner op's deadline class."""
        return 2.0 * max(p._layer_deadline(cls) for p in self.pools)

    # -- pool choice --

    def _pool_free(self, pool: ErasureSets) -> int:
        free = 0
        for d in pool.drives:
            try:
                free += d.disk_info().free
            except Exception:  # noqa: BLE001
                pass
        return free

    def _get_pool_idx_existing(self, bucket: str, obj: str,
                               version_id: str = "") -> int | None:
        """Index of the pool already holding the object, newest wins
        (reference getPoolIdxExisting, cmd/erasure-server-pool.go:252).

        Probes at the journal level (latest_fileinfo) so a key whose latest
        version is a delete marker still pins its pool — a re-PUT after a
        versioned delete must land where the version history lives, not be
        re-routed by free capacity (which would split versions across pools)."""
        results = parallel_map(
            [lambda p=p: p.latest_fileinfo(bucket, obj, version_id)
             for p in self.pools],
            deadline=self._layer_deadline(),
        )
        best, best_mt = None, -1.0
        for i, r in enumerate(results):
            if isinstance(r, FileInfo) and r.mod_time > best_mt:
                best, best_mt = i, r.mod_time
        return best

    def _get_pool_for_put(self, bucket: str, obj: str,
                          version_id: str = "") -> ErasureSets:
        if len(self.pools) == 1:
            return self.pools[0]
        existing = self._get_pool_idx_existing(bucket, obj, version_id)
        if existing is not None:
            return self.pools[existing]
        frees = [self._pool_free(p) for p in self.pools]
        return self.pools[max(range(len(frees)), key=frees.__getitem__)]

    def _owning_pool(self, bucket: str, obj: str, version_id: str = "") -> ErasureSets:
        if len(self.pools) == 1:
            return self.pools[0]
        idx = self._get_pool_idx_existing(bucket, obj, version_id)
        if idx is None:
            raise se.ObjectNotFound(bucket, obj)
        return self.pools[idx]

    # -- buckets --

    def make_bucket(self, bucket: str, opts: ObjectOptions | None = None) -> None:
        outcomes = parallel_map([lambda p=p: p.make_bucket(bucket, opts)
                                 for p in self.pools],
                                deadline=self._layer_deadline())
        for o in outcomes:
            if isinstance(o, Exception):
                raise o

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.pools[0].get_bucket_info(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        outcomes = parallel_map(
            [lambda p=p: p.delete_bucket(bucket, force=force) for p in self.pools],
            deadline=self._layer_deadline("data"),
        )
        for o in outcomes:
            if isinstance(o, Exception):
                raise o

    # -- objects --

    def put_object(self, bucket: str, obj: str, data: BinaryIO, size: int = -1,
                   opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self.metacache.mark_dirty(bucket)
        return self._get_pool_for_put(bucket, obj, opts.version_id).put_object(
            bucket, obj, data, size, opts)

    def get_object(self, bucket: str, obj: str, offset: int = 0,
                   length: int = -1, opts: ObjectOptions | None = None):
        opts = opts or ObjectOptions()
        return self._owning_pool(bucket, obj, opts.version_id).get_object(
            bucket, obj, offset, length, opts)

    def get_object_reader(self, bucket: str, obj: str,
                          opts: ObjectOptions | None = None):
        opts = opts or ObjectOptions()
        # Bucket existence first (cached at the set level): a GET for a
        # bucket that lives on another federated cluster must surface
        # BucketNotFound (the redirect trigger), not NoSuchKey.
        self.get_bucket_info(bucket)
        return self._owning_pool(bucket, obj, opts.version_id).get_object_reader(
            bucket, obj, opts)

    @property
    def fast_local_reads(self) -> bool:
        return all(getattr(p, "fast_local_reads", False) for p in self.pools)

    def get_object_info(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self.get_bucket_info(bucket)
        return self._owning_pool(bucket, obj, opts.version_id).get_object_info(
            bucket, obj, opts)

    def delete_object(self, bucket: str, obj: str,
                      opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        self.metacache.mark_dirty(bucket)
        if opts.versioned and not opts.version_id:
            # Delete markers land in the pool that owns (or would own) the key.
            idx = self._get_pool_idx_existing(bucket, obj)
            pool = self.pools[idx] if idx is not None else self.pools[0]
            return pool.delete_object(bucket, obj, opts)
        return self._owning_pool(bucket, obj, opts.version_id).delete_object(
            bucket, obj, opts)

    def delete_objects(self, bucket: str, objects: list[ObjectToDelete],
                       opts: ObjectOptions | None = None
                       ) -> list[DeletedObject | Exception]:
        return listing.bulk_delete(self.delete_object, bucket, objects, opts)

    def put_object_tags(self, bucket: str, obj: str, tags: str,
                        opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        return self._owning_pool(bucket, obj, opts.version_id).put_object_tags(
            bucket, obj, tags, opts)

    def transition_version(self, bucket: str, obj: str, version_id: str,
                           tier_name: str, tier_key: str,
                           storage_class: str = "",
                           expect_mod_time: float | None = None) -> None:
        return self._owning_pool(bucket, obj, version_id).transition_version(
            bucket, obj, version_id, tier_name, tier_key, storage_class,
            expect_mod_time)

    def restore_transitioned(self, bucket: str, obj: str,
                             version_id: str = "") -> None:
        return self._owning_pool(bucket, obj, version_id).restore_transitioned(
            bucket, obj, version_id)

    def put_object_metadata(self, bucket: str, obj: str, updates,
                            opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        return self._owning_pool(
            bucket, obj, opts.version_id).put_object_metadata(
            bucket, obj, updates, opts)

    def get_object_tags(self, bucket: str, obj: str,
                        opts: ObjectOptions | None = None) -> str:
        opts = opts or ObjectOptions()
        return self._owning_pool(bucket, obj, opts.version_id).get_object_tags(
            bucket, obj, opts)

    def delete_object_tags(self, bucket: str, obj: str,
                           opts: ObjectOptions | None = None) -> ObjectInfo:
        opts = opts or ObjectOptions()
        return self._owning_pool(bucket, obj, opts.version_id).delete_object_tags(
            bucket, obj, opts)

    # -- multipart --

    def new_multipart_upload(self, bucket: str, obj: str,
                             opts: ObjectOptions | None = None) -> str:
        return self._get_pool_for_put(bucket, obj).new_multipart_upload(
            bucket, obj, opts)

    def _upload_pool(self, bucket: str, obj: str, upload_id: str) -> ErasureSets:
        for p in self.pools:
            try:
                p.get_hashed_set(obj)._read_mp_meta(bucket, obj, upload_id)
                return p
            except se.InvalidUploadID:
                continue
        raise se.InvalidUploadID(bucket, obj, f"upload {upload_id} not found")

    def put_object_part(self, bucket: str, obj: str, upload_id: str,
                        part_number: int, data: BinaryIO, size: int = -1,
                        opts: ObjectOptions | None = None) -> PartInfoResult:
        return self._upload_pool(bucket, obj, upload_id).put_object_part(
            bucket, obj, upload_id, part_number, data, size, opts)

    def list_parts(self, bucket: str, obj: str, upload_id: str,
                   part_marker: int = 0, max_parts: int = 1000):
        return self._upload_pool(bucket, obj, upload_id).list_parts(
            bucket, obj, upload_id, part_marker, max_parts)

    def get_multipart_info(self, bucket: str, obj: str, upload_id: str):
        return self._upload_pool(bucket, obj, upload_id).get_multipart_info(
            bucket, obj, upload_id)

    def list_multipart_uploads(self, bucket: str, prefix: str = "",
                               max_uploads: int = 1000) -> list[MultipartInfo]:
        out: list[MultipartInfo] = []
        for p in self.pools:
            out.extend(p.list_multipart_uploads(bucket, prefix, max_uploads))
        return sorted(out, key=lambda u: (u.object, u.initiated))[:max_uploads]

    def abort_multipart_upload(self, bucket: str, obj: str, upload_id: str) -> None:
        return self._upload_pool(bucket, obj, upload_id).abort_multipart_upload(
            bucket, obj, upload_id)

    def complete_multipart_upload(self, bucket: str, obj: str, upload_id: str,
                                  parts: list[CompletePart],
                                  opts: ObjectOptions | None = None) -> ObjectInfo:
        self.metacache.mark_dirty(bucket)
        return self._upload_pool(bucket, obj, upload_id).complete_multipart_upload(
            bucket, obj, upload_id, parts, opts)

    # -- listing --

    def stream_journals(self, bucket: str, prefix: str = "",
                        start_after: str = ""):
        """Sorted (name, journal) stream across every pool (reference
        cmd/metacache-server-pool.go:59) — O(pools x sets x drives)
        memory regardless of namespace size."""
        return listing.merge_journal_streams(
            [p.stream_journals(bucket, prefix, start_after)
             for p in self.pools])

    def merged_journals(self, bucket: str, prefix: str) -> dict[str, XLMeta]:
        return dict(self.stream_journals(bucket, prefix))

    # Synchronous render bound: page 1 persists this many entries before
    # returning (bounds page-1 latency); a daemon renderer continues the
    # SAME walk up to METACACHE_MAX_STREAM in blocks, so sequential
    # continuations ride the persisted stream while memory stays
    # O(block) on both sides (cmd/metacache-stream.go progressive role).
    METACACHE_MAX_ENTRIES = 10_000
    METACACHE_MAX_STREAM = 1_000_000

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000) -> ListObjectsInfo:
        self.get_bucket_info(bucket)
        to_info = lambda name, fi: listing.fi_to_object_info(bucket, name, fi)  # noqa: E731
        # Continuation pages serve from the persisted metacache stream —
        # the first page walked the namespace and rendered it; the S3
        # marker seeks into the block index (cmd/metacache-stream.go).
        if marker:
            cached = self.metacache.entries_from(bucket, prefix, marker)
            if cached is not None:
                it, complete = cached
                try:
                    r = listing.paginate_cached(
                        it, prefix, marker, delimiter, max_keys)
                except metacache_mod.CacheGone:
                    r = None
                if r is not None and (r.is_truncated or complete):
                    return r
                # Capped stream drained mid-page (or a block vanished):
                # names past the rendered range may exist — fall through
                # to the walk for a correct page.
                self.metacache.misses += 1
        res = listing.paginate_objects(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter),
            to_info, prefix, marker, delimiter, max_keys)
        if (res.is_truncated and not marker
                and not self.metacache.recently_saved(bucket, prefix)):
            # More pages will follow: render a FRESH walk into the block
            # stream (sync up to the page-1 bound, then background).
            self.metacache.render(
                bucket, prefix,
                listing.iter_entries_from_journals(
                    self.stream_journals(bucket, prefix), to_info),
                kind="o", sync_cap=self.METACACHE_MAX_ENTRIES,
                stream_cap=self.METACACHE_MAX_STREAM)
        return res

    def list_object_versions(self, bucket: str, prefix: str = "", marker: str = "",
                             version_marker: str = "", delimiter: str = "",
                             max_keys: int = 1000) -> ListObjectVersionsInfo:
        self.get_bucket_info(bucket)
        to_info = lambda name, fi: listing.fi_to_object_info(bucket, name, fi)  # noqa: E731
        if marker:
            cached = self.metacache.entries_from(bucket, prefix, marker,
                                                 kind="v")
            if cached is not None:
                it, complete = cached
                try:
                    r = listing.paginate_versions_cached(
                        it, prefix, marker, version_marker, delimiter,
                        max_keys)
                except metacache_mod.CacheGone:
                    r = None
                if r is not None and (r.is_truncated or complete):
                    return r
                self.metacache.misses += 1
        res = listing.paginate_versions(
            listing.pushdown_stream(
                lambda sa: self.stream_journals(bucket, prefix, sa),
                prefix, marker, delimiter, version_marker), to_info,
            prefix, marker, version_marker, delimiter, max_keys)
        if (res.is_truncated and not marker
                and not self.metacache.recently_saved_versions(
                    bucket, prefix)):
            # Scanner + client continuations seek into the persisted
            # block stream instead of re-walking every page.
            self.metacache.render(
                bucket, prefix,
                listing.iter_version_entries_from_journals(
                    self.stream_journals(bucket, prefix), to_info),
                kind="v", sync_cap=self.METACACHE_MAX_ENTRIES,
                stream_cap=self.METACACHE_MAX_STREAM)
        return res

    # -- healing --

    def heal_bucket(self, bucket: str, dry_run: bool = False) -> HealResultItem:
        results = [p.heal_bucket(bucket, dry_run) for p in self.pools]
        out = results[0]
        for r in results[1:]:
            out.before.extend(r.before)
            out.after.extend(r.after)
            out.disk_count += r.disk_count
        return out

    def heal_object(self, bucket: str, obj: str, version_id: str = "",
                    **kw) -> HealResultItem:
        last: Exception | None = None
        for p in self.pools:
            try:
                return p.heal_object(bucket, obj, version_id, **kw)
            except se.ObjectError as e:
                last = e
        raise last or se.ObjectNotFound(bucket, obj)

    def heal_objects(self, bucket: str, prefix: str = "",
                     **kw) -> Iterator[HealResultItem]:
        for p in self.pools:
            yield from p.heal_objects(bucket, prefix, **kw)

    # -- health --

    def all_drives(self):
        return [d for p in self.pools for d in p.all_drives()]

    def read_sys_config(self, path: str) -> bytes:
        return self.pools[0].read_sys_config(path)

    def write_sys_config(self, path: str, data: bytes) -> None:
        self.pools[0].write_sys_config(path, data)

    def delete_sys_config(self, path: str) -> None:
        self.pools[0].delete_sys_config(path)

    def list_sys_config(self, prefix: str = "") -> list[str]:
        return self.pools[0].list_sys_config(prefix)

    def health(self) -> dict:
        pools = [p.health() for p in self.pools]
        return {"healthy": all(h["healthy"] for h in pools), "pools": pools}
