"""ErasureCodec — geometry + batched device codec for object streams.

Mirrors the Erasure surface (cmd/erasure-coding.go:28-143): shard_size /
shard_file_size / shard_file_offset math plus Encode/Decode entry points —
but batched: the streaming loops hand the codec a *batch* of 1 MiB blocks
per call so the GF(2) matmul launches stay MXU-sized (the reference encodes
block-at-a-time per goroutine; on TPU batching across blocks is where
throughput comes from — SURVEY.md §2.4 P2).

Partial-block handling exploits column independence of the GF math: a short
block is split into ceil(len/k) shards, zero-padded to the full shard width,
batch-encoded with the full blocks, and the parity is simply truncated back
— parity columns never mix, so padding is free.
"""

from __future__ import annotations

import time

import numpy as np

from minio_tpu.obs import kernel as obs_kernel
from minio_tpu.ops import rs_xla
from minio_tpu.utils.shardmath import ceil_div as _ceil_div
from minio_tpu.utils import shardmath

DEFAULT_BLOCK_SIZE = 1 << 20  # reference blockSizeV2, cmd/object-api-common.go:41

_SERVING_MESH: object = "unset"


def serving_mesh():
    """The device mesh the SERVING codec shards over, or None.

    Multi-chip hosts (a v5e-8 slice is 8 local devices) run the fused
    encode+digest launch sharded (dp, tp, sp) with psum completing the
    GF(2) contraction over ICI — the P6/ICI path of SURVEY §2.4/§5.8 in
    the production PutObject, not just the dryrun. Single-device hosts
    return None (plain fused launch). CPU "devices" are virtual (one
    physical core), so the mesh path is opt-in there via
    MTPU_MESH_CODEC=1 — which is how the test suite exercises it on the
    8-device CPU mesh.
    """
    global _SERVING_MESH
    import os

    if _SERVING_MESH == "unset":
        import jax

        devs = jax.devices()
        use = len(devs) > 1 and (
            devs[0].platform != "cpu"
            or os.environ.get("MTPU_MESH_CODEC") == "1")
        if use:
            from minio_tpu.parallel import make_mesh

            _SERVING_MESH = make_mesh(devices=devs)
        else:
            _SERVING_MESH = None
    return _SERVING_MESH


class PendingEncode:
    """Handle to an in-flight device encode launch (JAX async dispatch).

    begin_encode returns immediately after queuing the launch; the host
    thread overlaps the next batch's read/copy and the previous batch's
    drive fan-out with this batch's device compute — the reference's
    read/encode/write block pipeline (cmd/erasure-encode.go:80-107, P2 in
    SURVEY §2.4) expressed as dispatch-ahead instead of goroutines.

    wait() materializes results with ONE contiguous device->host transfer
    per tensor and hands out zero-copy memoryview slices (no per-shard
    .tobytes()). Data chunks alias the caller's original block buffers;
    parity chunks alias the transferred array, which the views keep alive.
    """

    def __init__(self, codec: "ErasureCodec", blocks: list[bytes],
                 chunk_lens: list[int], padded: list[bytes | None],
                 parity_dev, digs_dev):
        self._codec = codec
        self._blocks = blocks
        self._lens = chunk_lens
        self._padded = padded
        self._parity_dev = parity_dev
        self._digs_dev = digs_dev

    def wait(self) -> tuple[list[list[memoryview]], list[list[bytes]] | None]:
        """-> (per-block list of n shard chunks, per-block list of n chunk
        digests or None when digests were not requested)."""
        k, m = self._codec.k, self._codec.m
        parity = np.asarray(self._parity_dev) if self._parity_dev is not None else None
        digs = np.asarray(self._digs_dev) if self._digs_dev is not None else None
        out_chunks: list[list[memoryview]] = []
        out_digs: list[list[bytes]] | None = [] if digs is not None else None
        for bi, block in enumerate(self._blocks):
            s = self._lens[bi]
            src = self._padded[bi] if self._padded[bi] is not None else block
            mv = memoryview(src)
            chunks = [mv[i * s:(i + 1) * s] for i in range(k)]
            if m:
                chunks += [memoryview(parity[bi, j])[:s] for j in range(m)]
            out_chunks.append(chunks)
            if out_digs is not None:
                out_digs.append([bytes(digs[bi, i]) for i in range(k + m)])
        return out_chunks, out_digs


class PendingDecode:
    """Handle to an in-flight rebuild launch (see begin_reconstruct)."""

    def __init__(self, targets: tuple[int, ...], chunk_lens: list[int],
                 rebuilt_dev, digs_dev):
        self.targets = targets
        self._lens = chunk_lens
        self._rebuilt_dev = rebuilt_dev
        self._digs_dev = digs_dev

    def wait(self) -> tuple[list[list[bytes]], list[list[bytes]] | None]:
        """-> (per block: rebuilt chunk per target, per block: digest per
        target or None)."""
        rebuilt = np.asarray(self._rebuilt_dev)
        digs = (np.asarray(self._digs_dev)
                if self._digs_dev is not None else None)
        out_chunks, out_digs = [], [] if digs is not None else None
        for bi, s in enumerate(self._lens):
            out_chunks.append([rebuilt[bi, ti, :s].tobytes()
                               for ti in range(len(self.targets))])
            if out_digs is not None:
                out_digs.append([bytes(digs[bi, ti])
                                 for ti in range(len(self.targets))])
        return out_chunks, out_digs


class ErasureCodec:
    def __init__(self, data_blocks: int, parity_blocks: int,
                 block_size: int = DEFAULT_BLOCK_SIZE):
        if data_blocks <= 0 or parity_blocks < 0:
            raise ValueError(f"bad geometry k={data_blocks} m={parity_blocks}")
        if data_blocks + parity_blocks > 256:
            raise ValueError("k+m exceeds GF(2^8) limit of 256")
        self.k = data_blocks
        self.m = parity_blocks
        self.block_size = block_size

    # --- geometry (cmd/erasure-coding.go:115-143) ---

    def shard_size(self) -> int:
        return shardmath.shard_size(self.block_size, self.k)

    def shard_file_size(self, total_length: int) -> int:
        return shardmath.shard_file_size(total_length, self.block_size, self.k)

    def shard_file_offset(self, start: int, length: int, total_length: int) -> int:
        return shardmath.shard_file_offset(start, length, total_length,
                                           self.block_size, self.k)

    # --- batched encode ---

    def begin_encode(self, blocks: list[bytes],
                     with_digests: bool = False) -> PendingEncode:
        """Queue one device launch encoding a batch of erasure blocks
        (parity, and with_digests=True the mxsum256 bitrot digest of every
        shard chunk in the same launch — ops/fused.py). Returns immediately;
        results come from PendingEncode.wait()."""
        import jax.numpy as jnp

        from minio_tpu.ops import fused

        s_full = self.shard_size()
        # Shape bucketing (fused.bucket_rows / bucket_width): pad the
        # row count to the next power of two so mixed object sizes
        # (whose tail batches carry arbitrary block counts) cannot
        # churn the jit trace cache, and stage at the batch's ACTUAL
        # pow-2 chunk width instead of the geometry's full shard width
        # — a 10 KiB object must not pay a 1 MiB-block-wide launch.
        # Both paddings are invisible in results: parity columns never
        # mix and mxsum digests are cap-invariant; pad rows are zeros
        # with chunk_len 0 and every consumer iterates real blocks only.
        chunk_lens: list[int] = []
        for bi, block in enumerate(blocks):
            if not 0 < len(block) <= self.block_size:
                raise ValueError(f"block {bi} size {len(block)}")
            chunk_lens.append(_ceil_div(len(block), self.k))
        rows = fused.bucket_rows(len(blocks))
        s_stage = min(s_full, fused.bucket_width(max(chunk_lens)))
        batch = np.empty((rows, self.k, s_stage), dtype=np.uint8)
        padded: list[bytes | None] = []
        for bi, block in enumerate(blocks):
            s = chunk_lens[bi]
            if s == s_stage and len(block) == self.k * s_stage:
                padded.append(None)
                batch[bi] = np.frombuffer(block, dtype=np.uint8).reshape(
                    self.k, s_stage)
            else:
                flat = np.zeros(self.k * s, dtype=np.uint8)
                flat[: len(block)] = np.frombuffer(block, dtype=np.uint8)
                padded.append(flat.tobytes())
                batch[bi, :, :s] = flat.reshape(self.k, s)
                batch[bi, :, s:] = 0
        if rows != len(blocks):
            batch[len(blocks):] = 0
        staged_lens = chunk_lens + [0] * (rows - len(blocks))
        parity_dev = digs_dev = None
        if self.m or with_digests:
            mesh = serving_mesh()
            b = len(blocks)
            # rows (not b) is the staged batch dim: pow-2 row padding
            # keeps non-pow-2 tail batches mesh-eligible — pad rows are
            # zeros, their parity/digests are computed and ignored
            # (wait() iterates real blocks only).
            dims_ok = (mesh is not None
                       and rows % mesh.shape["dp"] == 0
                       and self.k % mesh.shape["tp"] == 0
                       and s_full % mesh.shape["sp"] == 0)
            if (dims_ok and self.m and with_digests
                    and all(s == s_full for s in chunk_lens)):
                # Multi-device host, full blocks: the mesh-sharded fused
                # launch (psum GF contraction over ICI, sp-sharded mxsum)
                # — the host numpy batch stays uncommitted so jit shards
                # it straight onto the mesh. Ragged tails fall through to
                # the single-device launch, which handles per-block
                # lengths.
                from minio_tpu.parallel import sharded_encode_with_mxsum

                t0 = time.perf_counter()
                parity_dev, digs_dev = sharded_encode_with_mxsum(
                    mesh, batch, self.k, self.m)
                obs_kernel.observe("encode_digests", "mesh", t0,
                                   blocks=b, nbytes=batch.size,
                                   out=parity_dev)
            elif dims_ok and self.m and not with_digests:
                from minio_tpu.parallel import sharded_encode

                t0 = time.perf_counter()
                parity_dev = sharded_encode(mesh, batch, self.k, self.m)
                obs_kernel.observe("encode", "mesh", t0,
                                   blocks=b, nbytes=batch.size,
                                   out=parity_dev)
            else:
                data_dev = jnp.asarray(batch)
                lens_dev = jnp.asarray(staged_lens, dtype=jnp.int32)
                if self.m and with_digests:
                    parity_dev, digs_dev = fused.encode_with_digests(
                        data_dev, self.k, self.m, lens_dev)
                elif self.m:
                    parity_dev = fused.encode_only(data_dev, self.k, self.m)
                else:  # digests for a parity-less geometry (k shards only)
                    digs_dev = fused.verify_digests(
                        data_dev.reshape(rows * self.k, s_stage),
                        jnp.repeat(lens_dev, self.k),
                    ).reshape(rows, self.k, -1)
        return PendingEncode(self, blocks, chunk_lens, padded,
                             parity_dev, digs_dev)

    def encode_blocks(self, blocks: list[bytes]) -> list[list[bytes]]:
        """Synchronous encode: per block, the n = k+m shard chunks (data
        first, then parity), each ceil(len(block)/k) bytes."""
        if not blocks:
            return []
        chunks, _ = self.begin_encode(blocks).wait()
        return [[bytes(c) for c in row] for row in chunks]

    def begin_reconstruct(self, shard_chunks: list[list[bytes | None]],
                          block_lens: list[int],
                          targets: tuple[int, ...],
                          with_digests: bool = False) -> "PendingDecode":
        """Queue one rebuild launch for a batch of blocks sharing a single
        failure pattern (the heal loop's shape: one object, one drive
        state). with_digests=True computes the rebuilt chunks' mxsum256
        digests in the SAME launch (fused.reconstruct_with_digests) —
        heal writes them straight into fresh [digest][chunk] shard files.
        Returns immediately (JAX async dispatch): the heal loop reads the
        next batch while the device rebuilds this one."""
        import jax.numpy as jnp

        from minio_tpu.ops import fused
        from minio_tpu.utils import errors as se

        if not shard_chunks:
            return PendingDecode(tuple(targets), [], None, None)
        n = self.k + self.m
        s_full = self.shard_size()
        pattern = [c is not None for c in shard_chunks[0]]
        for row in shard_chunks[1:]:
            if [c is not None for c in row] != pattern:
                raise ValueError(
                    "begin_reconstruct needs one failure pattern per batch "
                    "(use decode_blocks for mixed patterns)")
        present = [i for i in range(n) if pattern[i]]
        if len(present) < self.k:
            raise se.InsufficientReadQuorum(
                "", "", f"only {len(present)} of {self.k} shards available")
        survivors = tuple(present[: self.k])
        chunk_lens = [_ceil_div(bl, self.k) for bl in block_lens]
        # Survivor-compacted staging ([B, k, S], no dead parity rows) and
        # the decode matrix as runtime data — the failure pattern stays
        # out of the jit compile key (C(n, <=m) patterns exist; static
        # args would recompile the kernel per pattern mid-sweep). Rows
        # pad to the power-of-two bucket (fused.bucket_rows) so a heal
        # sweep's ragged tail batches reuse the same compiled program.
        rows = fused.bucket_rows(len(shard_chunks))
        s_stage = min(s_full, fused.bucket_width(max(chunk_lens)))
        batch = np.zeros((rows, self.k, s_stage), dtype=np.uint8)
        for bi, row in enumerate(shard_chunks):
            for ci, si in enumerate(survivors):
                c = row[si]
                batch[bi, ci, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        from minio_tpu.ops import rs_pallas

        w_t = jnp.asarray(rs_pallas._decode_weights_t(
            self.k, n, survivors, tuple(targets)))
        staged_lens = chunk_lens + [0] * (rows - len(shard_chunks))
        rebuilt_dev, digs_dev = fused.reconstruct_weights_digests(
            jnp.asarray(batch), w_t,
            jnp.asarray(staged_lens, dtype=jnp.int32),
            len(targets), with_digests=with_digests)
        return PendingDecode(tuple(targets), chunk_lens, rebuilt_dev, digs_dev)

    # --- batched decode / reconstruct ---

    def decode_blocks(
        self,
        shard_chunks: list[list[bytes | None]],
        block_lens: list[int],
        need_all: bool = False,
    ) -> list[list[bytes]]:
        """Rebuild data (and optionally parity) chunks for a batch of blocks.

        shard_chunks[b][i] is shard i's chunk for block b, or None if that
        drive is unavailable — the any-k semantics of the reference's
        DecodeDataBlocks/Reconstruct (cmd/erasure-coding.go:89-113). All
        blocks in one call must share a single failure pattern (the caller
        groups by pattern; patterns are per-GET stable since drive health
        doesn't flip per block).

        Returns per block the k data chunks (need_all=False) or all n chunks.
        """
        n = self.k + self.m
        if not shard_chunks:
            return []
        present = [shard_chunks[0][i] is not None for i in range(n)]
        for row in shard_chunks:
            if [c is not None for c in row] != present:
                # Mixed failure patterns: the per-block-weight launch.
                return self.decode_blocks_multi(shard_chunks, block_lens, need_all)
        if sum(present) < self.k:
            from minio_tpu.utils import errors as se
            raise se.InsufficientReadQuorum(
                "", "", f"only {sum(present)} of required {self.k} shards available"
            )
        want = range(n) if need_all else range(self.k)
        targets = [i for i in want if not present[i]]

        chunk_lens = [_ceil_div(bl, self.k) for bl in block_lens]
        if not targets:
            return [
                [row[i] for i in want]  # type: ignore[misc]
                for row in shard_chunks
            ]

        survivors = tuple([i for i in range(n) if present[i]][: self.k])
        from minio_tpu.ops import fused

        s_stage = min(self.shard_size(),
                      fused.bucket_width(max(chunk_lens)))
        # Rows are already compacted to the k survivors, so feed the raw
        # GF(2) contraction with the per-pattern decode weights directly.
        batch = np.zeros((len(shard_chunks), self.k, s_stage),
                         dtype=np.uint8)
        for bi, row in enumerate(shard_chunks):
            for si, shard_idx in enumerate(survivors):
                c = row[shard_idx]
                batch[bi, si, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        w = rs_xla._device_decode_weights(self.k, n, survivors, tuple(targets))
        rebuilt = np.asarray(
            rs_xla.gf2_matmul_with_weights(batch, w, len(targets))
        )
        out = []
        for bi, row in enumerate(shard_chunks):
            s = chunk_lens[bi]
            fixed = list(row)
            for ti, shard_idx in enumerate(targets):
                fixed[shard_idx] = rebuilt[bi, ti, :s].tobytes()
            out.append([fixed[i] for i in want])
        return out

    def decode_blocks_multi(
        self,
        shard_chunks: list[list[bytes | None]],
        block_lens: list[int],
        need_all: bool = False,
    ) -> list[list[bytes]]:
        """decode_blocks for a batch whose blocks have DIFFERENT failure
        patterns: every block carries its own stacked decode matrix and the
        whole batch rebuilds in ONE launch (rs_xla.gf2_matmul_multi) — the
        TPU-native form of healing many objects with differing drive states
        in a single batched solve (cmd/erasure-healing.go heals pattern by
        pattern)."""
        from minio_tpu.utils import errors as se

        from minio_tpu.ops import fused

        n = self.k + self.m
        if not shard_chunks:
            return []
        want = list(range(n) if need_all else range(self.k))
        chunk_lens = [_ceil_div(bl, self.k) for bl in block_lens]
        s_stage = min(self.shard_size(),
                      fused.bucket_width(max(chunk_lens)))

        per_block: list[tuple[tuple[int, ...], tuple[int, ...]]] = []
        t_max = 1
        for bi, row in enumerate(shard_chunks):
            present = [i for i in range(n) if row[i] is not None]
            if len(present) < self.k:
                raise se.InsufficientReadQuorum(
                    "", "",
                    f"block {bi}: only {len(present)} of {self.k} shards")
            survivors = tuple(present[: self.k])
            targets = tuple(i for i in want if row[i] is None)
            per_block.append((survivors, targets))
            t_max = max(t_max, len(targets))

        if all(not t for _, t in per_block):
            return [[row[i] for i in want] for row in shard_chunks]  # type: ignore[misc]

        batch = np.zeros((len(shard_chunks), self.k, s_stage),
                         dtype=np.uint8)
        weights = np.zeros((len(shard_chunks), self.k * 8, t_max * 8),
                           dtype=np.int8)
        for bi, row in enumerate(shard_chunks):
            survivors, targets = per_block[bi]
            for si, shard_idx in enumerate(survivors):
                c = row[shard_idx]
                batch[bi, si, : len(c)] = np.frombuffer(c, dtype=np.uint8)
            if targets:
                w = rs_xla._decode_weights_np(self.k, n, survivors, targets)
                weights[bi, :, : len(targets) * 8] = w
        rebuilt = np.asarray(rs_xla.gf2_matmul_multi(batch, weights, t_max))
        out = []
        for bi, row in enumerate(shard_chunks):
            _, targets = per_block[bi]
            s = chunk_lens[bi]
            fixed = list(row)
            for ti, shard_idx in enumerate(targets):
                fixed[shard_idx] = rebuilt[bi, ti, :s].tobytes()
            out.append([fixed[i] for i in want])
        return out
