"""ErasureCodec — geometry + batched device codec for object streams.

Mirrors the Erasure surface (cmd/erasure-coding.go:28-143): shard_size /
shard_file_size / shard_file_offset math plus Encode/Decode entry points —
but batched: the streaming loops hand the codec a *batch* of 1 MiB blocks
per call so the GF(2) matmul launches stay MXU-sized (the reference encodes
block-at-a-time per goroutine; on TPU batching across blocks is where
throughput comes from — SURVEY.md §2.4 P2).

Partial-block handling exploits column independence of the GF math: a short
block is split into ceil(len/k) shards, zero-padded to the full shard width,
batch-encoded with the full blocks, and the parity is simply truncated back
— parity columns never mix, so padding is free.
"""

from __future__ import annotations

import numpy as np

from minio_tpu.ops import rs_xla
from minio_tpu.utils.shardmath import ceil_div as _ceil_div
from minio_tpu.utils import shardmath

DEFAULT_BLOCK_SIZE = 1 << 20  # reference blockSizeV2, cmd/object-api-common.go:41


class ErasureCodec:
    def __init__(self, data_blocks: int, parity_blocks: int,
                 block_size: int = DEFAULT_BLOCK_SIZE):
        if data_blocks <= 0 or parity_blocks < 0:
            raise ValueError(f"bad geometry k={data_blocks} m={parity_blocks}")
        if data_blocks + parity_blocks > 256:
            raise ValueError("k+m exceeds GF(2^8) limit of 256")
        self.k = data_blocks
        self.m = parity_blocks
        self.block_size = block_size

    # --- geometry (cmd/erasure-coding.go:115-143) ---

    def shard_size(self) -> int:
        return shardmath.shard_size(self.block_size, self.k)

    def shard_file_size(self, total_length: int) -> int:
        return shardmath.shard_file_size(total_length, self.block_size, self.k)

    def shard_file_offset(self, start: int, length: int, total_length: int) -> int:
        return shardmath.shard_file_offset(start, length, total_length,
                                           self.block_size, self.k)

    # --- batched encode ---

    def encode_blocks(self, blocks: list[bytes]) -> list[list[bytes]]:
        """Encode a batch of erasure blocks.

        Returns, per block, the n = k+m shard chunks (data first, then
        parity), each ceil(len(block)/k) bytes.
        """
        if not blocks:
            return []
        s_full = self.shard_size()
        batch = np.zeros((len(blocks), self.k, s_full), dtype=np.uint8)
        chunk_lens = []
        for bi, block in enumerate(blocks):
            if not 0 < len(block) <= self.block_size:
                raise ValueError(f"block {bi} size {len(block)}")
            s = _ceil_div(len(block), self.k)
            chunk_lens.append(s)
            flat = np.frombuffer(block, dtype=np.uint8)
            padded = np.zeros(self.k * s, dtype=np.uint8)
            padded[: flat.size] = flat
            batch[bi, :, :s] = padded.reshape(self.k, s)
        if self.m:
            parity = np.asarray(rs_xla.encode(batch, self.k, self.m))
        out = []
        for bi, s in enumerate(chunk_lens):
            chunks = [batch[bi, i, :s].tobytes() for i in range(self.k)]
            if self.m:
                chunks += [parity[bi, j, :s].tobytes() for j in range(self.m)]
            out.append(chunks)
        return out

    # --- batched decode / reconstruct ---

    def decode_blocks(
        self,
        shard_chunks: list[list[bytes | None]],
        block_lens: list[int],
        need_all: bool = False,
    ) -> list[list[bytes]]:
        """Rebuild data (and optionally parity) chunks for a batch of blocks.

        shard_chunks[b][i] is shard i's chunk for block b, or None if that
        drive is unavailable — the any-k semantics of the reference's
        DecodeDataBlocks/Reconstruct (cmd/erasure-coding.go:89-113). All
        blocks in one call must share a single failure pattern (the caller
        groups by pattern; patterns are per-GET stable since drive health
        doesn't flip per block).

        Returns per block the k data chunks (need_all=False) or all n chunks.
        """
        n = self.k + self.m
        if not shard_chunks:
            return []
        present = [shard_chunks[0][i] is not None for i in range(n)]
        for row in shard_chunks:
            if [c is not None for c in row] != present:
                raise ValueError("all blocks in a batch must share a failure pattern")
        if sum(present) < self.k:
            from minio_tpu.utils import errors as se
            raise se.InsufficientReadQuorum(
                "", "", f"only {sum(present)} of required {self.k} shards available"
            )
        want = range(n) if need_all else range(self.k)
        targets = [i for i in want if not present[i]]

        chunk_lens = [_ceil_div(bl, self.k) for bl in block_lens]
        if not targets:
            return [
                [row[i] for i in want]  # type: ignore[misc]
                for row in shard_chunks
            ]

        survivors = tuple([i for i in range(n) if present[i]][: self.k])
        s_full = self.shard_size()
        # Rows are already compacted to the k survivors, so feed the raw
        # GF(2) contraction with the per-pattern decode weights directly.
        batch = np.zeros((len(shard_chunks), self.k, s_full), dtype=np.uint8)
        for bi, row in enumerate(shard_chunks):
            for si, shard_idx in enumerate(survivors):
                c = row[shard_idx]
                batch[bi, si, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        w = rs_xla._device_decode_weights(self.k, n, survivors, tuple(targets))
        rebuilt = np.asarray(
            rs_xla.gf2_matmul_with_weights(batch, w, len(targets))
        )
        out = []
        for bi, row in enumerate(shard_chunks):
            s = chunk_lens[bi]
            fixed = list(row)
            for ti, shard_idx in enumerate(targets):
                fixed[shard_idx] = rebuilt[bi, ti, :s].tobytes()
            out.append([fixed[i] for i in want])
        return out
