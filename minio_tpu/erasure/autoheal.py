"""Background new-drive auto-heal with a persisted, resumable tracker.

Role-equivalent of cmd/background-newdisks-heal-ops.go: when a fresh or
replaced drive joins a set (detected at format time or by the background
monitor), a healing tracker is persisted ON THE HEALING DRIVE ITSELF
(:47,139 — the tracker travels with the drive, so a restart resumes the
walk instead of starting over), the whole set's namespace is walked through
the standard healObject path, progress is checkpointed every few objects,
and the tracker is removed on completion (initAutoHeal :241,
monitorLocalDisksAndHeal :310).

The walk itself heals through ErasureObjects.heal_object, i.e. the batched
device reconstruct (codec.decode_blocks / gf2_matmul_multi) — the TPU
design means a resumed heal is the same batched solve, just restarted at
the bookmark.
"""

from __future__ import annotations

import json
import threading
import time

from minio_tpu.storage.api import StorageAPI
from minio_tpu.utils import errors as se

SYS_VOL = ".mtpu.sys"
TRACKER_PATH = "healing.json"
CHECKPOINT_EVERY = 16  # objects healed between tracker saves


class HealingTracker:
    """Progress bookmark persisted on the healing drive."""

    def __init__(self, drive_uuid: str = "", started: float = 0.0,
                 bucket: str = "", obj: str = "",
                 healed: int = 0, failed: int = 0,
                 finished_buckets: list[str] | None = None):
        self.drive_uuid = drive_uuid
        self.started = started or time.time()
        self.bucket = bucket              # bucket currently being walked
        self.obj = obj                    # last object healed in it
        self.healed = healed
        self.failed = failed
        self.finished_buckets = finished_buckets or []

    def to_doc(self) -> dict:
        return {
            "drive_uuid": self.drive_uuid, "started": self.started,
            "bucket": self.bucket, "object": self.obj,
            "healed": self.healed, "failed": self.failed,
            "finished_buckets": self.finished_buckets,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "HealingTracker":
        return cls(drive_uuid=doc.get("drive_uuid", ""),
                   started=doc.get("started", 0.0),
                   bucket=doc.get("bucket", ""), obj=doc.get("object", ""),
                   healed=doc.get("healed", 0), failed=doc.get("failed", 0),
                   finished_buckets=doc.get("finished_buckets", []))

    # -- persistence on the drive --

    def save(self, drive: StorageAPI) -> None:
        try:
            drive.make_vol(SYS_VOL)
        except se.StorageError:
            pass
        drive.write_all(SYS_VOL, TRACKER_PATH, json.dumps(self.to_doc()).encode())

    @staticmethod
    def load(drive: StorageAPI) -> "HealingTracker | None":
        try:
            raw = drive.read_all(SYS_VOL, TRACKER_PATH)
        except se.StorageError:
            return None
        try:
            return HealingTracker.from_doc(json.loads(raw))
        except (ValueError, KeyError):
            return None

    @staticmethod
    def delete(drive: StorageAPI) -> None:
        try:
            drive.delete(SYS_VOL, TRACKER_PATH)
        except se.StorageError:
            pass


def mark_drive_healing(drive: StorageAPI, drive_uuid: str) -> None:
    """Persist a fresh tracker on a just-formatted replacement drive —
    called by the format layer when it heals a blank drive into a slot that
    belongs to a set with existing data (cmd/erasure-sets.go:197 connectDisks
    -> healFreshDisk)."""
    if HealingTracker.load(drive) is None:
        HealingTracker(drive_uuid=drive_uuid).save(drive)


class AutoHealer:
    """Background monitor: finds drives carrying a healing tracker and
    walks their set's namespace through heal_object, checkpointing and
    resuming via the tracker (reference monitorLocalDisksAndHeal)."""

    def __init__(self, sets, interval: float = 10.0, config=None,
                 load_fn=None):
        # `sets` is anything exposing .sets -> list[ErasureObjects]
        # (ErasureSets / pools) or a single ErasureObjects. When it is a
        # full ErasureSets (carries the format layout), the monitor also
        # runs live drive-replacement detection (heal_format) each pass.
        # `config` provides heal.max_sleep / heal.max_io; `load_fn`
        # returns the CURRENT foreground request count. Pacing follows the
        # reference's waitForLowHTTPReq: the heal sweep sleeps (up to
        # max_sleep per object) ONLY while foreground load exceeds
        # max_io — an idle system heals at full speed.
        self._owner = sets if hasattr(sets, "format") else None
        self._sets = getattr(sets, "sets", None) or [sets]
        self.interval = interval
        self.config = config
        self.load_fn = load_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _pacing(self) -> tuple[float, int]:
        """(max_sleep seconds, objects healed per sleep) from the live
        heal config; (0, 1) disables pacing."""
        if self.config is None:
            return 0.0, 1
        from minio_tpu.utils.dyntimeout import parse_duration

        try:
            max_sleep = parse_duration(
                self.config.get("heal", "max_sleep"), 0.0)
        except Exception:  # noqa: BLE001
            max_sleep = 0.0
        try:
            max_io = max(1, int(self.config.get("heal", "max_io") or 1))
        except Exception:  # noqa: BLE001
            max_io = 1
        return max(0.0, max_sleep), max_io

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 - keep the monitor alive
                pass

    # -- one monitor pass (test entry point) --

    def run_once(self) -> int:
        """Heal every drive that carries a tracker; returns drives healed.
        Detects wiped/replaced drives first (heal_format) so a blank drive
        is reformatted, tracker-marked, and rebuilt in the SAME pass —
        the reference's monitorLocalDisksAndHeal flow (connectDisks ->
        healFreshDisk -> healErasureSet)."""
        if self._owner is not None:
            from minio_tpu.erasure.format import heal_format

            try:
                heal_format(self._owner)
            except Exception:  # noqa: BLE001 - keep the monitor alive
                pass
        healed_drives = 0
        for es in self._sets:
            for drive in es.drives:
                tracker = HealingTracker.load(drive)
                if tracker is None:
                    continue
                self._heal_set_onto(es, drive, tracker)
                healed_drives += 1
        return healed_drives

    def _heal_set_onto(self, es, drive: StorageAPI,
                       tracker: HealingTracker) -> None:
        """Walk the set's buckets/objects, healing each (the standard
        healObject path rebuilds shards onto every outdated drive — this
        one included), resuming after the tracker's bookmark."""
        buckets = sorted(b.name for b in es.list_buckets())
        since_save = 0
        max_sleep, max_io = self._pacing()
        for bucket in buckets:
            if bucket in tracker.finished_buckets:
                continue
            if tracker.bucket and bucket < tracker.bucket:
                tracker.finished_buckets.append(bucket)
                continue
            try:
                es.heal_bucket(bucket)
            except se.StorageError:
                pass
            start_after = tracker.obj if tracker.bucket == bucket else ""
            # Streamed walk: the heal pass holds O(drives) journal state,
            # not the whole namespace, and the tracker bookmark skips
            # already-healed names WITHOUT parsing their journals.
            for name, _meta in es.stream_journals(bucket, "",
                                                  start_after=start_after):
                if self._stop.is_set():
                    tracker.save(drive)
                    return
                try:
                    es.heal_object(bucket, name)
                    tracker.healed += 1
                except Exception:  # noqa: BLE001
                    tracker.failed += 1
                tracker.bucket, tracker.obj = bucket, name
                since_save += 1
                if (max_sleep > 0 and self.load_fn is not None
                        and self.load_fn() > max_io):
                    # Foreground load above heal.max_io: yield up to
                    # heal.max_sleep before the next object (reference
                    # waitForLowHTTPReq) — idle systems never sleep.
                    if self._stop.wait(max_sleep):
                        tracker.save(drive)
                        return
                if since_save >= CHECKPOINT_EVERY:
                    tracker.save(drive)
                    since_save = 0
            tracker.finished_buckets.append(bucket)
            tracker.bucket, tracker.obj = "", ""
            tracker.save(drive)
        HealingTracker.delete(drive)
