"""Web console backend: JSON-RPC 2.0 + JWT upload/download.

Role-equivalent of cmd/web-handlers.go:102-1358 + cmd/web-router.go:55 +
cmd/jwt/: the API the browser console talks to — Login issues a JWT bound
to an IAM identity; RPC methods cover bucket/object browsing and
management; upload/download endpoints stream bodies with the JWT (or a
short-lived URL token for downloads, matching CreateURLToken).

Mounted at /minio/webrpc (RPC), /minio/upload/{bucket}/{object},
/minio/download/{bucket}/{object}?token=...
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse

from aiohttp import web

from minio_tpu.iam.policy import PolicyArgs
from minio_tpu.iam.reqctx import (
    get_condition_context,
    set_condition_context,
)
from minio_tpu.utils import errors as se

TOKEN_TTL = 24 * 3600.0
URL_TOKEN_TTL = 60.0


# --- JWT (HMAC-SHA256, cmd/jwt role) ----------------------------------------

def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).decode().rstrip("=")


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def make_jwt(secret: str, access_key: str, ttl: float = TOKEN_TTL,
             scope: str = "") -> str:
    """scope != "" mints a CAPABILITY token (e.g. "dl:bucket/key"):
    accepted ONLY by the endpoint that checks that scope, never as a
    console session — a share link must not hand its recipient the
    sharer's identity."""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = {"sub": access_key, "exp": time.time() + ttl}
    if scope:
        claims["scope"] = scope
    payload = _b64(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = _b64(hmac.new(secret.encode(), signing, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def verify_jwt_claims(secret: str, token: str) -> dict | None:
    """Verified claims dict ({sub, exp[, scope]}), or None."""
    try:
        header, payload, sig = token.split(".")
        signing = f"{header}.{payload}".encode()
        want = _b64(hmac.new(secret.encode(), signing,
                             hashlib.sha256).digest())
        if not hmac.compare_digest(want, sig):
            return None
        doc = json.loads(_unb64(payload))
        if doc.get("exp", 0) < time.time():
            return None
        return doc
    except Exception:  # noqa: BLE001
        return None


def verify_jwt(secret: str, token: str) -> str | None:
    """Returns the access key of an UNSCOPED (session) token, or None —
    scoped capability tokens are refused here so a leaked share link can
    never authenticate RPC or upload calls."""
    doc = verify_jwt_claims(secret, token)
    if doc is None or doc.get("scope"):
        return None
    return doc.get("sub")


class WebAPI:
    """The RPC surface. `server` is the S3Server."""

    def __init__(self, server):
        self.s = server

    # -- auth plumbing --

    def _jwt_secret(self) -> str:
        return "mtpu-web-jwt:" + self.s.creds.secret_key

    def _identity_from(self, request) -> "object | None":
        auth = request.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else ""
        ak = verify_jwt(self._jwt_secret(), token)
        if ak is None:
            return None
        try:
            return self.s.iam.identify(ak)
        except se.InvalidAccessKey:
            return None

    def _allowed(self, ident, action: str, bucket: str = "",
                 obj: str = "") -> bool:
        # Conditioned policies evaluate against the real request here
        # too (a conditioned Deny must bite on the console plane, not
        # just the S3 API) — context set at rpc/upload/download dispatch.
        return self.s.iam.is_allowed(
            ident, PolicyArgs(action=action, bucket=bucket, object=obj,
                              conditions=get_condition_context()))

    # -- JSON-RPC 2.0 endpoint --

    async def rpc(self, request: web.Request) -> web.Response:
        try:
            req = json.loads(await request.read())
        except ValueError:
            return _rpc_error(None, -32700, "parse error")
        rid = req.get("id")
        method = str(req.get("method", ""))
        params = req.get("params") or {}
        short = method.rsplit(".", 1)[-1]

        if short == "Login":
            return await self._login(rid, params)

        ident = self._identity_from(request)
        if ident is None:
            return _rpc_error(rid, 401, "invalid or expired token")
        set_condition_context(self.s._condition_context(request, ident))

        handlers = {
            "ListBuckets": self._list_buckets,
            "MakeBucket": self._make_bucket,
            "DeleteBucket": self._delete_bucket,
            "ListObjects": self._list_objects,
            "RemoveObject": self._remove_objects,
            "ServerInfo": self._server_info,
            "StorageInfo": self._storage_info,
            "CreateURLToken": self._create_url_token,
            "PresignedGet": self._presigned_get,
            "GetBucketPolicy": self._get_bucket_policy,
            "SetBucketPolicy": self._set_bucket_policy,
            "SetAuth": self._set_auth,
        }
        fn = handlers.get(short)
        if fn is None:
            return _rpc_error(rid, -32601, f"unknown method {method}")
        try:
            result = await fn(ident, params)
        except (se.ObjectError, se.StorageError) as e:
            return _rpc_error(rid, 500, str(e))
        except se.IAMError as e:
            return _rpc_error(rid, 400, str(e))
        except PermissionError as e:
            return _rpc_error(rid, 403, str(e))
        return _rpc_result(rid, result)

    async def _login(self, rid, params) -> web.Response:
        ak = params.get("username", "")
        sk = params.get("password", "")
        try:
            if self.s.iam.get_secret(ak) != sk:
                raise se.InvalidAccessKey(ak)
        except se.InvalidAccessKey:
            return _rpc_error(rid, 401, "invalid credentials")
        return _rpc_result(rid, {
            "token": make_jwt(self._jwt_secret(), ak),
            "uiVersion": "minio_tpu-console/1.0"})

    # -- methods --

    async def _list_buckets(self, ident, params):
        import asyncio

        loop = asyncio.get_running_loop()
        buckets = await loop.run_in_executor(None, self.s.obj.list_buckets)
        out = []
        for b in buckets:
            if ident.is_owner or self._allowed(ident, "s3:ListBucket", b.name):
                out.append({"name": b.name, "creationDate": b.created})
        return {"buckets": out}

    async def _make_bucket(self, ident, params):
        bucket = params["bucketName"]
        if not self._allowed(ident, "s3:CreateBucket", bucket):
            raise PermissionError("CreateBucket denied")
        import asyncio

        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.s.obj.make_bucket, bucket)
        return {}

    async def _delete_bucket(self, ident, params):
        bucket = params["bucketName"]
        if not self._allowed(ident, "s3:DeleteBucket", bucket):
            raise PermissionError("DeleteBucket denied")
        import asyncio

        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.s.obj.delete_bucket, bucket)
        self.s.bucket_meta.drop_bucket(bucket)
        return {}

    async def _list_objects(self, ident, params):
        import asyncio

        bucket = params["bucketName"]
        prefix = params.get("prefix", "")
        if not self._allowed(ident, "s3:ListBucket", bucket):
            raise PermissionError("ListBucket denied")
        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(
            None, lambda: self.s.obj.list_objects(
                bucket, prefix, params.get("marker", ""), "/", 1000))
        return {
            "objects": [{"name": o.name, "size": o.size,
                         "lastModified": o.mod_time, "etag": o.etag}
                        for o in res.objects],
            "prefixes": res.prefixes,
            "isTruncated": res.is_truncated,
            "nextMarker": res.next_marker,
        }

    async def _remove_objects(self, ident, params):
        import asyncio

        from minio_tpu.erasure.types import ObjectOptions, ObjectToDelete

        bucket = params["bucketName"]
        objects = params.get("objects", [])
        for o in objects:
            if not self._allowed(ident, "s3:DeleteObject", bucket, o):
                raise PermissionError(f"DeleteObject denied on {o}")
        loop = asyncio.get_running_loop()
        results = await loop.run_in_executor(
            None, lambda: self.s.obj.delete_objects(
                bucket, [ObjectToDelete(o) for o in objects],
                ObjectOptions(versioned=self.s._bucket_versioned(bucket))))
        errors = [str(r) for r in results if isinstance(r, Exception)]
        return {"errors": errors}

    async def _set_auth(self, ident, params):
        """Change the LOGGED-IN user's own secret (reference console
        ChangePasswordModal / web SetAuth): current secret re-verified,
        root refused — the root credential is deployment configuration
        (CLI/env), not a mutable IAM document. The session JWT stays
        valid (it is signed by the server secret, not the user's)."""
        import asyncio

        current = str(params.get("currentSecretKey", ""))
        new = str(params.get("newSecretKey", ""))
        if ident.kind != "user":
            # Root is deployment config; STS/service-account sessions
            # must NOT mint a permanent IAM user under their (ephemeral)
            # access key — set_user would outlive the credential.
            raise PermissionError(
                "only IAM users can rotate their secret here")
        if len(new) < 8 or len(new) > 40:
            raise se.IAMError("secret key must be 8-40 characters")
        if not hmac.compare_digest(
                self.s.iam.get_secret(ident.access_key), current):
            raise PermissionError("current secret key is wrong")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.s.iam.set_user, ident.access_key, new)
        return {}

    async def _server_info(self, ident, params):
        return {"version": "minio_tpu/1.0",
                "platform": "tpu",
                "uptime": self.s.stats.snapshot()["uptime"]}

    async def _storage_info(self, ident, params):
        import asyncio

        loop = asyncio.get_running_loop()
        h = await loop.run_in_executor(None, self.s.obj.health)
        total = free = 0
        for d in getattr(self.s.obj, "all_drives", lambda: [])():
            try:
                di = d.disk_info()
                total += di.total
                free += di.free
            except Exception:  # noqa: BLE001
                pass
        return {"healthy": h.get("healthy", False), "total": total,
                "free": free}

    async def _get_bucket_policy(self, ident, params):
        """Canned anonymous-access level (reference GetBucketPolicy,
        cmd/web-handlers.go): none | readonly | writeonly | readwrite —
        classified by EVALUATING the stored policy for an anonymous
        principal (the parser handles single-dict statements, principal
        lists and resource scoping that a hand-rolled walk would not)."""
        import asyncio

        from minio_tpu.iam.policy import Policy, PolicyArgs

        bucket = params["bucketName"]
        if not self._allowed(ident, "s3:GetBucketPolicy", bucket):
            raise PermissionError("GetBucketPolicy denied")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.s.obj.get_bucket_info, bucket)  # 404 semantics
        raw = self.s.bucket_meta.get(bucket).policy_json
        level = "none"
        if raw:
            try:
                bp = Policy.parse_cached(raw)

                def anon_allows(action: str) -> bool:
                    return bp.is_allowed(PolicyArgs(
                        action=action, bucket=bucket, object="any-object",
                        account="*"))

                reads = anon_allows("s3:GetObject")
                writes = anon_allows("s3:PutObject")
                level = ("readwrite" if reads and writes else
                         "readonly" if reads else
                         "writeonly" if writes else "none")
            except Exception:  # noqa: BLE001 - unparsable doc reads as none
                pass
        return {"policy": level}

    async def _set_bucket_policy(self, ident, params):
        """Apply a canned anonymous-access level (reference
        SetBucketPolicy): writes the equivalent bucket policy document."""
        import asyncio

        bucket = params["bucketName"]
        level = params.get("policy", "none")
        if level not in ("none", "readonly", "writeonly", "readwrite"):
            raise PermissionError(f"unknown policy level {level!r}")
        if not self._allowed(ident, "s3:PutBucketPolicy", bucket):
            raise PermissionError("PutBucketPolicy denied")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.s.obj.get_bucket_info, bucket)  # 404 semantics
        arn_b = f"arn:aws:s3:::{bucket}"
        arn_o = f"arn:aws:s3:::{bucket}/*"
        statements = []
        if level in ("readonly", "readwrite"):
            statements += [
                {"Effect": "Allow", "Principal": {"AWS": ["*"]},
                 "Action": ["s3:GetBucketLocation", "s3:ListBucket"],
                 "Resource": [arn_b]},
                {"Effect": "Allow", "Principal": {"AWS": ["*"]},
                 "Action": ["s3:GetObject"], "Resource": [arn_o]},
            ]
        if level in ("writeonly", "readwrite"):
            statements.append(
                {"Effect": "Allow", "Principal": {"AWS": ["*"]},
                 "Action": ["s3:PutObject", "s3:DeleteObject",
                            "s3:AbortMultipartUpload",
                            "s3:ListMultipartUploadParts"],
                 "Resource": [arn_o]})
        body = (b"" if not statements else json.dumps(
            {"Version": "2012-10-17", "Statement": statements}).encode())
        await loop.run_in_executor(
            None, lambda: self.s.bucket_meta.update(
                bucket, policy_json=body))
        return {}

    async def _create_url_token(self, ident, params):
        # Download-only capability (any object the identity may read) —
        # never a console session.
        return {"token": make_jwt(self._jwt_secret(), ident.access_key,
                                  ttl=URL_TOKEN_TTL, scope="dl:*")}

    async def _presigned_get(self, ident, params):
        """Download/share URL. `expiry` seconds (optional) supports the
        console's share dialog — capped at 7 days like S3 presigned URLs
        (reference ShareObject, cmd/web-handlers.go)."""
        bucket = params["bucketName"]
        obj = params["objectName"]
        if not self._allowed(ident, "s3:GetObject", bucket, obj):
            raise PermissionError("GetObject denied")
        try:
            ttl = float(params.get("expiry", URL_TOKEN_TTL))
        except (TypeError, ValueError):
            ttl = URL_TOKEN_TTL
        ttl = max(1.0, min(ttl, 7 * 24 * 3600.0))
        token = make_jwt(self._jwt_secret(), ident.access_key, ttl=ttl,
                         scope=f"dl:{bucket}/{obj}")
        return {"url": f"/minio/download/{bucket}/"
                       f"{urllib.parse.quote(obj)}?token={token}",
                "expiry": ttl}

    # -- streaming upload / download --

    async def upload(self, request: web.Request, bucket: str,
                     key: str) -> web.Response:
        """Console upload endpoint. Single-shot PUT by default; large
        files drive the multipart session protocol (the reference
        browser's chunked uploads, browser/app/js/uploads):

            POST ?action=initiate                 -> {"uploadId"}
            PUT  ?uploadId=U&partNumber=N  (body) -> {"etag"}
            POST ?action=complete  {"uploadId", "parts": [{n, etag}]}
            POST ?action=abort     {"uploadId"}
        """
        ident = self._identity_from(request)
        if ident is None:
            raise web.HTTPForbidden(text="invalid token")
        set_condition_context(self.s._condition_context(request, ident))
        if not self._allowed(ident, "s3:PutObject", bucket, key):
            raise web.HTTPForbidden(text="PutObject denied")
        import asyncio
        import io

        from minio_tpu.erasure.types import CompletePart, ObjectOptions

        loop = asyncio.get_running_loop()
        # Multipart control requests carry application/json; the OBJECT's
        # content type rides the ?ctype= query param on initiate (the
        # single-shot path uses the request's own Content-Type).
        ctype = (request.query.get("ctype")
                 or request.headers.get("Content-Type",
                                        "application/octet-stream"))
        opts = ObjectOptions(
            versioned=self.s._bucket_versioned(bucket),
            user_defined={"content-type": ctype})
        action = request.query.get("action", "")
        upload_id = request.query.get("uploadId", "")
        if action not in ("", "initiate", "complete", "abort"):
            # An unknown action must never fall through to the whole-
            # object PUT — a typo'd ?action=compelte would overwrite the
            # object with the control request's JSON body.
            raise web.HTTPBadRequest(text=f"unknown action {action!r}")
        if action == "initiate":
            uid = await loop.run_in_executor(
                None, lambda: self.s.obj.new_multipart_upload(
                    bucket, key, opts))
            return web.json_response({"uploadId": uid})
        if action in ("complete", "abort"):
            doc = json.loads(await request.read() or b"{}")
            uid = doc.get("uploadId") or upload_id
            if action == "abort":
                await loop.run_in_executor(
                    None, lambda: self.s.obj.abort_multipart_upload(
                        bucket, key, uid))
                return web.json_response({})
            parts = [CompletePart(int(p["partNumber"]), str(p["etag"]))
                     for p in doc.get("parts", [])]
            info = await loop.run_in_executor(
                None, lambda: self.s.obj.complete_multipart_upload(
                    bucket, key, uid, parts))
            return web.json_response({"etag": info.etag})
        body = await request.read()
        if upload_id:
            part_number = int(request.query.get("partNumber", "0"))
            pi = await loop.run_in_executor(
                None, lambda: self.s.obj.put_object_part(
                    bucket, key, upload_id, part_number,
                    io.BytesIO(body), len(body)))
            return web.json_response({"etag": pi.etag})
        await loop.run_in_executor(
            None, lambda: self.s.obj.put_object(
                bucket, key, io.BytesIO(body), len(body), opts))
        return web.Response(status=200)

    async def download(self, request: web.Request, bucket: str,
                       key: str) -> web.StreamResponse:
        token = request.query.get("token", "")
        claims = verify_jwt_claims(self._jwt_secret(), token)
        if claims is None:
            raise web.HTTPForbidden(text="invalid token")
        scope = claims.get("scope", "")
        if scope not in ("dl:*", f"dl:{bucket}/{key}"):
            # Session tokens and foreign-object capabilities are refused:
            # the ?token= travels in a shareable URL.
            raise web.HTTPForbidden(text="token not valid for this object")
        ak = claims.get("sub")
        try:
            ident = self.s.iam.identify(ak)
        except se.InvalidAccessKey:
            raise web.HTTPForbidden(text="unknown identity") from None
        set_condition_context(self.s._condition_context(request, ident))
        if not self._allowed(ident, "s3:GetObject", bucket, key):
            raise web.HTTPForbidden(text="GetObject denied")
        import asyncio

        loop = asyncio.get_running_loop()
        info, stream = await loop.run_in_executor(
            None, lambda: self.s.obj.get_object(bucket, key))
        ctype = info.content_type or "application/octet-stream"
        # Inline rendering (the console's preview pane) only for content
        # types that cannot execute script, and even then sandboxed: the
        # download URL lives on the console origin, so an inline HTML
        # object would otherwise run attacker script with console reach.
        inline = (request.query.get("inline") == "1"
                  and (ctype.startswith(("image/", "video/", "audio/"))
                       or ctype in ("text/plain", "application/json",
                                    "application/pdf", "text/csv")))
        disp = "inline" if inline else "attachment"
        headers = {
            "Content-Type": ctype,
            "Content-Length": str(info.size),
            "X-Content-Type-Options": "nosniff",
            "Content-Disposition":
                f'{disp}; filename="{key.rsplit("/", 1)[-1]}"'}
        if ctype != "application/pdf":
            # Sandbox anything that could carry script (svg images, html
            # downloads). PDFs are exempt: Chromium refuses to start its
            # PDF viewer in a sandboxed context, and the viewer brings
            # its own isolation.
            headers["Content-Security-Policy"] = "sandbox"
        resp = web.StreamResponse(status=200, headers=headers)
        await resp.prepare(request)
        it = iter(stream)
        while True:
            chunk = await loop.run_in_executor(None, next, it, None)
            if chunk is None:
                break
            await resp.write(chunk)
        await resp.write_eof()
        return resp


def _rpc_result(rid, result) -> web.Response:
    return web.json_response({"jsonrpc": "2.0", "id": rid, "result": result})


def _rpc_error(rid, code: int, message: str) -> web.Response:
    return web.json_response({"jsonrpc": "2.0", "id": rid,
                              "error": {"code": code, "message": message}})
