"""S3 API error codes + mapping from internal exceptions.

Reference: cmd/api-errors.go (the big toAPIErrorCode switch). Each APIError
renders as the S3 error XML document with Code/Message/Resource/RequestId.
"""

from __future__ import annotations

from dataclasses import dataclass

from minio_tpu.utils import errors as se


@dataclass(frozen=True)
class APIError:
    code: str
    message: str
    http_status: int


ERRORS = {
    "AccessDenied": APIError("AccessDenied", "Access Denied.", 403),
    "BadDigest": APIError("BadDigest", "The Content-Md5 you specified did not match what we received.", 400),
    "BucketAlreadyOwnedByYou": APIError("BucketAlreadyOwnedByYou", "Your previous request to create the named bucket succeeded and you already own it.", 409),
    "BucketAlreadyExists": APIError("BucketAlreadyExists", "The requested bucket name is not available.", 409),
    "BucketNotEmpty": APIError("BucketNotEmpty", "The bucket you tried to delete is not empty.", 409),
    "EntityTooLarge": APIError("EntityTooLarge", "Your proposed upload exceeds the maximum allowed object size.", 400),
    "EntityTooSmall": APIError("EntityTooSmall", "Your proposed upload is smaller than the minimum allowed object size.", 400),
    "IncompleteBody": APIError("IncompleteBody", "You did not provide the number of bytes specified by the Content-Length HTTP header.", 400),
    "InternalError": APIError("InternalError", "We encountered an internal error, please try again.", 500),
    "InvalidAccessKeyId": APIError("InvalidAccessKeyId", "The Access Key Id you provided does not exist in our records.", 403),
    "InvalidArgument": APIError("InvalidArgument", "Invalid Argument", 400),
    "InvalidBucketName": APIError("InvalidBucketName", "The specified bucket is not valid.", 400),
    "InvalidDigest": APIError("InvalidDigest", "The Content-Md5 you specified is not valid.", 400),
    "InvalidPart": APIError("InvalidPart", "One or more of the specified parts could not be found.", 400),
    "InvalidPartOrder": APIError("InvalidPartOrder", "The list of parts was not in ascending order.", 400),
    "InvalidRange": APIError("InvalidRange", "The requested range is not satisfiable", 416),
    "InvalidRequest": APIError("InvalidRequest", "Invalid Request", 400),
    "MalformedXML": APIError("MalformedXML", "The XML you provided was not well-formed or did not validate against our published schema.", 400),
    "MethodNotAllowed": APIError("MethodNotAllowed", "The specified method is not allowed against this resource.", 405),
    "MissingContentLength": APIError("MissingContentLength", "You must provide the Content-Length HTTP header.", 411),
    "NoSuchBucket": APIError("NoSuchBucket", "The specified bucket does not exist", 404),
    "NoSuchKey": APIError("NoSuchKey", "The specified key does not exist.", 404),
    "NoSuchUpload": APIError("NoSuchUpload", "The specified multipart upload does not exist. The upload ID may be invalid, or the upload may have been aborted or completed.", 404),
    "NoSuchVersion": APIError("NoSuchVersion", "The specified version does not exist.", 404),
    "NoSuchTagSet": APIError("NoSuchTagSet", "The TagSet does not exist", 404),
    "NotImplemented": APIError("NotImplemented", "A header you provided implies functionality that is not implemented", 501),
    "PreconditionFailed": APIError("PreconditionFailed", "At least one of the pre-conditions you specified did not hold", 412),
    "RequestTimeTooSkewed": APIError("RequestTimeTooSkewed", "The difference between the request time and the server's time is too large.", 403),
    "SignatureDoesNotMatch": APIError("SignatureDoesNotMatch", "The request signature we calculated does not match the signature you provided. Check your key and signing method.", 403),
    "SlowDown": APIError("SlowDown", "Resource requested is unreadable, please reduce your request rate", 503),
    "XAmzContentSHA256Mismatch": APIError("XAmzContentSHA256Mismatch", "The provided 'x-amz-content-sha256' header does not match what was computed.", 400),
    "ServiceUnavailable": APIError("ServiceUnavailable", "The service is unavailable. Please retry.", 503),
    "AuthorizationHeaderMalformed": APIError("AuthorizationHeaderMalformed", "The authorization header is malformed.", 400),
    "NoSuchBucketPolicy": APIError("NoSuchBucketPolicy", "The bucket policy does not exist", 404),
    "NoSuchWebsiteConfiguration": APIError("NoSuchWebsiteConfiguration", "The specified bucket does not have a website configuration", 404),
    "MalformedPolicy": APIError("MalformedPolicy", "Policy has invalid resource.", 400),
    "NoSuchLifecycleConfiguration": APIError("NoSuchLifecycleConfiguration", "The lifecycle configuration does not exist", 404),
    "ServerSideEncryptionConfigurationNotFoundError": APIError("ServerSideEncryptionConfigurationNotFoundError", "The server side encryption configuration was not found", 404),
    "ObjectLockConfigurationNotFoundError": APIError("ObjectLockConfigurationNotFoundError", "Object Lock configuration does not exist for this bucket", 404),
    "ReplicationConfigurationNotFoundError": APIError("ReplicationConfigurationNotFoundError", "The replication configuration was not found", 404),
    "InvalidBucketState": APIError("InvalidBucketState", "The request is not valid with the current state of the bucket.", 409),
    "ExpiredToken": APIError("ExpiredToken", "The provided token has expired.", 400),
    "InvalidToken": APIError("InvalidToken", "The provided token is malformed or otherwise invalid.", 400),
    "STSMissingParameter": APIError("MissingParameter", "A required parameter is missing.", 400),
    "STSNotImplemented": APIError("NotImplemented", "The requested STS action is not implemented.", 501),
}


class S3Error(Exception):
    def __init__(self, code: str, message: str | None = None,
                 resource: str = "", extra: dict | None = None):
        self.api = ERRORS[code]
        self.message = message or self.api.message
        self.resource = resource
        self.extra = extra or {}
        super().__init__(f"{code}: {self.message}")


_EXC_MAP: list[tuple[type, str]] = [
    (se.BucketNameInvalid, "InvalidBucketName"),
    (se.BucketExists, "BucketAlreadyOwnedByYou"),
    (se.BucketNotEmpty, "BucketNotEmpty"),
    (se.BucketNotFound, "NoSuchBucket"),
    (se.VersionNotFound, "NoSuchVersion"),
    (se.ObjectNotFound, "NoSuchKey"),
    (se.ObjectNameInvalid, "NoSuchKey"),
    (se.InvalidUploadID, "NoSuchUpload"),
    (se.InvalidPart, "InvalidPart"),
    (se.PartTooSmall, "EntityTooSmall"),
    (se.IncompleteBody, "IncompleteBody"),
    (se.InvalidRange, "InvalidRange"),
    (se.PreconditionFailed, "PreconditionFailed"),
    (se.InsufficientReadQuorum, "SlowDown"),
    (se.InsufficientWriteQuorum, "SlowDown"),
    # A deadline'd drive fan-out that still missed quorum: retryable 503,
    # never a 500 (the drive-resilience plane's visible degradation mode).
    (se.OperationTimedOut, "SlowDown"),
    (se.MethodNotAllowed, "MethodNotAllowed"),
    (se.FileNotFound, "NoSuchKey"),
    (se.StorageError, "InternalError"),
    (se.MalformedPolicy, "MalformedPolicy"),
    (se.InvalidAccessKey, "InvalidAccessKeyId"),
    (se.IAMError, "InvalidRequest"),
]


def from_exception(exc: Exception, resource: str = "") -> S3Error:
    if isinstance(exc, S3Error):
        return exc
    for etype, code in _EXC_MAP:
        if isinstance(exc, etype):
            return S3Error(code, resource=resource)
    return S3Error("InternalError", message=str(exc) or None, resource=resource)
