"""The S3 HTTP server: request classification, auth, dispatch.

Reference: cmd/routers.go + cmd/api-router.go + cmd/object-handlers.go /
cmd/bucket-handlers.go. S3 routing is query-string-driven, so instead of a
route table per verb we classify each request once (bucket, key, query,
method) and dispatch from one table — the same effect as the reference's
gorilla/mux Queries() matchers without the mux.

Run: python -m minio_tpu.s3.server --address 127.0.0.1:9000 /tmp/d{0...5}
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import os
import tempfile
import time
import urllib.parse
import uuid
from typing import Iterator

from aiohttp import web

from minio_tpu import obs, qos
from minio_tpu.obs import flight
from minio_tpu.admin.configkv import ConfigSys
from minio_tpu.admin.handlers import ADMIN_PREFIX, AdminAPI
from minio_tpu.admin.metrics import (
    OPENMETRICS_CONTENT_TYPE,
    PROM_CONTENT_TYPE,
    collect_cluster_metrics,
    collect_node_metrics,
    maybe_gzip,
    wants_openmetrics,
)
from minio_tpu.admin.stats import HTTPStats
from minio_tpu.bucket import objectlock as olock
from minio_tpu.crypto import compress as czip
from minio_tpu.crypto import sse
from minio_tpu.bucket.meta import BucketMetadataSys
from minio_tpu.erasure import ErasureObjects
from minio_tpu.erasure.types import CompletePart, ObjectOptions, ObjectToDelete
from minio_tpu.event import EventNotifier, new_object_event
from minio_tpu.event import event as evt
from minio_tpu.iam.actions import action_for
from minio_tpu.iam.policy import Policy, PolicyArgs
from minio_tpu.iam.sys import ANONYMOUS, IAMSys
from minio_tpu.s3 import sigv2, sigv4, xmlutil
from minio_tpu.s3.errors import S3Error, from_exception
from minio_tpu.storage import LocalDrive
from minio_tpu.utils import errors as se


class _MemStore:
    """In-memory sys-config store for backends without one (FS/tests)."""

    def __init__(self):
        self._docs: dict[str, bytes] = {}

    def read_sys_config(self, path: str) -> bytes:
        if path not in self._docs:
            raise se.FileNotFound(path)
        return self._docs[path]

    def write_sys_config(self, path: str, data: bytes) -> None:
        self._docs[path] = data

    def delete_sys_config(self, path: str) -> None:
        if self._docs.pop(path, None) is None:
            raise se.FileNotFound(path)

    def list_sys_config(self, prefix: str = "") -> list[str]:
        return sorted(k for k in self._docs if k.startswith(prefix))

XML_TYPE = "application/xml"
MAX_OBJECT_SIZE = 5 * (1 << 40)

# Request-path latency distributions (reference metrics-v2
# minio_s3_requests_* / minio_s3_ttfb_seconds). TTFB for a streamed GET
# is stamped when the response headers flush; buffered responses fall
# back to handler completion (bytes leave with the return).
_REQ_LATENCY = obs.histogram(
    "minio_tpu_s3_requests_latency_seconds",
    "End-to-end request latency by API", ("api",))
_REQ_TTFB = obs.histogram(
    "minio_tpu_s3_ttfb_seconds",
    "Time to first response byte by API", ("api",))
# Per-tenant SLO families (QoS plane, docs/QOS.md): tenant = the
# "access_key/bucket" key bound in _dispatch. Always on — the noisy-
# neighbor chaos gate reads scrape deltas of these to prove each
# victim's p99/5xx held while an aggressor shed.
_TENANT_LATENCY = obs.histogram(
    "minio_tpu_tenant_request_seconds",
    "End-to-end request latency by tenant", ("tenant",))
_TENANT_REQS = obs.counter(
    "minio_tpu_tenant_requests_total",
    "Requests by tenant and status class", ("tenant", "code"))
# Inline-object streams are plain list iterators (zero IO behind next()) —
# the GET fast path detects them by type to drain on the event loop.
_LIST_ITER = type(iter([]))
SPOOL_LIMIT = 32 << 20


def _scalar_claim(v) -> str | None:
    """Claim value as a condition string; compound claims (lists, maps)
    don't map to a single condition value and are skipped. The string
    spelling itself is the condition subsystem's (one coercion rule for
    stamping at STS issue time and evaluating at request time)."""
    from minio_tpu.iam.condition import scalar_str

    if isinstance(v, (str, int, float, bool)):
        return scalar_str(v)
    return None


def _int_q(q: dict, name: str, default: int, lo: int = 0, hi: int = 100_000) -> int:
    raw = q.get(name)
    if raw in (None, ""):
        return default
    try:
        v = int(raw)
    except ValueError:
        raise S3Error("InvalidArgument", f"invalid {name}") from None
    if not lo <= v <= hi:
        raise S3Error("InvalidArgument", f"{name} out of range")
    return v


class S3Server:
    def __init__(self, object_layer, credentials: sigv4.Credentials,
                 region: str = "us-east-1", versioned_buckets: bool = False,
                 notification_sys=None):
        self.obj = object_layer
        self.creds = credentials
        self.region = region
        # Server-level versioning default (tests/simple deployments);
        # per-bucket config from BucketMetadataSys overrides.
        self.versioned_buckets = versioned_buckets
        self.app = web.Application(client_max_size=1 << 30)
        self.app.router.add_route("*", "/{tail:.*}", self._entry)

        # Security + CORS headers on every response, including prepared
        # streams (reference addSecurityHeaders + CrossDomainPolicy/CORS,
        # cmd/generic-handlers.go). The allowed origin comes from the
        # `api.cors_allow_origin` config ("*" default, "" disables).
        async def _security_headers(request, response):
            response.headers.setdefault("X-Content-Type-Options", "nosniff")
            response.headers.setdefault("X-XSS-Protection", "1; mode=block")
            response.headers.setdefault(
                "Content-Security-Policy", "block-all-mixed-content")
            response.headers.setdefault("Server", "minio-tpu")
            origin = self._cors_origin()
            if origin and request.headers.get("Origin"):
                response.headers.setdefault(
                    "Access-Control-Allow-Origin", origin)
                response.headers.setdefault(
                    "Access-Control-Expose-Headers",
                    "ETag, x-amz-version-id, x-amz-request-id, "
                    "Content-Range, Content-Length")

        self.app.on_response_prepare.append(_security_headers)

        # Subsystems persist into the quorum sys store when the backend
        # provides one (erasure); memory-only otherwise.
        has_store = hasattr(object_layer, "read_sys_config")
        store = object_layer if has_store else _MemStore()
        self.sys_store = store
        # Config + IAM are sealed at rest under the root credential
        # (cmd/config-encrypted.go role); bucket metadata and scanner
        # state stay plaintext, matching the reference's scope.
        from minio_tpu.crypto.configcrypt import SealedSysStore
        # Federated identity: MTPU_ETCD_ENDPOINT moves the IAM store to a
        # shared etcd cluster (reference cmd/etcd.go + iam-etcd-store.go
        # role) so every site sees the same users/policies; bucket
        # metadata and scanner state stay on the drive-quorum store, the
        # reference's scope. Sealing layers identically over either.
        self._etcd = None
        etcd_ep = os.environ.get("MTPU_ETCD_ENDPOINT", "")
        iam_backing = store if has_store else None
        if etcd_ep:
            from minio_tpu.dist.etcdstore import EtcdConfigStore
            self._etcd = EtcdConfigStore(
                etcd_ep,
                username=os.environ.get("MTPU_ETCD_USERNAME", ""),
                password=os.environ.get("MTPU_ETCD_PASSWORD", ""))
            iam_backing = self._etcd
        # IAM alone federates over etcd; per-cluster config, bucket
        # metadata, tiers and scanner state STAY on the drive-quorum
        # store — sharing e.g. storageclass EC:N between a 4-drive and a
        # 12-drive site would corrupt both.
        sealed = (SealedSysStore(store, credentials.secret_key)
                  if has_store else None)
        sealed_iam = (SealedSysStore(iam_backing, credentials.secret_key)
                      if iam_backing is not None else None)
        notify_bm = (notification_sys.invalidate_bucket_metadata
                     if notification_sys is not None else None)
        notify_iam = (notification_sys.reload_iam
                      if notification_sys is not None else None)
        self.bucket_meta = BucketMetadataSys(store, notify=notify_bm)
        self.iam = IAMSys(credentials.access_key, credentials.secret_key,
                          store=sealed_iam, notify=notify_iam)
        if self._etcd is not None:
            # Cross-cluster IAM changes land via the watch: another
            # site's user add/REMOVE shows up here within the poll
            # interval (iam-etcd-store.go watchIAM role). reload, not
            # load: deletions must drop from memory too.
            self._etcd.watch(
                "iam/", self.iam.reload,
                interval=float(os.environ.get(
                    "MTPU_ETCD_WATCH_INTERVAL", "5")))

        # Eventing: durable per-target queues under a local spool dir
        # (reference pkg/event/target/queuestore.go).
        queue_dir = os.environ.get(
            "MTPU_EVENT_QUEUE_DIR",
            os.path.join(tempfile.gettempdir(), f"mtpu-events-{os.getpid()}"))
        self.notifier = EventNotifier(queue_dir=queue_dir)
        self._rules_loaded: set = set()
        self._event_targets_cfg: str = ""
        self.scanner = None
        from minio_tpu.scanner.tracker import UpdateTracker
        self.update_tracker = UpdateTracker(
            store if has_store else None)

        # Admin plane + observability (cmd/admin-router.go, pkg/pubsub,
        # cmd/http-stats.go, cmd/config/).
        self.stats = HTTPStats()
        self.bandwidth: dict[str, dict[str, int]] = {}
        self._bw_mu = __import__("threading").Lock()
        # The PROCESS trace bus (reference globalTrace): storage, RPC and
        # erasure spans publish here too, so one `mc admin trace`
        # subscription sees the whole request path.
        self.trace_bus = obs.trace_bus()
        self.config = ConfigSys(sealed)
        # Per-bucket bandwidth ENFORCEMENT (pkg/bandwidth role) — rates
        # from the `bandwidth` config subsystem, applied to PUT ingest and
        # GET egress streams; the accounting dict above stays the monitor.
        from minio_tpu.utils.bandwidth import BandwidthThrottle
        self.bw_throttle = BandwidthThrottle(self.config)

        # Structured ops + audit logging (reference cmd/logger/): targets
        # come from the config KV subsystems logger_webhook / audit_webhook /
        # audit_file and can be (re)applied at runtime via admin config-set.
        from minio_tpu.logger import get_logger
        self.logger = get_logger()
        self.configure_logging()
        self.configure_event_targets()

        # Storage-class parity from the `storageclass` config (EC:N).
        self.apply_storage_class_config()

        # Replication plane (cmd/bucket-replication.go).
        from minio_tpu.replication.pool import BucketTargetSys, ReplicationPool
        self.bucket_targets = BucketTargetSys(store)
        self.replication = ReplicationPool(object_layer, self.bucket_meta,
                                           self.bucket_targets)
        from minio_tpu.admin.profiling import Profiler
        self.profiler = Profiler()

        # Bucket federation (cmd/etcd.go + pkg/dns role): when enabled,
        # bucket ownership registers in a shared directory and requests
        # for foreign buckets 307-redirect to the owning cluster.
        self.federation = None
        if (self.config.get("federation", "enable") or "") in (
                "on", "1", "true"):
            fdir = self.config.get("federation", "directory") or ""
            fep = self.config.get("federation", "endpoint") or ""
            if fdir and fep:
                from minio_tpu.dist.federation import (
                    FederationError,
                    FederationStore,
                )
                self.federation = FederationStore(fdir, fep)
                # Register buckets that predate federation (the
                # reference's initFederatorBackend does the same at
                # startup) — otherwise another cluster could claim the
                # name and split the namespace. Conflicts are logged,
                # not fatal: the operator must resolve a genuine split.
                for b in object_layer.list_buckets():
                    try:
                        self.federation.register(b.name)
                    except FederationError as e:
                        self.logger.error(
                            f"federation conflict at startup: {e}")

        # KMS for SSE-KMS envelope encryption (cmd/crypto/kes.go role):
        # a networked KES backend when kms.kes_endpoint is configured,
        # else local master keys.
        from minio_tpu.crypto.kes import kms_from_config
        self.kms = kms_from_config(self.config)

        # ILM tiers (transition targets; reference tier subsystem). Tier
        # docs carry remote-storage credentials — sealed like config/IAM.
        from minio_tpu.scanner.tiers import TierRegistry, set_global
        self.tiers = TierRegistry(sealed)
        set_global(self.tiers)
        self.admin = AdminAPI(self)

        # SLO plane (docs/SLO.md): arm the on-node metric ring + burn-
        # rate engine (no-op under MTPU_SLO=0), persist coarse history
        # through the sys store, and feed the exporter-side per-API
        # counters into the ring. Keyed source: a rebuilt server in the
        # same process replaces its predecessor's stats feed.
        from minio_tpu.obs import calibration as _calibration
        from minio_tpu.obs import slo as _slo
        _calibration.publish_build_info()
        _slo_engine = _slo.ensure_started(store=store)
        if _slo_engine is not None:
            _slo_engine.db.add_source(self._slo_stats_source,
                                      key="s3-stats")

        self.local_locker = None  # set by the cluster node when distributed
        self.notification = notification_sys  # peer fan-out (distributed)
        self.cluster_node = None
        # Advertised node identity: the `node` field on trace records and
        # the `server` label in the federated cluster scrape. Standalone
        # servers fall back to the process default (hostname);
        # attach_cluster overrides with the advertised host:port.
        self.node_name = ""

        # upload_id -> user_defined: saves a quorum metadata read per
        # UploadPart/ListParts (SSE decisions are sealed at create time and
        # immutable for the upload's life).
        self._mp_sse_cache: dict[str, dict] = {}

        from minio_tpu.s3.web import WebAPI
        self.web = WebAPI(self)

    def _cluster_scrape(self, openmetrics: bool = False) -> bytes:
        """The federated cluster scrape — ONE definition shared by
        /minio/v2/metrics/cluster and its /minio/admin/v3/metrics mirror
        (docs promise they match). Blocking; run in an executor."""
        return collect_cluster_metrics(
            self.obj, self.stats,
            self.scanner.usage if self.scanner else None,
            notification=self.notification,
            local_name=self.node_name,
            openmetrics=openmetrics)

    def _has_peers(self) -> bool:
        return bool(self.notification is not None
                    and getattr(self.notification, "peers", None))

    def _slo_stats_source(self):
        """TSDB source (obs/tsdb.py): the HTTPStats-derived per-API
        request/error counters only exist exporter-side, so the ring
        samples them through this closure."""
        snap = self.stats.snapshot()
        for api, s in snap["apis"].items():
            lbl = {"api": api}
            yield "minio_tpu_s3_requests_total", lbl, s["count"]
            yield "minio_tpu_s3_requests_errors_total", lbl, s["errors"]
            yield ("minio_tpu_s3_requests_5xx_errors_total", lbl,
                   s["5xx"])

    def _cors_origin(self) -> str:
        """api.cors_allow_origin, cached against the config generation —
        this runs on EVERY response."""
        gen = getattr(self.config, "generation", 0)
        cached = getattr(self, "_cors_cache", None)
        if cached is not None and cached[0] == gen:
            return cached[1]
        try:
            origin = self.config.get("api", "cors_allow_origin")
        except Exception:  # noqa: BLE001 - config not ready yet
            origin = "*"
        self._cors_cache = (gen, origin)
        return origin

    def apply_storage_class_config(self) -> None:
        """Parse storageclass.standard/rrs ("EC:N") and stamp the parity
        map onto every erasure set — live-appliable via admin config-set
        (reference cmd/config/storageclass)."""
        def parse(v: str):
            v = (v or "").strip().upper()
            if v.startswith("EC:"):
                try:
                    return int(v[3:])
                except ValueError:
                    return None
            return None

        sc_map = {}
        for key, name in (("standard", "STANDARD"), ("rrs", "RRS")):
            try:
                m = parse(self.config.get("storageclass", key))
            except Exception:  # noqa: BLE001
                m = None
            if m is not None:
                sc_map[name] = m
        # The per-set clamp (parity <= drives/2, reference
        # validateParity) applies where the geometry is known.
        layer = self.obj
        while layer is not None and not any(
                hasattr(layer, a) for a in ("pools", "sets", "drives")):
            layer = getattr(layer, "inner", None)
        stack = [layer] if layer is not None else []
        while stack:
            node = stack.pop()
            if node is None:
                continue
            for attr in ("pools", "sets"):
                kids = getattr(node, attr, None)
                if kids:
                    stack.extend(kids)
            if hasattr(node, "parity_for_class"):
                node.sc_parity = dict(sc_map)

    def start_scanner(self, interval: float = 60.0,
                      heal_objects: bool = True) -> None:
        """Boot the background data scanner (reference initDataScanner,
        cmd/data-scanner.go:65)."""
        from minio_tpu.scanner import DataScanner

        self.scanner = DataScanner(self.obj, self.bucket_meta,
                                   notifier=self.notifier,
                                   interval=interval,
                                   heal_objects=heal_objects,
                                   tracker=self.update_tracker,
                                   config=self.config,
                                   replication=self.replication)
        self.scanner.start()

    # Set by main() (the CLI entry point); embedded servers either leave it
    # None (restart reports NotImplemented) or override restart().
    restart_cmd: list[str] | None = None

    @property
    def can_restart(self) -> bool:
        return (self.restart_cmd is not None
                or "restart" in self.__dict__           # instance override
                or type(self).restart is not S3Server.restart)  # subclass

    def restart(self) -> None:
        """In-place process restart (`mc admin service restart` role,
        cmd/admin-handlers.go ServiceActionHandler): re-exec the command
        line main() registered; durable state (format, journals, config,
        IAM) is all on disk, so the new process resumes cleanly.
        Overridable hook so embedded/test servers can intercept."""
        if self.restart_cmd:
            os.execv(self.restart_cmd[0], self.restart_cmd)

    def shutdown(self) -> None:
        os._exit(0)

    def attach_cluster(self, node) -> None:
        """Wire this node's observability into the peer plane so every
        peer can pull our trace/console/info/profiles (the NotificationSys
        breadth of cmd/peer-rest-common.go:27-61)."""
        self.cluster_node = node
        self.notification = node.notification
        self.node_name = node.node_name
        # Admin force-unlock operates on THIS node's dsync locker (the
        # reference ForceUnlockHandler clears the local lock-rest
        # server): without this wire the endpoint 501s in exactly the
        # deployment it exists for. The chaos tier leans on it as the
        # documented remedy for a dead node's stale heal lock.
        self.local_locker = node.locker
        # Replication's faultplane identity: partition rules between
        # clusters name this node's advertised host:port as the source.
        self.replication.set_node(node.node_name)
        obs.set_default_node(node.node_name)
        node.hooks.trace_bus = self.trace_bus
        node.hooks.console_bus = self.logger.console_bus
        node.hooks.server_info = self.admin._server_info
        node.hooks.obd_info = self.admin._obd_info
        node.hooks.profiler = self.profiler
        # Flight-recorder federation: the perf/timeline endpoint fans
        # out the same way server_info does — each peer answers with its
        # local ring/worst boards, filtered server-side.
        node.hooks.perf_timeline = self.admin._perf_timelines
        # Metrics federation: peers scrape this node's node-scope
        # exposition over the peer plane and merge it under a `server`
        # label (admin/metrics.collect_cluster_metrics).
        node.hooks.metrics = lambda: collect_node_metrics(self.stats)
        # SLO federation: peers pull this node's worker-merged burn-rate
        # state for the federated GET /minio/admin/v3/slo.
        from minio_tpu.obs import slo as _slo
        node.hooks.slo = _slo.collect_local

    def configure_logging(self) -> None:
        """(Re)build log/audit targets from the config KV store — the
        dynamic subset of cmd/config: logger_webhook.{enable,endpoint,
        auth_token}, audit_webhook.{...}, audit_file.path."""
        from minio_tpu.logger import FileTarget, HTTPTarget

        log_targets: list = []
        audit_targets: list = []
        if (self.config.get("logger_webhook", "enable") or "") in ("on", "1", "true"):
            ep = self.config.get("logger_webhook", "endpoint") or ""
            if ep:
                log_targets.append(HTTPTarget(
                    ep, self.config.get("logger_webhook", "auth_token") or ""))
        if (self.config.get("audit_webhook", "enable") or "") in ("on", "1", "true"):
            ep = self.config.get("audit_webhook", "endpoint") or ""
            if ep:
                audit_targets.append(HTTPTarget(
                    ep, self.config.get("audit_webhook", "auth_token") or ""))
        audit_path = self.config.get("audit_file", "path") or ""
        if audit_path:
            audit_targets.append(FileTarget(audit_path))
        # Close displaced webhook targets — each holds a drain thread and
        # a bounded queue that would otherwise leak on every re-apply.
        for t in self.logger.targets[1:] + self.logger.audit_targets:
            if hasattr(t, "close"):
                t.close()
        self.logger.targets = self.logger.targets[:1] + log_targets
        self.logger.audit_targets = audit_targets

    def configure_event_targets(self) -> None:
        """(Re)apply notification targets from the notify_* config
        subsystems (reference cmd/config/notify + pkg/event/target/*):
        enabled targets register, changed ones are replaced, disabled ones
        unregister. Reads through ConfigSys.get so env overrides keep
        their documented precedence."""
        import json as _json

        from minio_tpu.event.targets import (
            AMQPTarget,
            ElasticsearchTarget,
            KafkaTarget,
            MQTTTarget,
            MySQLTarget,
            NATSTarget,
            NSQTarget,
            PostgresTarget,
            RedisTarget,
            WebhookTarget,
        )

        subsys_keys = {
            "notify_webhook": ("enable", "endpoint", "auth_token"),
            "notify_nats": ("enable", "address", "subject"),
            "notify_redis": ("enable", "address", "key", "password", "format"),
            "notify_mqtt": ("enable", "address", "topic"),
            "notify_elasticsearch": ("enable", "url", "index"),
            "notify_nsq": ("enable", "address", "topic"),
            "notify_kafka": ("enable", "brokers", "topic"),
            "notify_amqp": ("enable", "url", "exchange", "routing_key",
                            "user", "password", "vhost"),
            "notify_postgres": ("enable", "address", "table", "user",
                                "password", "database"),
            "notify_mysql": ("enable", "address", "table", "user",
                             "password", "database"),
        }
        cfg = {s: {k: self.config.get(s, k) or "" for k in keys}
               for s, keys in subsys_keys.items()}
        sig = _json.dumps(cfg, sort_keys=True)
        if sig == self._event_targets_cfg:
            return
        self._event_targets_cfg = sig

        def on(s):
            return cfg[s]["enable"] in ("on", "1", "true")

        targets = []

        def add(factory) -> None:
            # A malformed persisted value (bad URL/port/table name) must
            # degrade to a logged error, never an unbootable server:
            # this runs during __init__ on every start.
            try:
                targets.append(factory())
            except (ValueError, OSError, KeyError) as e:
                self.logger.error(f"event target config invalid: {e}")
        if on("notify_webhook") and cfg["notify_webhook"]["endpoint"]:
            add(lambda: WebhookTarget(
                cfg["notify_webhook"]["endpoint"],
                auth_token=cfg["notify_webhook"]["auth_token"]))
        if on("notify_nats") and cfg["notify_nats"]["address"]:
            add(lambda: NATSTarget(cfg["notify_nats"]["address"],
                                      cfg["notify_nats"]["subject"]))
        if on("notify_redis") and cfg["notify_redis"]["address"]:
            add(lambda: RedisTarget(
                cfg["notify_redis"]["address"], cfg["notify_redis"]["key"],
                password=cfg["notify_redis"]["password"],
                publish=cfg["notify_redis"]["format"] == "channel"))
        if on("notify_mqtt") and cfg["notify_mqtt"]["address"]:
            add(lambda: MQTTTarget(cfg["notify_mqtt"]["address"],
                                      cfg["notify_mqtt"]["topic"]))
        if on("notify_elasticsearch") and cfg["notify_elasticsearch"]["url"]:
            add(lambda: ElasticsearchTarget(
                cfg["notify_elasticsearch"]["url"],
                cfg["notify_elasticsearch"]["index"]))
        if on("notify_nsq") and cfg["notify_nsq"]["address"]:
            add(lambda: NSQTarget(cfg["notify_nsq"]["address"],
                                     cfg["notify_nsq"]["topic"]))
        if on("notify_kafka") and cfg["notify_kafka"]["brokers"]:
            add(lambda: KafkaTarget(cfg["notify_kafka"]["brokers"],
                                       cfg["notify_kafka"]["topic"]))
        if on("notify_amqp") and cfg["notify_amqp"]["url"]:
            add(lambda: AMQPTarget(
                cfg["notify_amqp"]["url"],
                cfg["notify_amqp"]["exchange"],
                cfg["notify_amqp"]["routing_key"],
                user=cfg["notify_amqp"]["user"],
                password=cfg["notify_amqp"]["password"],
                vhost=cfg["notify_amqp"]["vhost"]))
        if on("notify_postgres") and cfg["notify_postgres"]["address"] \
                and cfg["notify_postgres"]["table"]:
            add(lambda: PostgresTarget(
                cfg["notify_postgres"]["address"],
                cfg["notify_postgres"]["table"],
                user=cfg["notify_postgres"]["user"],
                password=cfg["notify_postgres"]["password"],
                database=cfg["notify_postgres"]["database"]))
        if on("notify_mysql") and cfg["notify_mysql"]["address"] \
                and cfg["notify_mysql"]["table"]:
            add(lambda: MySQLTarget(
                cfg["notify_mysql"]["address"],
                cfg["notify_mysql"]["table"],
                user=cfg["notify_mysql"]["user"],
                password=cfg["notify_mysql"]["password"],
                database=cfg["notify_mysql"]["database"]))

        # Replace-or-remove semantics over the config-managed ARN space.
        managed_kinds = ("webhook", "nats", "redis", "mqtt",
                         "elasticsearch", "nsq", "kafka", "amqp",
                         "postgresql", "mysql")
        want = {t.arn: t for t in targets}
        for arn in list(self.notifier.target_arns):
            if arn.rsplit(":", 1)[-1] in managed_kinds and arn not in want:
                self.notifier.unregister_target(arn)
        for arn, t in want.items():
            if arn in self.notifier.target_arns:
                self.notifier.unregister_target(arn)  # config changed
            self.notifier.register_target(t)

    def start_auto_heal(self, interval: float = 10.0) -> None:
        """Boot the background new-drive healer (reference initAutoHeal,
        cmd/background-newdisks-heal-ops.go:241): drives carrying a
        persisted healing tracker get their set rebuilt and the tracker
        resumes across restarts."""
        from minio_tpu.erasure.autoheal import AutoHealer

        target = self.obj
        # unwrap decorators (cache) down to something with sets/drives
        while not hasattr(target, "drives") and hasattr(target, "inner"):
            target = target.inner
        pools = getattr(target, "pools", None)
        load_fn = lambda: self.stats.current_requests  # noqa: E731
        if pools:
            self.auto_healer = [AutoHealer(p, interval=interval,
                                           config=self.config,
                                           load_fn=load_fn)
                                for p in pools]
            for h in self.auto_healer:
                h.start()
        elif hasattr(target, "drives") or hasattr(target, "sets"):
            self.auto_healer = [AutoHealer(target, interval=interval,
                                           config=self.config,
                                           load_fn=load_fn)]
            self.auto_healer[0].start()
        else:
            self.auto_healer = []

    # ------------------------------------------------------------------

    def _lookup(self, access_key: str):
        try:
            return sigv4.Credentials(access_key,
                                     self.iam.get_secret(access_key))
        except se.InvalidAccessKey:
            return None

    def _bucket_versioned(self, bucket: str) -> bool:
        if self.versioned_buckets:
            return True
        return self.bucket_meta.get(bucket).versioning_enabled

    def _condition_context(self, request, identity,
                           q: dict | None = None) -> dict[str, list[str]]:
        """The request's condition values (reference getConditionValues,
        cmd/bucket-policy.go:65-110): every authorized request carries a
        POPULATED context so conditioned statements — above all a
        conditioned Deny — evaluate against real values instead of
        silently not applying. Keys are lowercase (condition keys are
        case-insensitive); values are string lists."""
        now = time.time()
        # Same trust gate as _client_ip: behind a TLS-terminating proxy
        # the backend hop is plaintext, so the canonical enforce-TLS
        # Deny (Bool aws:SecureTransport false) would lock the bucket
        # for everyone unless X-Forwarded-Proto is honored.
        secure = request.secure
        if (self.config.get("api", "trust_proxy_headers") or "") in (
                "on", "1", "true"):
            fwd_proto = request.headers.get("X-Forwarded-Proto", "")
            if fwd_proto:
                secure = fwd_proto.split(",")[0].strip().lower() == "https"
        ctx: dict[str, list[str]] = {
            "aws:sourceip": [self._client_ip(request)],
            "aws:securetransport": ["true" if secure else "false"],
            "aws:currenttime": [time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime(now))],
            "aws:epochtime": [str(int(now))],
        }
        ua = request.headers.get("User-Agent", "")
        if ua:
            ctx["aws:useragent"] = [ua]
        referer = request.headers.get("Referer", "")
        if referer:
            ctx["aws:referer"] = [referer]
        kind = getattr(identity, "kind", "anonymous")
        if kind == "anonymous":
            ctx["aws:principaltype"] = ["Anonymous"]
        else:
            ctx["aws:principaltype"] = [
                {"root": "Account", "sts": "AssumedRole"}.get(kind, "User")]
            # MinIO usernames ARE access keys; temp/service credentials
            # report their owning user (cmd/iam.go policy variables).
            ctx["aws:username"] = [identity.parent or identity.access_key]
            ctx["aws:userid"] = [identity.access_key]
        # Auth classification (set during signature verification; absent
        # on the web/admin JWT planes, where the keys stay missing).
        auth = request.get("auth-type")
        if auth:
            ctx["s3:authtype"] = [auth[0]]
            ctx["s3:signatureversion"] = [auth[1]]
        # STS claim values ("jwt:sub", "ldap:username", ...) let
        # WebIdentity/LDAP session policies scope by claim.
        for ck, cv in getattr(identity, "claims", {}).items():
            lk = str(ck).lower()
            if lk.startswith(("jwt:", "ldap:")):
                ctx[lk] = [str(cv)]
        if q:
            if q.get("versionId"):
                ctx["s3:versionid"] = [q["versionId"]]
            # Listing scope keys ride only when the client sent them
            # (AWS populates s3:prefix et al. per-request, not with
            # defaults — a policy requiring s3:prefix must see an
            # unprefixed listing as non-matching).
            for qk, ck2 in (("prefix", "s3:prefix"),
                            ("delimiter", "s3:delimiter"),
                            ("max-keys", "s3:max-keys")):
                if qk in q:
                    ctx[ck2] = [q[qk]]
        for hk, ck3 in (
                ("x-amz-object-lock-mode", "s3:object-lock-mode"),
                ("x-amz-object-lock-retain-until-date",
                 "s3:object-lock-retain-until-date"),
                ("x-amz-object-lock-legal-hold",
                 "s3:object-lock-legal-hold"),
                ("x-amz-acl", "s3:x-amz-acl"),
                ("x-amz-copy-source", "s3:x-amz-copy-source"),
                ("x-amz-storage-class", "s3:x-amz-storage-class"),
                ("x-amz-metadata-directive", "s3:x-amz-metadata-directive"),
                ("x-amz-server-side-encryption",
                 "s3:x-amz-server-side-encryption"),
                ("x-amz-server-side-encryption-aws-kms-key-id",
                 "s3:x-amz-server-side-encryption-aws-kms-key-id"),
                ("x-amz-content-sha256", "s3:x-amz-content-sha256"),
        ):
            hv = request.headers.get(hk, "")
            if hv:
                ctx[ck3] = [hv]
        # Already lowercase str-lists — mark it so the PolicyArgs built
        # from this context (one per _check_access; one per KEY on bulk
        # delete) don't each re-copy the dict.
        from minio_tpu.iam.condition import normalize_values
        return normalize_values(ctx)

    def _check_access(self, identity, action: str, bucket: str, key: str,
                      conditions: dict) -> None:
        """Authorize: identity policies ∪ bucket policy; explicit denies in
        either win (cmd/auth-handler.go:274 checkRequestAuthType).
        `conditions` is required — every call site supplies the populated
        per-request context from _condition_context (an empty default here
        made conditioned Deny statements silently inert)."""
        args = PolicyArgs(action=action, bucket=bucket, object=key,
                          conditions=conditions)
        pol_raw = (self.bucket_meta.get(bucket).policy_json
                   if bucket else b"")
        if pol_raw:
            bp = Policy.parse_cached(pol_raw)
            bargs = PolicyArgs(action=action, bucket=bucket, object=key,
                               conditions=conditions,
                               account=identity.access_key or "*")
            # Bucket-policy deny beats everything, including identity allow.
            for st in bp.statements:
                if st.effect == "Deny" and st.applies(bargs):
                    raise S3Error("AccessDenied", resource=f"/{bucket}/{key}")
            if bp.is_allowed(bargs):
                return
        if self.iam.is_allowed(identity, args):
            return
        raise S3Error("AccessDenied", resource=f"/{bucket}/{key}")

    @staticmethod
    def _require_private_acl(request, body: bytes) -> None:
        """PutBucketAcl/PutObjectAcl accept only the private canned ACL
        (header or XML body); grants the policy model can't express are
        refused with NotImplemented (reference acl-handlers.go)."""
        canned = request.headers.get("x-amz-acl", "")
        if canned and canned != "private":
            raise S3Error("NotImplemented",
                          f"canned ACL {canned!r} is not supported")
        try:
            if not xmlutil.acl_body_is_private(body):
                raise S3Error("NotImplemented",
                              "only the private (FULL_CONTROL owner) ACL "
                              "is supported")
        except ValueError:
            raise S3Error("MalformedXML") from None

    async def _entry(self, request: web.Request) -> web.StreamResponse:
        request_id = uuid.uuid4().hex[:16].upper()
        # The request id IS the trace id: bound to the handler's context
        # here, copied into every executor/pool hop (obs.ctx_wrap), and
        # carried to peers as the x-mtpu-trace-id RPC header — every
        # trace record this request causes, on any node, shares it.
        obs.set_trace_context(request_id, node=self.node_name or None)
        # Flight recorder: the stage timeline opens with the trace
        # context and closes (final `resp_drain` segment) in the finally
        # below — queryable via /minio/admin/v3/perf/timeline.
        flight.begin(request_id)
        path = urllib.parse.unquote(request.raw_path.split("?", 1)[0])
        if request.method == "OPTIONS" and request.headers.get("Origin") \
                and self._cors_origin():
            # CORS preflight (reference CorsHandler) — only when CORS is
            # enabled; Authorization must be listed explicitly (the Fetch
            # spec's wildcard excludes it, which would block signed
            # cross-origin requests). Allow-Origin attaches in the shared
            # on_response_prepare hook.
            return web.Response(status=200, headers={
                "Access-Control-Allow-Methods":
                    "GET, PUT, POST, DELETE, HEAD",
                "Access-Control-Allow-Headers":
                    "Authorization, Content-Type, Content-MD5, "
                    "x-amz-date, x-amz-content-sha256, "
                    "x-amz-security-token, x-amz-user-agent, *",
                "Access-Control-Max-Age": "3600"})
        t0 = self.stats.begin(
            request_id=request_id,
            api_hint=request.method.lower(),
            remote=self._client_ip(request),
            # Live API resolution: dispatch stamps request["api"] once it
            # classifies the call; the `top api` view reads it through
            # this getter so an in-flight request shows its real API.
            api_get=lambda: request.get("api"),
            # Same lazy contract for the tenant column: bound by
            # dispatch after auth resolves the identity.
            tenant_get=lambda: request.get("tenant"))
        request["mtpu-t0"] = t0
        resp = None
        canceled = False
        try:
            # Request-concurrency throttle (reference maxClients,
            # cmd/handler-api.go:136): over the configured ceiling new
            # requests shed with 503 + Retry-After rather than queue.
            limit = int(self.config.get("api", "requests_max") or 0)
            if limit and self.stats.current_requests > limit:
                raise S3Error("SlowDown", resource=path)
            resp = await self._dispatch(request, path, request_id)
            return resp
        except S3Error as e:
            if e.api.code == "NoSuchBucket":
                fed = await self._federation_redirect(request, path)
                if fed is not None:
                    resp = fed
                    return resp
            resp = self._error_response(e, path, request_id)
            return resp
        except web.HTTPException as e:  # web-console handlers raise these
            resp = e
            raise
        except asyncio.CancelledError:
            # Client went away mid-request (aiohttp cancels the handler):
            # account it separately — a disconnect is not a server error.
            canceled = True
            raise
        except Exception as e:  # noqa: BLE001 - surface as S3 InternalError
            s3e = from_exception(e, path)
            if s3e.api.code == "NoSuchBucket":
                fed = await self._federation_redirect(request, path)
                if fed is not None:
                    resp = fed
                    return resp
            resp = self._error_response(s3e, path, request_id)
            return resp
        finally:
            status = resp.status if resp is not None else 500
            if canceled and resp is None:
                # Client closed the connection before a response formed —
                # nginx's 499, NOT a server error.
                status = 499
            api = request.get("api", request.method.lower())
            rx = request.content_length or 0
            tx = (resp.content_length or 0) if resp is not None else 0
            dt = time.perf_counter() - t0
            flight.set_api(api)
            flight.end(status=status)
            self.stats.end(api, t0, status, rx=rx, tx=tx, canceled=canceled,
                           request_id=request_id)
            _REQ_LATENCY.labels(api=api).observe(dt)
            tkey = request.get("tenant")
            if tkey:
                # metric_key folds unbounded tenant keys (scanner
                # probes mint "anonymous/<path>" pre-bucket-check) into
                # "~other" past the registry cardinality backstop.
                mkey = qos.metric_key(tkey)
                _TENANT_LATENCY.labels(tenant=mkey).observe(dt)
                _TENANT_REQS.labels(
                    tenant=mkey, code=f"{status // 100}xx").inc()
            # Streamed GETs stamp first-byte at header flush; everything
            # else flushes with the handler return, so TTFB == latency.
            ttfb = request.get("mtpu-ttfb")
            _REQ_TTFB.labels(api=api).observe(dt if ttfb is None else ttfb)
            # Per-bucket bandwidth accounting (pkg/bandwidth role).
            bkt = path.lstrip("/").split("/", 1)[0]
            if bkt and not bkt.startswith("minio") and (rx or tx):
                with self._bw_mu:
                    b = self.bandwidth.setdefault(
                        bkt, {"rx": 0, "tx": 0})
                    b["rx"] += rx
                    b["tx"] += tx
            # Trace record only when someone is watching
            # (cmd/handler-utils.go:362-364 zero-overhead contract).
            if self.trace_bus.has_subscribers:
                import time as _time

                rec = {
                    "type": "http",
                    "time": _time.time(), "api": api,
                    "method": request.method, "path": path,
                    "status": status, "requestId": request_id,
                    "remote": self._client_ip(request),
                    "durationNs": int(dt * 1e9),
                    "rx": rx, "tx": tx,
                }
                if canceled:
                    rec["canceled"] = True
                if ttfb is not None:
                    rec["ttfbNs"] = int(ttfb * 1e9)
                # obs.publish enriches with trace_id + node (the bus is
                # the same object; the gate above already passed).
                obs.publish(rec)
            # Per-request AUDIT record (reference logger.AuditLog at every
            # handler, cmd/object-handlers.go:1378) — zero cost unless an
            # audit target is configured.
            if self.logger.audit_targets:
                import time as _time

                from minio_tpu.logger import audit_entry

                parts = path.lstrip("/").split("/", 1)
                ident = request.get("identity")
                self.logger.audit(audit_entry(
                    api=api,
                    bucket=parts[0] if parts and not parts[0].startswith("minio") else "",
                    object=parts[1] if len(parts) > 1 else "",
                    status_code=status,
                    access_key=getattr(ident, "access_key", "") or "",
                    remote_host=self._client_ip(request),
                    user_agent=request.headers.get("User-Agent", ""),
                    request_id=request_id,
                    rx_bytes=rx, tx_bytes=tx,
                    duration_ms=(_time.perf_counter() - t0) * 1000,
                    query=dict(urllib.parse.parse_qsl(request.query_string)),
                ))

    async def _federation_redirect(self, request, path: str):
        """307 to the owning cluster when the missing bucket is federated
        elsewhere (the server-side analogue of the reference's DNS
        bucket records; clients re-sign and follow)."""
        if self.federation is None:
            return None
        bucket = path.lstrip("/").split("/", 1)[0]
        if not bucket or bucket.startswith("minio"):
            return None
        # Directory lookup is shared-file I/O (possibly NFS): keep it off
        # the event loop like every other blocking call.
        loop = asyncio.get_running_loop()
        owner = await loop.run_in_executor(
            None, self.federation.lookup, bucket)
        if owner is None or owner == self.federation.endpoint:
            return None
        # raw_path keeps the client's percent-encoding — the decoded path
        # would corrupt keys containing '#', '%', '?' or non-ASCII.
        raw = request.raw_path.split("?", 1)[0]
        loc = owner + raw
        if request.query_string:
            loc += "?" + request.query_string
        return web.Response(status=307, headers={"Location": loc})

    def _client_ip(self, request) -> str:
        """Requester IP for audit/trace records. Proxy headers
        (X-Forwarded-For leftmost hop, X-Real-IP) are honored only when
        api.trust_proxy_headers is on — they are client-spoofable
        otherwise (pkg/handlers GetSourceIP role)."""
        if (self.config.get("api", "trust_proxy_headers") or "") in (
                "on", "1", "true"):
            fwd = request.headers.get("X-Forwarded-For", "")
            if fwd:
                return fwd.split(",")[0].strip()
            real = request.headers.get("X-Real-IP", "")
            if real:
                return real.strip()
        return request.remote or ""

    def _error_response(self, e: S3Error, resource: str, request_id: str):
        body = xmlutil.error_xml(e.api.code, e.message, resource, request_id, e.extra)
        return web.Response(
            status=e.api.http_status, body=body, content_type=XML_TYPE,
            headers={"x-amz-request-id": request_id},
        )

    async def _dispatch(self, request: web.Request, path: str,
                        request_id: str) -> web.StreamResponse:
        # ---------- health probes: unauthenticated (healthcheck-router) ----
        if path.startswith("/minio/health/"):
            request["api"] = "healthcheck"
            kind = path.rsplit("/", 1)[-1]
            if kind == "live":
                # Liveness = the process answers; never touches drives
                # (reference LivenessCheckHandler).
                return web.Response(status=200)
            if kind in ("ready", "cluster"):
                # Readiness/cluster = write-quorum aware: every set must
                # keep at least write-quorum drives online. With
                # ?maintenance=true the bar rises by one drive per set —
                # "can I take one more node down without losing quorum"
                # (reference ClusterCheckHandler + maintenance mode).
                maintenance = request.query.get(
                    "maintenance", "").lower() in ("true", "1", "yes")
                health_fn = getattr(self.obj, "health",
                                    lambda: {"healthy": True})
                loop = asyncio.get_running_loop()
                h = await loop.run_in_executor(None, health_fn)
                # Sets layer reports {"sets": [...]}, the pools layer
                # nests per-pool {"pools": [{"sets": [...]}]} — flatten.
                sets = h.get("sets") or [
                    s for p in h.get("pools", [])
                    for s in p.get("sets", [])]
                healthy = bool(h.get("healthy"))
                if maintenance and sets:
                    healthy = all(
                        s.get("online", 0) >= s.get("write_quorum", 0) + 1
                        for s in sets)
                headers = {}
                # Peer fabric: breaker-derived liveness. OPEN breakers
                # already fail drive probes instantly (so the quorum
                # math above is partition-fast); additionally, a node
                # that cannot reach a majority of the cluster is on the
                # minority side of a partition — report 503 so the load
                # balancer drains it even while its local drives alone
                # still clear write quorum.
                node = self.cluster_node
                if node is not None and node.peer_nodes:
                    fabric = node.peer_fabric_info()
                    open_peers = [p["peer"] for p in fabric
                                  if p["state"] == "open"]
                    total = len(fabric) + 1          # peers + self
                    reachable = total - len(open_peers)
                    # Drain only a STRICT minority side. On an exact even
                    # split (2-node cluster losing a node, 2-2 in a
                    # 4-node cluster) there is no minority — draining
                    # both halves would turn a partial failure into a
                    # full outage, so ties stay up and the drive
                    # write-quorum check above remains the arbiter.
                    if reachable * 2 < total:
                        healthy = False
                    headers["X-Minio-Peers-Online"] = str(reachable - 1)
                    headers["X-Minio-Peers-Offline"] = str(len(open_peers))
                if sets:
                    headers["X-Minio-Write-Quorum"] = str(
                        max(s.get("write_quorum", 0) for s in sets))
                    # Status must agree with the response code the caller
                    # gets — maintenance bar included.
                    headers["X-Minio-Server-Status"] = (
                        "online" if healthy else "degraded")
                return web.Response(status=200 if healthy else 503,
                                    headers=headers)
            raise S3Error("MethodNotAllowed", resource=path)

        query_items = [(k, v) for k, v in urllib.parse.parse_qsl(
            request.query_string, keep_blank_values=True)]
        q = dict(query_items)
        # --- auth (reference cmd/auth-handler.go:102 classification) ---
        # The classification also feeds the s3:authtype /
        # s3:signatureversion condition keys (request["auth-type"]).
        if "X-Amz-Signature" in q:
            creds = sigv4.verify_presigned(
                request.method, path, query_items, request.headers,
                self._lookup)
            # Honor a content binding if the signer pinned one in the
            # signed query (else anyone with the URL uploads arbitrary bytes).
            payload_hash = q.get("X-Amz-Content-Sha256", sigv4.UNSIGNED_PAYLOAD)
            auth_sig = None
            identity = self.iam.identify(creds.access_key)
            request["auth-type"] = ("REST-QUERY-STRING", "AWS4-HMAC-SHA256")
        elif request.headers.get("Authorization", "").startswith(sigv4.ALGORITHM):
            _, payload_hash = sigv4.verify_header_auth(
                request.method, path, query_items, request.headers, self._lookup)
            auth_sig = sigv4.parse_auth_header(request.headers["Authorization"])
            identity = self.iam.identify(auth_sig.access_key)
            request["auth-type"] = ("REST-HEADER", "AWS4-HMAC-SHA256")
        elif sigv2.is_v2_header(request.headers):
            # Legacy SigV2 clients (cmd/signature-v2.go).
            creds = sigv2.verify_header_auth(
                request.method, path, query_items, request.headers,
                self._lookup)
            auth_sig = None
            payload_hash = sigv4.UNSIGNED_PAYLOAD
            identity = self.iam.identify(creds.access_key)
            request["auth-type"] = ("REST-HEADER", "AWS")
        elif sigv2.is_v2_presigned(q):
            creds = sigv2.verify_presigned(
                request.method, path, query_items, request.headers,
                self._lookup)
            auth_sig = None
            payload_hash = sigv4.UNSIGNED_PAYLOAD
            identity = self.iam.identify(creds.access_key)
            request["auth-type"] = ("REST-QUERY-STRING", "AWS")
        else:
            # Anonymous: allowed only where the bucket policy grants it.
            identity, payload_hash, auth_sig = (
                ANONYMOUS, sigv4.UNSIGNED_PAYLOAD, None)

        request["identity"] = identity
        # Tenant identity (minio_tpu/qos): (access key, bucket), bound
        # ONCE here next to the trace contextvar — every batch-plane
        # submit, WAL record, shm ring slot and shed counter downstream
        # attributes to it (the contextvar crosses executor hops via
        # obs.ctx_wrap exactly like the trace id). The /minio/ admin
        # and metrics planes stay on the unattributed system lane —
        # the EXACT reserved segment only: a real bucket merely named
        # "minio-..." is a tenant like any other (quotas, metrics,
        # fairness), never the system lane.
        tpath = path.lstrip("/").split("/", 1)[0]
        if tpath != "minio":
            qos.bind(getattr(identity, "access_key", "") or "anonymous",
                     tpath)
            tkey = qos.current_key()
            request["tenant"] = tkey
            flight.set_tenant(tkey)
        # Timeline: everything up to here (header parse + signature
        # verification + identity resolution) is the auth stage.
        flight.mark("auth")

        # Temp (STS) credentials must also present their session token
        # (cmd/auth-handler.go getSessionToken check).
        if identity.kind == "sts":
            token = (request.headers.get("x-amz-security-token", "")
                     or q.get("X-Amz-Security-Token", ""))
            if not self.iam.verify_session_token(identity.access_key, token):
                raise S3Error("InvalidToken")

        # ---------- admin + metrics planes (signed requests only) ----------
        if path.startswith("/minio/"):
            from minio_tpu.admin.handlers import ADMIN_PREFIX

            if path.startswith(ADMIN_PREFIX):
                request["api"] = "admin." + path[len(ADMIN_PREFIX):].split(
                    "/", 1)[0]
                return await self.admin.handle(
                    request, path[len(ADMIN_PREFIX):], identity)
            if path in ("/minio/browser", "/minio/browser/"):
                # Single-file object browser (role of the reference's React
                # console, browser/app/js) — static page; auth happens
                # in-page against /minio/webrpc.
                request["api"] = "browser"
                return web.Response(body=_browser_page(),
                                    content_type="text/html")
            if path == "/minio/webrpc":
                request["api"] = "webrpc"
                return await self.web.rpc(request)
            if path.startswith("/minio/upload/"):
                request["api"] = "webupload"
                b, _, k = path[len("/minio/upload/"):].partition("/")
                return await self.web.upload(request, b, k)
            if path.startswith("/minio/download/"):
                request["api"] = "webdownload"
                b, _, k = path[len("/minio/download/"):].partition("/")
                return await self.web.download(request, b, k)
            if path == "/minio/v2/metrics/cluster":
                request["api"] = "metrics"
                self.admin.authorize_http(request, identity,
                                          "admin:Prometheus")
                loop = asyncio.get_running_loop()
                # OpenMetrics (exemplars) only applies single-node: the
                # multi-node merge relabels samples and cannot carry
                # exemplar suffixes (docs/SLO.md).
                om = (wants_openmetrics(request.headers.get("Accept"))
                      and not self._has_peers())
                # Federated: peer node scrapes merge in under a `server`
                # label, deadline-bounded (a hung peer becomes a scrape
                # error, never a hung scrape).
                body = await loop.run_in_executor(
                    None, self._cluster_scrape, om)
                body, enc = maybe_gzip(
                    body, request.headers.get("Accept-Encoding"))
                headers = {"Content-Type": OPENMETRICS_CONTENT_TYPE
                           if om else PROM_CONTENT_TYPE}
                if enc:
                    headers["Content-Encoding"] = enc
                return web.Response(body=body, headers=headers)
            if path == "/minio/v2/metrics/node":
                # Node-scope scrape: this process's planes only (the
                # reference's cluster/node metrics-v2 split).
                request["api"] = "metrics"
                self.admin.authorize_http(request, identity,
                                          "admin:Prometheus")
                loop = asyncio.get_running_loop()
                om = wants_openmetrics(request.headers.get("Accept"))
                body = await loop.run_in_executor(
                    None, lambda: collect_node_metrics(
                        self.stats, openmetrics=om))
                body, enc = maybe_gzip(
                    body, request.headers.get("Accept-Encoding"))
                headers = {"Content-Type": OPENMETRICS_CONTENT_TYPE
                           if om else PROM_CONTENT_TYPE}
                if enc:
                    headers["Content-Encoding"] = enc
                return web.Response(body=body, headers=headers)
            raise S3Error("MethodNotAllowed", resource=path)

        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""

        loop = asyncio.get_running_loop()

        def run(fn, *args, **kw):
            # Copy the request's context (trace id, node) into the
            # executor thread — run_in_executor does not propagate
            # contextvars, and the storage layer's trace records are
            # emitted from there.
            return loop.run_in_executor(
                None, obs.ctx_wrap(lambda: fn(*args, **kw)))

        m = request.method
        hdr = {"x-amz-request-id": request_id}

        # ---------- service level ----------
        if not bucket:
            if m == "POST":  # STS API rides the root path (sts-handlers.go)
                return await self._sts_handler(request, identity, hdr)
            if m == "GET":
                if identity.kind == "anonymous":
                    raise S3Error("AccessDenied", resource=path)
                buckets = await run(self.obj.list_buckets)
                if not identity.is_owner:
                    cond_ctx = self._condition_context(request, identity, q)
                    allowed = []
                    for b in buckets:
                        ok_args = PolicyArgs(action="s3:ListBucket",
                                             bucket=b.name,
                                             conditions=cond_ctx)
                        if self.iam.is_allowed(identity, ok_args):
                            allowed.append(b)
                    buckets = allowed
                return web.Response(body=xmlutil.list_buckets_xml(buckets),
                                    content_type=XML_TYPE, headers=hdr)
            raise S3Error("MethodNotAllowed", resource=path)

        # Auth params travel in the query on presigned requests; they are not
        # S3 subresources and must not affect routing.
        sub = {k for k in q if not k.startswith("X-Amz-")}

        # --- authorization (identity policies ∪ bucket policy) ---
        post_form = (m == "POST" and not key
                     and request.content_type == "multipart/form-data")
        action = action_for(m, sub, bucket, key, request.headers)
        request["api"] = "PostPolicy" if post_form else action.split(":", 1)[-1]
        bulk_delete = m == "POST" and not key and "delete" in q
        # Built once per request, reused by in-handler re-checks
        # (RestoreObject, bulk delete) — the values don't change
        # mid-request.
        cond_ctx = self._condition_context(request, identity, q)
        request["cond-ctx"] = cond_ctx
        if not post_form and not bulk_delete:
            # Browser POST uploads authenticate via the signed policy
            # document inside the form; the handler checks access itself.
            # Bulk delete authorizes per object key (AWS DeleteObjects
            # semantics) — an endpoint-level check against the bare bucket
            # resource would wrongly reject object-scoped policies.
            self._check_access(identity, action, bucket, key, cond_ctx)

        # ---------- bucket config subresources ----------
        if not key:
            resp = await self._bucket_subresource(request, bucket, m, sub,
                                                  q, hdr, run)
            if resp is not None:
                return resp

        # ---------- bucket level ----------
        if not key:
            if m == "PUT" and not sub:
                if self.federation is not None:
                    from minio_tpu.dist.federation import FederationError
                    try:
                        # Claim BEFORE creating: global name uniqueness
                        # (the reference's DNS check on MakeBucket).
                        await run(self.federation.register, bucket)
                    except FederationError:
                        raise S3Error("BucketAlreadyExists",
                                      resource=f"/{bucket}") from None
                    try:
                        await run(self.obj.make_bucket, bucket)
                    except BaseException:
                        # Release the claim — a failed create must not
                        # poison the global name for every cluster.
                        try:
                            await run(self.federation.unregister, bucket)
                        except Exception:  # noqa: BLE001
                            pass
                        raise
                else:
                    await run(self.obj.make_bucket, bucket)
                changes = {"created": __import__("time").time()}
                if request.headers.get(
                        "x-amz-bucket-object-lock-enabled", "").lower() == "true":
                    # Object lock requires versioning (S3 semantics).
                    changes["versioning_status"] = "Enabled"
                    changes["object_lock_xml"] = (
                        b'<ObjectLockConfiguration xmlns="http://s3.amazonaws'
                        b'.com/doc/2006-03-01/"><ObjectLockEnabled>Enabled'
                        b'</ObjectLockEnabled></ObjectLockConfiguration>')
                await run(self.bucket_meta.update, bucket, **changes)
                return web.Response(status=200, headers={**hdr, "Location": f"/{bucket}"})
            if m == "HEAD":
                await run(self.obj.get_bucket_info, bucket)
                return web.Response(status=200, headers=hdr)
            if m == "DELETE" and not sub:
                await run(self.obj.delete_bucket, bucket)
                await run(self.bucket_meta.drop_bucket, bucket)
                if self.federation is not None:
                    await run(self.federation.unregister, bucket)
                return web.Response(status=204, headers=hdr)
            if m == "POST" and "delete" in q:
                return await self._delete_objects(request, bucket, hdr, run)
            if m == "POST" and request.content_type == "multipart/form-data":
                return await self._post_policy_upload(request, bucket, hdr,
                                                      run)
            if m == "GET":
                if "versions" in q:
                    res = await run(
                        self.obj.list_object_versions, bucket,
                        q.get("prefix", ""), q.get("key-marker", ""),
                        q.get("version-id-marker", ""), q.get("delimiter", ""),
                        _int_q(q, "max-keys", 1000),
                    )
                    return web.Response(
                        body=xmlutil.list_versions_xml(bucket, q.get("prefix", ""), res),
                        content_type=XML_TYPE, headers=hdr)
                if "uploads" in q:
                    uploads = await run(
                        self.obj.list_multipart_uploads, bucket,
                        q.get("prefix", ""), _int_q(q, "max-uploads", 1000),
                    )
                    return web.Response(
                        body=xmlutil.list_uploads_xml(bucket, uploads),
                        content_type=XML_TYPE, headers=hdr)
                if "location" in q:
                    await run(self.obj.get_bucket_info, bucket)
                    body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                            b'<LocationConstraint xmlns="http://s3.amazonaws.com/'
                            b'doc/2006-03-01/"></LocationConstraint>')
                    return web.Response(body=body, content_type=XML_TYPE, headers=hdr)
                if q.get("list-type") == "2":
                    token = q.get("continuation-token", "")
                    start_after = q.get("start-after", "")
                    marker = token or start_after
                    res = await run(
                        self.obj.list_objects, bucket, q.get("prefix", ""),
                        marker, q.get("delimiter", ""),
                        _int_q(q, "max-keys", 1000),
                    )
                    return web.Response(
                        body=xmlutil.list_objects_v2_xml(
                            bucket, q.get("prefix", ""), token, start_after,
                            q.get("delimiter", ""), _int_q(q, "max-keys", 1000), res),
                        content_type=XML_TYPE, headers=hdr)
                res = await run(
                    self.obj.list_objects, bucket, q.get("prefix", ""),
                    q.get("marker", ""), q.get("delimiter", ""),
                    _int_q(q, "max-keys", 1000),
                )
                return web.Response(
                    body=xmlutil.list_objects_v1_xml(
                        bucket, q.get("prefix", ""), q.get("marker", ""),
                        q.get("delimiter", ""), _int_q(q, "max-keys", 1000), res),
                    content_type=XML_TYPE, headers=hdr)
            raise S3Error("MethodNotAllowed", resource=path)

        # ---------- object level ----------
        # S3's literal versionId "null" names the null (unversioned)
        # version; the journal resolves it to the empty stored id
        # (storage/xlmeta.py NULL_VERSION_REQ) — passed through verbatim
        # so it can never be mistaken for "latest" on versioned buckets.
        opts = ObjectOptions(
            version_id=q.get("versionId", ""),
            versioned=self._bucket_versioned(bucket),
        )
        if m in ("GET", "HEAD") and "tagging" in q:
            tags = await run(self.obj.get_object_tags, bucket, key, opts)
            return web.Response(body=xmlutil.tagging_xml(tags),
                                content_type=XML_TYPE, headers=hdr)
        if m == "PUT" and "tagging" in q:
            body = await request.read()
            tags = xmlutil.parse_tagging_xml(body)
            await run(self.obj.put_object_tags, bucket, key, tags, opts)
            return web.Response(status=200, headers=hdr)
        if m == "DELETE" and "tagging" in q:
            await run(self.obj.delete_object_tags, bucket, key, opts)
            return web.Response(status=204, headers=hdr)

        # ----- object ACL: canned FULL_CONTROL answer, private-only PUT
        #       (reference cmd/acl-handlers.go GetObjectACLHandler) -----
        if "acl" in q:
            if m in ("GET", "HEAD"):
                await run(self.obj.get_object_info, bucket, key, opts)
                return web.Response(body=xmlutil.acl_xml(),
                                    content_type=XML_TYPE, headers=hdr)
            if m == "PUT":
                self._require_private_acl(request, await request.read())
                await run(self.obj.get_object_info, bucket, key, opts)
                return web.Response(status=200, headers=hdr)
            # Terminal: DELETE ?acl must never fall through to the
            # object-DELETE branch below (S3 has no DeleteObjectAcl).
            raise S3Error("MethodNotAllowed", resource=path)

        # ----- object lock: retention / legal hold (pkg/bucket/object/lock,
        #       cmd/object-handlers.go PutObjectRetentionHandler etc.) -----
        if "retention" in q:
            if m == "PUT":
                try:
                    mode, until = olock.parse_retention_xml(await request.read())
                except ValueError:
                    raise S3Error("MalformedXML") from None
                info = await run(self.obj.get_object_info, bucket, key, opts)
                try:
                    olock.check_worm(
                        info.user_defined,
                        bypass_governance=request.headers.get(
                            "x-amz-bypass-governance-retention", ""
                        ).lower() == "true")
                except olock.WORMProtected as e:
                    raise S3Error("AccessDenied", str(e)) from None
                await run(self.obj.put_object_metadata, bucket, key,
                          {olock.KEY_MODE: mode,
                           olock.KEY_UNTIL: olock.to_iso(until)}, opts)
                return web.Response(status=200, headers=hdr)
            if m in ("GET", "HEAD"):
                info = await run(self.obj.get_object_info, bucket, key, opts)
                mode = info.user_defined.get(olock.KEY_MODE, "")
                until = info.user_defined.get(olock.KEY_UNTIL, "")
                if not mode:
                    raise S3Error("ObjectLockConfigurationNotFoundError",
                                  resource=f"/{bucket}/{key}")
                return web.Response(
                    body=olock.retention_xml(mode, olock.parse_iso(until)),
                    content_type=XML_TYPE, headers=hdr)
        if "legal-hold" in q:
            if m == "PUT":
                try:
                    status = olock.parse_legal_hold_xml(await request.read())
                except ValueError:
                    raise S3Error("MalformedXML") from None
                await run(self.obj.put_object_metadata, bucket, key,
                          {olock.KEY_HOLD: status}, opts)
                return web.Response(status=200, headers=hdr)
            if m in ("GET", "HEAD"):
                info = await run(self.obj.get_object_info, bucket, key, opts)
                status = info.user_defined.get(olock.KEY_HOLD, "")
                if not status:
                    raise S3Error("ObjectLockConfigurationNotFoundError",
                                  resource=f"/{bucket}/{key}")
                return web.Response(body=olock.legal_hold_xml(status),
                                    content_type=XML_TYPE, headers=hdr)

        # ----- S3 Select (reference SelectObjectContentHandler,
        #       cmd/object-handlers.go:95; engine pkg/s3select) -----
        if m == "POST" and "restore" in q:
            # RestoreObject: re-materialize a tiered version's data
            # (reference PostRestoreObjectHandler; our tiers read through,
            # so restore = pull the data back into the cluster).
            request["api"] = "RestoreObject"
            self._check_access(identity, "s3:RestoreObject", bucket, key,
                               request["cond-ctx"])
            if not hasattr(self.obj, "restore_transitioned"):
                raise S3Error("NotImplemented", resource=path)
            try:
                await run(self.obj.restore_transitioned, bucket, key,
                          opts.version_id)
            except se.ObjectError as e:
                raise from_exception(e, path) from None
            return web.Response(status=202, headers=hdr)

        if m == "POST" and "select" in q:
            from minio_tpu.s3select import S3SelectRequest, run_select
            from minio_tpu.s3select.sql import SelectError

            body = await request.read()
            try:
                sel = S3SelectRequest.parse_xml(body)
            except SelectError as e:
                raise S3Error("InvalidArgument", str(e)) from None
            info, stream, _size = await self._open_object_stream(
                request, bucket, key, opts, 0, -1, run)
            reader = _IterReader(stream)
            resp = web.StreamResponse(status=200, headers={
                **hdr, "Content-Type": "application/octet-stream"})
            await resp.prepare(request)

            def frames():
                try:
                    yield from run_select(reader, sel)
                except SelectError:
                    raise
            it = iter(frames())
            try:
                while True:
                    frame = await run(next, it, None)
                    if frame is None:
                        break
                    await resp.write(frame)
            except SelectError as e:
                # Past the prepared response: close the stream; errors
                # before any frame surface normally via the except path.
                await resp.write_eof()
                return resp
            await resp.write_eof()
            return resp

        # ----- multipart (reference cmd/erasure-multipart.go via
        #       object-handlers) -----
        if m == "POST" and "uploads" in q:
            user_defined = _metadata_headers(request)
            self._maybe_sse_multipart_create(request, bucket, key,
                                             user_defined)
            mp_opts = ObjectOptions(user_defined=user_defined)
            upload_id = await run(self.obj.new_multipart_upload, bucket, key, mp_opts)
            self._mp_cache_put(upload_id, dict(user_defined))
            return web.Response(
                body=xmlutil.initiate_multipart_xml(bucket, key, upload_id),
                content_type=XML_TYPE, headers=hdr)
        if "uploadId" in q:
            upload_id = q["uploadId"]
            if m == "PUT":
                part_number = _int_q(q, "partNumber", 0, lo=1, hi=10000)
                src = request.headers.get("x-amz-copy-source")
                if src:
                    return await self._upload_part_copy(
                        request, bucket, key, upload_id, part_number, src, hdr, run)
                return await self._put_part(request, bucket, key, upload_id,
                                            part_number, hdr, payload_hash,
                                            auth_sig, run)
            if m == "GET":
                parts = await run(self.obj.list_parts, bucket, key, upload_id,
                                  _int_q(q, "part-number-marker", 0),
                                  _int_q(q, "max-parts", 1000))
                mp_meta = await run(self._mp_user_defined, bucket, key,
                                    upload_id)
                if sse.META_ALGO in mp_meta:
                    # Report plaintext sizes (the reference reports the
                    # decrypted part size in ListObjectParts) so a client
                    # resuming by summing sizes lands on the right offset.
                    import dataclasses
                    parts = [dataclasses.replace(
                        p, size=sse.part_plain_size(p.size),
                        actual_size=sse.part_plain_size(p.size))
                        for p in parts]
                return web.Response(
                    body=xmlutil.list_parts_xml(bucket, key, upload_id, parts),
                    content_type=XML_TYPE, headers=hdr)
            if m == "DELETE":
                await run(self.obj.abort_multipart_upload, bucket, key, upload_id)
                self._mp_sse_cache.pop(upload_id, None)
                return web.Response(status=204, headers=hdr)
            if m == "POST":
                body = await request.read()
                pairs = xmlutil.parse_complete_multipart_xml(body)
                if not pairs:
                    raise S3Error("MalformedXML")
                parts = [CompletePart(n, e) for n, e in pairs]
                mp_meta = await run(self._mp_user_defined, bucket, key,
                                    upload_id)
                if sse.META_ALGO in mp_meta:
                    # The layer's 5 MiB minimum checks stored sizes; SSE
                    # framing inflates them, so enforce the S3 minimum on
                    # *plaintext* sizes here (AWS validates decrypted).
                    listed = {p.part_number: p for p in await run(
                        self.obj.list_parts, bucket, key, upload_id,
                        0, 10000)}
                    for n, _ in pairs[:-1]:
                        p = listed.get(n)
                        if p is not None and sse.part_plain_size(
                                p.size) < (5 << 20):
                            raise S3Error("EntityTooSmall")
                info = await run(self.obj.complete_multipart_upload, bucket,
                                 key, upload_id, parts, opts)
                self._mp_sse_cache.pop(upload_id, None)
                extra = {}
                if info.version_id:
                    extra["x-amz-version-id"] = info.version_id
                self.update_tracker.mark(bucket)
                self._emit(request, evt.OBJECT_CREATED_COMPLETE_MULTIPART,
                           bucket, key, size=info.size, etag=info.etag,
                           version_id=info.version_id)
                return web.Response(
                    body=xmlutil.complete_multipart_xml(
                        f"/{bucket}/{key}", bucket, key, info.etag),
                    content_type=XML_TYPE, headers={**hdr, **extra})

        if m == "HEAD":
            info = await run(self.obj.get_object_info, bucket, key, opts)
            if sse.META_ALGO in info.user_defined:
                self._sse_unseal(request, bucket, key, info.user_defined)
            if _check_conditional(request, info):
                return web.Response(status=304,
                                    headers={**hdr, "ETag": f'"{info.etag}"'})
            return web.Response(status=200, headers={**hdr, **_object_headers(info)})
        if m == "GET":
            return await self._get_object(request, bucket, key, opts, hdr, run)
        if m == "PUT":
            src = request.headers.get("x-amz-copy-source")
            if src:
                return await self._copy_object(request, bucket, key, src, opts, hdr, run)
            return await self._put_object(request, bucket, key, opts, hdr,
                                          payload_hash, auth_sig, run)
        if m == "DELETE":
            if opts.version_id:
                # Destroying a specific version: WORM check first
                # (cmd/bucket-object-lock.go enforceRetentionForDeletion).
                try:
                    pre = await run(self.obj.get_object_info, bucket, key, opts)
                    olock.check_worm(
                        pre.user_defined,
                        bypass_governance=request.headers.get(
                            "x-amz-bypass-governance-retention", ""
                        ).lower() == "true")
                except olock.WORMProtected as e:
                    raise S3Error("AccessDenied", str(e)) from None
                except S3Error:
                    raise
                except Exception:  # noqa: BLE001 - missing version: fall through
                    pass
            info = await run(self.obj.delete_object, bucket, key, opts)
            extra = {}
            if info.delete_marker:
                extra["x-amz-delete-marker"] = "true"
            if info.version_id:
                extra["x-amz-version-id"] = info.version_id
            self.update_tracker.mark(bucket)
            self._emit(request,
                       evt.OBJECT_REMOVED_DELETE_MARKER if info.delete_marker
                       else evt.OBJECT_REMOVED_DELETE,
                       bucket, key, version_id=info.version_id)
            from minio_tpu.replication.pool import OP_DELETE, ReplicationTask
            self.replication.queue_task(ReplicationTask(
                bucket, key, op=OP_DELETE))
            return web.Response(status=204, headers={**hdr, **extra})
        raise S3Error("MethodNotAllowed", resource=path)

    async def _post_policy_upload(self, request, bucket, hdr, run):
        """Browser form upload (reference PostPolicyBucketHandler,
        cmd/bucket-handlers.go + cmd/postpolicyform.go): the policy
        document IS the auth — signature over its base64, conditions
        enforced against the submitted fields."""
        reader = await request.multipart()
        form: dict[str, str] = {}
        file_bytes = b""
        filename = ""
        async for part in reader:
            name = (part.name or "").lower()
            if name == "file":
                filename = part.filename or ""
                file_bytes = await part.read(decode=False)
                break  # fields after the file are ignored (S3 semantics)
            form[name] = (await part.read(decode=False)).decode(
                "utf-8", "replace")

        creds = sigv4.verify_post_policy(form, self._lookup)
        request["auth-type"] = ("POST", "AWS4-HMAC-SHA256")
        # The "bucket" condition matches the request target, not a form
        # field (cmd/postpolicyform.go injects it the same way).
        form.setdefault("bucket", bucket)
        sigv4.check_post_policy_conditions(
            form.get("policy", ""), form, len(file_bytes))

        key = form.get("key", "")
        if not key:
            raise S3Error("InvalidArgument", "POST form requires key")
        key = key.replace("${filename}", filename)

        identity = self.iam.identify(creds.access_key)
        request["identity"] = identity
        self._check_access(identity, "s3:PutObject", bucket, key,
                           self._condition_context(request, identity))

        opts = ObjectOptions(versioned=self._bucket_versioned(bucket))
        if "content-type" in form:
            opts.user_defined["content-type"] = form["content-type"]
        for k, v in form.items():
            if k.startswith("x-amz-meta-") and not _is_reserved_meta(k):
                opts.user_defined[k] = v
        import io as _io

        info = await run(self.obj.put_object, bucket, key,
                         _io.BytesIO(file_bytes), len(file_bytes), opts)
        self.update_tracker.mark(bucket)
        self._emit(request, evt.OBJECT_CREATED_POST, bucket, key,
                   size=info.size, etag=info.etag,
                   version_id=info.version_id)
        status = int(form.get("success_action_status", "204"))
        if status not in (200, 201, 204):
            status = 204
        if status == 201:
            body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                    f'<PostResponse><Location>/{bucket}/{key}</Location>'
                    f'<Bucket>{bucket}</Bucket><Key>{key}</Key>'
                    f'<ETag>"{info.etag}"</ETag></PostResponse>').encode()
            return web.Response(status=201, body=body,
                                content_type=XML_TYPE, headers=hdr)
        return web.Response(status=status,
                            headers={**hdr, "ETag": f'"{info.etag}"'})

    # ------------------------------------------------------------------
    # bucket config subresources (policy/versioning/lifecycle/... —
    # reference per-feature files cmd/bucket-policy-handlers.go etc.)
    # ------------------------------------------------------------------

    async def _bucket_subresource(self, request, bucket, m, sub, q, hdr, run):
        """Handle ?policy/?versioning/?lifecycle/?tagging/?encryption/
        ?object-lock/?notification/?replication. Returns None if the
        request isn't a config subresource."""
        # Stored-verbatim XML configs: (query key, metadata field,
        # GET-miss error code).
        verbatim = {
            "lifecycle": ("lifecycle_xml", "NoSuchLifecycleConfiguration"),
            "tagging": ("tagging_xml", "NoSuchTagSet"),
            "encryption": ("sse_xml",
                           "ServerSideEncryptionConfigurationNotFoundError"),
            "replication": ("replication_xml",
                            "ReplicationConfigurationNotFoundError"),
        }
        config_subs = ({"policy", "versioning", "object-lock", "notification",
                        "acl", "website", "accelerate", "requestPayment",
                        "logging"}
                       | set(verbatim))
        if not (sub & config_subs):
            return None

        await run(self.obj.get_bucket_info, bucket)  # 404 before config

        # ----- ACL: canned answers only (reference cmd/acl-handlers.go:
        # 120-287 — access control is policy-based; ACL probes from SDK
        # tooling like gsutil `ls -L` / boto get_acl get the FULL_CONTROL
        # owner document, and only the private canned ACL is writable) --
        if "acl" in sub:
            if m in ("GET", "HEAD"):
                return web.Response(body=xmlutil.acl_xml(),
                                    content_type=XML_TYPE, headers=hdr)
            if m == "PUT":
                self._require_private_acl(request, await request.read())
                return web.Response(status=200, headers=hdr)
            raise S3Error("MethodNotAllowed", resource=f"/{bucket}")

        # ----- dummy subresources (reference cmd/dummy-handlers.go):
        # harmless defaults so SDK probes succeed instead of erroring ----
        if "website" in sub:
            if m in ("GET", "HEAD"):
                raise S3Error("NoSuchWebsiteConfiguration",
                              resource=f"/{bucket}")
            if m == "DELETE":
                return web.Response(status=204, headers=hdr)
            raise S3Error("NotImplemented", resource=f"/{bucket}")
        if "accelerate" in sub:
            if m in ("GET", "HEAD"):
                body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                        b'<AccelerateConfiguration xmlns="http://s3.amazon'
                        b'aws.com/doc/2006-03-01/"></AccelerateConfiguration>')
                return web.Response(body=body, content_type=XML_TYPE,
                                    headers=hdr)
            raise S3Error("NotImplemented", resource=f"/{bucket}")
        if "requestPayment" in sub:
            if m in ("GET", "HEAD"):
                body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                        b'<RequestPaymentConfiguration xmlns="http://s3.'
                        b'amazonaws.com/doc/2006-03-01/"><Payer>BucketOwner'
                        b'</Payer></RequestPaymentConfiguration>')
                return web.Response(body=body, content_type=XML_TYPE,
                                    headers=hdr)
            raise S3Error("NotImplemented", resource=f"/{bucket}")
        if "logging" in sub:
            if m in ("GET", "HEAD"):
                body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                        b'<BucketLoggingStatus xmlns="http://s3.amazonaws'
                        b'.com/doc/2006-03-01/"></BucketLoggingStatus>')
                return web.Response(body=body, content_type=XML_TYPE,
                                    headers=hdr)
            raise S3Error("NotImplemented", resource=f"/{bucket}")

        if "policy" in sub:
            if m == "PUT":
                body = await request.read()
                pol = Policy.parse(body)
                pol.validate()
                if any(s.principals is None for s in pol.statements):
                    raise S3Error("MalformedPolicy",
                                  "bucket policy requires Principal")
                await run(self.bucket_meta.update, bucket, policy_json=body)
                return web.Response(status=204, headers=hdr)
            if m == "GET":
                raw = self.bucket_meta.get(bucket).policy_json
                if not raw:
                    raise S3Error("NoSuchBucketPolicy", resource=f"/{bucket}")
                return web.Response(body=raw, content_type="application/json",
                                    headers=hdr)
            if m == "DELETE":
                await run(self.bucket_meta.update, bucket, policy_json=b"")
                return web.Response(status=204, headers=hdr)

        if "versioning" in sub:
            if m == "PUT":
                body = await request.read()
                try:
                    status = xmlutil.parse_versioning_xml(body)
                except ValueError:
                    raise S3Error("MalformedXML") from None
                meta = self.bucket_meta.get(bucket)
                if meta.object_lock_xml and status == "Suspended":
                    raise S3Error("InvalidBucketState",
                                  "object lock requires versioning")
                await run(self.bucket_meta.update, bucket,
                          versioning_status=status)
                return web.Response(status=200, headers=hdr)
            if m == "GET":
                status = self.bucket_meta.get(bucket).versioning_status
                if self.versioned_buckets and not status:
                    status = "Enabled"
                return web.Response(body=xmlutil.versioning_xml(status),
                                    content_type=XML_TYPE, headers=hdr)

        if "object-lock" in sub:
            if m == "PUT":
                body = await request.read()
                meta = self.bucket_meta.get(bucket)
                if not meta.versioning_enabled:
                    raise S3Error("InvalidBucketState",
                                  "object lock requires versioning")
                await run(self.bucket_meta.update, bucket,
                          object_lock_xml=body)
                return web.Response(status=200, headers=hdr)
            if m == "GET":
                raw = self.bucket_meta.get(bucket).object_lock_xml
                if not raw:
                    raise S3Error("ObjectLockConfigurationNotFoundError",
                                  resource=f"/{bucket}")
                return web.Response(body=raw, content_type=XML_TYPE,
                                    headers=hdr)

        if "notification" in sub:
            if m == "PUT":
                body = await request.read()
                try:
                    await run(self.notifier.set_bucket_rules, bucket, body)
                except ValueError as e:
                    raise S3Error("InvalidArgument", str(e)) from None
                self._rules_loaded.add(bucket)
                await run(self.bucket_meta.update, bucket,
                          notification_xml=body)
                return web.Response(status=200, headers=hdr)
            if m == "GET":
                raw = self.bucket_meta.get(bucket).notification_xml
                if not raw:
                    raw = (b'<?xml version="1.0" encoding="UTF-8"?>'
                           b'<NotificationConfiguration xmlns="http://s3.'
                           b'amazonaws.com/doc/2006-03-01/">'
                           b'</NotificationConfiguration>')
                return web.Response(body=raw, content_type=XML_TYPE,
                                    headers=hdr)

        for name, (attr, miss_code) in verbatim.items():
            if name not in sub:
                continue
            if m == "PUT":
                body = await request.read()
                _validate_xml(body)
                await run(self.bucket_meta.update, bucket, **{attr: body})
                return web.Response(status=200, headers=hdr)
            if m == "GET":
                raw = getattr(self.bucket_meta.get(bucket), attr)
                if not raw:
                    raise S3Error(miss_code, resource=f"/{bucket}")
                return web.Response(body=raw, content_type=XML_TYPE,
                                    headers=hdr)
            if m == "DELETE":
                await run(self.bucket_meta.update, bucket, **{attr: b""})
                return web.Response(status=204, headers=hdr)

        return None

    # ------------------------------------------------------------------
    # STS (reference cmd/sts-handlers.go — AssumeRole on the root path)
    # ------------------------------------------------------------------

    async def _sts_handler(self, request, identity, hdr):
        form = urllib.parse.parse_qs((await request.read()).decode())
        action = form.get("Action", [""])[0]
        duration = int(form.get("DurationSeconds", ["3600"])[0])
        session_policy = form.get("Policy", [""])[0]

        if action == "AssumeRole":
            if identity.kind == "anonymous":
                raise S3Error("AccessDenied", "STS requires signed credentials")
            if identity.kind in ("sts", "svc"):
                raise S3Error("AccessDenied",
                              "temporary credentials cannot assume roles")
            tc = self.iam.assume_role(identity.access_key, duration,
                                      session_policy)
            subject = ""
        elif action in ("AssumeRoleWithWebIdentity",
                        "AssumeRoleWithClientGrants"):
            # Federated: unauthenticated call carrying an IdP-signed JWT
            # (cmd/sts-handlers.go:49-102). The token IS the credential.
            from minio_tpu.iam.oidc import OIDCError, OpenIDValidator

            token = form.get(
                "WebIdentityToken" if action.endswith("WebIdentity")
                else "Token", [""])[0]
            if not token:
                raise S3Error("InvalidRequest", "missing identity token")
            try:
                validator = OpenIDValidator.from_config(self.config)
                if validator is None:
                    raise S3Error("STSNotImplemented",
                                  "identity_openid is not configured")
                claims = validator.validate(token)
                policies = validator.policies_from(claims)
            except OIDCError as e:
                raise S3Error("AccessDenied", str(e)) from None
            if not policies:
                raise S3Error(
                    "AccessDenied",
                    f"token carries no {validator.claim_name!r} claim")
            subject = str(claims.get("sub", ""))
            # Credentials never outlive the identity token itself
            # (cmd/sts-handlers.go caps at the JWT expiry).
            remaining = int(float(claims["exp"]) - time.time())
            if remaining <= 0:
                raise S3Error("AccessDenied", "identity token expired")
            duration = min(max(900, duration), remaining)
            # Scalar token claims travel namespaced ("jwt:sub", ...) so
            # session/identity policies can condition on them.
            jwt_claims = {f"jwt:{k}": s for k, v in claims.items()
                          if (s := _scalar_claim(v)) is not None}
            tc = self.iam.assume_role_with_claims(
                subject, policies, duration, session_policy,
                claims=jwt_claims)
        elif action == "AssumeRoleWithLDAPIdentity":
            from minio_tpu.iam.ldap import LDAPError, LDAPValidator

            username = form.get("LDAPUsername", [""])[0]
            password = form.get("LDAPPassword", [""])[0]
            if not username or not password:
                raise S3Error("InvalidRequest",
                              "LDAPUsername and LDAPPassword required")
            try:
                validator = LDAPValidator.from_config(self.config)
            except LDAPError as e:  # enabled-but-misconfigured: say so
                raise S3Error("InvalidRequest", str(e)) from None
            if validator is None:
                raise S3Error("STSNotImplemented",
                              "identity_ldap is not configured")
            policies = validator.policies
            if not policies:
                # Check BEFORE binding: an always-denied setup must not
                # hammer the directory with real authentications.
                raise S3Error("AccessDenied",
                              "no sts_policy configured for LDAP identities")
            try:
                # Blocking directory I/O stays off the event loop.
                loop = asyncio.get_running_loop()
                subject = await loop.run_in_executor(
                    None, validator.authenticate, username, password)
            except LDAPError as e:
                raise S3Error("AccessDenied", str(e)) from None
            tc = self.iam.assume_role_with_claims(
                subject, policies, max(900, duration), session_policy,
                claims={"ldap:username": username, "ldap:user": subject})
        else:
            raise S3Error("STSNotImplemented")

        import datetime
        exp = datetime.datetime.fromtimestamp(
            tc.expiry, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        body = xmlutil.sts_assume_role_xml(
            tc.access_key, tc.secret_key, tc.session_token, exp,
            hdr["x-amz-request-id"], action=action, subject=subject)
        return web.Response(body=body, content_type=XML_TYPE, headers=hdr)

    # ------------------------------------------------------------------
    # SSE (cmd/encryption-v1.go EncryptRequest/DecryptObjectInfo roles)
    # ------------------------------------------------------------------

    def _sse_master_key(self) -> bytes:
        """SSE-S3 master key: MTPU_KMS_SECRET_KEY env, else derived from
        the root secret (the reference requires a KMS; a derived local
        master keeps SSE-S3 usable out of the box)."""
        import hashlib as _hl

        secret = os.environ.get("MTPU_KMS_SECRET_KEY",
                                "mtpu-sse-s3:" + self.creds.secret_key)
        return _hl.sha256(secret.encode()).digest()

    def _maybe_compress_put(self, request, bucket: str, key: str, opts,
                            spool, size: int):
        """Wrap the upload in the streaming compressor when the
        compression config matches (isCompressible role). Returns
        (reader, size) — size becomes -1 (stream length unknown)."""
        if self.config.get("compression", "enable") != "on":
            return spool, size
        # SSE and compression don't stack (compressed-then-encrypted sizes
        # become doubly virtual; the reference also refuses).
        if (request.headers.get("x-amz-server-side-encryption")
                or request.headers.get(
                    "x-amz-server-side-encryption-customer-algorithm")):
            return spool, size
        exts = [e for e in self.config.get(
            "compression", "extensions").split(",") if e]
        mimes = [m for m in self.config.get(
            "compression", "mime_types").split(",") if m]
        ct = opts.user_defined.get("content-type", "")
        if not czip.is_compressible(key, ct, exts, mimes):
            return spool, size
        if size >= 0:
            opts.user_defined[czip.META_ACTUAL_SIZE] = str(size)
        scheme = czip.default_scheme()
        opts.user_defined[czip.META_COMPRESSION] = scheme
        return czip.CompressReader(spool, scheme), -1

    def _sse_setup(self, request, bucket: str, key: str,
                   user_defined: dict) -> bytes | None:
        """Decide SSE applicability (request headers or bucket default),
        then generate + seal a fresh per-object data key into metadata.
        Returns the plaintext object key, or None when SSE does not apply.
        Shared by single PUT and CreateMultipartUpload so their encryption
        decisions can never diverge."""
        import base64 as _b64
        import hashlib as _hl

        try:
            ssec_key = sse.parse_ssec_headers(request.headers)
        except sse.SSEError as e:
            raise S3Error("InvalidArgument", str(e)) from None
        sse_hdr = request.headers.get("x-amz-server-side-encryption", "")
        sse_s3 = sse_hdr == "AES256"
        sse_kms = sse_hdr == "aws:kms"
        kms_key_id = request.headers.get(
            "x-amz-server-side-encryption-aws-kms-key-id", "")
        if not sse_s3 and not sse_kms and ssec_key is None:
            # Bucket default SSE config (PUT ?encryption).
            default = self.bucket_meta.get(bucket).sse_xml
            if b"aws:kms" in default:
                sse_kms = True
            elif b"AES256" in default:
                sse_s3 = True
        if ssec_key is None and not sse_s3 and not sse_kms:
            return None
        aad = f"{bucket}/{key}"
        if sse_kms:
            # Envelope encryption: the KMS mints the per-object data key
            # and only the sealed blob is stored (cmd/encryption-v1.go:195
            # + cmd/crypto/kes.go GenerateKey role).
            from minio_tpu.crypto.kms import KMSError

            try:
                kid, object_key, sealed = self.kms.generate_data_key(
                    kms_key_id, context=aad)
            except KMSError as e:
                raise S3Error("InvalidRequest", f"KMS: {e}") from None
            user_defined[sse.META_ALGO] = "SSE-KMS"
            user_defined[sse.META_SEALED_KEY] = sealed
            user_defined[sse.META_KMS_KEY_ID] = kid
            return object_key
        object_key = os.urandom(32)
        if ssec_key is not None:
            user_defined[sse.META_ALGO] = "SSE-C"
            user_defined[sse.META_SEALED_KEY] = sse.seal_key(
                object_key, ssec_key, aad)
            user_defined[sse.META_KEY_MD5] = _b64.b64encode(
                _hl.md5(ssec_key).digest()).decode()
        else:
            user_defined[sse.META_ALGO] = "SSE-S3"
            user_defined[sse.META_SEALED_KEY] = sse.seal_key(
                object_key, self._sse_master_key(), aad)
        return object_key

    def _maybe_encrypt_put(self, request, bucket: str, key: str, opts,
                           spool, size: int):
        """Wrap the upload stream in a DARE encryptor when SSE applies.
        Returns (reader, stored_size)."""
        import base64 as _b64

        staged: dict = {}
        object_key = self._sse_setup(request, bucket, key, staged)
        if object_key is None:
            return spool, size
        if size < 0:
            raise S3Error("MissingContentLength",
                          "SSE requires a known content length")
        opts.user_defined.update(staged)
        nonce = os.urandom(12)
        opts.user_defined[sse.META_NONCE] = _b64.b64encode(nonce).decode()
        opts.user_defined[sse.META_ACTUAL_SIZE] = str(size)
        return (sse.EncryptReader(spool, object_key, nonce),
                sse.encrypted_size(size))

    def _maybe_sse_multipart_create(self, request, bucket: str, key: str,
                                    user_defined: dict) -> None:
        """Seal a per-upload object key at CreateMultipartUpload time when
        SSE applies; every part is then encrypted under it (reference
        newMultipartUpload encryption setup, cmd/erasure-multipart.go:269 +
        cmd/object-handlers.go NewMultipartUploadHandler). No META_NONCE is
        stored: parts are independent streams, each carrying its own
        random nonce as a 12-byte prefix."""
        self._sse_setup(request, bucket, key, user_defined)

    def _mp_cache_put(self, upload_id: str, meta: dict) -> None:
        if len(self._mp_sse_cache) > 2048:
            self._mp_sse_cache.clear()
        self._mp_sse_cache[upload_id] = meta

    def _mp_user_defined(self, bucket: str, key: str,
                         upload_id: str) -> dict:
        """The upload session's user metadata, cached per upload_id —
        immutable after CreateMultipartUpload, so UploadPart/ListParts
        skip the per-call quorum metadata read."""
        meta = self._mp_sse_cache.get(upload_id)
        if meta is None:
            meta = self.obj.get_multipart_info(
                bucket, key, upload_id).user_defined
            self._mp_cache_put(upload_id, meta)
        return meta

    def _maybe_encrypt_part(self, request, bucket: str, key: str,
                            upload_id: str, reader, size: int):
        """Wrap one part's stream in DARE encryption under the upload's
        sealed object key, with a fresh per-part nonce carried as a stream
        prefix. Returns (reader, stored_size)."""
        mp_meta = self._mp_user_defined(bucket, key, upload_id)
        if sse.META_ALGO not in mp_meta:
            return reader, size
        if size < 0:
            raise S3Error("MissingContentLength",
                          "SSE requires a known content length")
        object_key = self._sse_object_key(request, bucket, key, mp_meta)
        nonce = os.urandom(sse.NONCE_SIZE)
        part_key = sse.derive_part_key(object_key, nonce)
        return (_PrefixReader(nonce,
                              sse.EncryptReader(reader, part_key, nonce)),
                sse.encrypted_part_size(size))

    @staticmethod
    def _visible_size(info) -> int:
        """Client-visible (plaintext/uncompressed) byte count of an object
        — info.size is the stored size, which SSE and compression inflate
        or shrink."""
        if sse.META_ACTUAL_SIZE in info.user_defined:
            return int(info.user_defined[sse.META_ACTUAL_SIZE])
        if czip.META_ACTUAL_SIZE in info.user_defined:
            return int(info.user_defined[czip.META_ACTUAL_SIZE])
        if sse.META_ALGO in info.user_defined and info.parts:
            # Multipart SSE: derivable from the fixed DARE framing of each
            # independently-encrypted part.
            return sum(sse.part_plain_size(s) for _, s in info.parts)
        return info.size

    def _mp_sse_stream(self, request, bucket, key, opts, pre,
                       offset, length, copy_source=False):
        """(info, iterator, actual_size) for a multipart SSE object —
        parts are independently encrypted [nonce | DARE] streams laid
        back-to-back; decrypt only the chunks each part-range touches."""
        object_key = self._sse_object_key(request, bucket, key,
                                          pre.user_defined,
                                          copy_source=copy_source)
        if pre.version_id and not opts.version_id:
            # Pin the version across the per-part reads — a concurrent
            # overwrite mid-download must not splice replacement bytes
            # into the stream (single-PUT SSE reads in one backend call
            # and has no such window).
            import dataclasses
            opts = dataclasses.replace(opts, version_id=pre.version_id)
        plains = [sse.part_plain_size(stored) for _, stored in pre.parts]
        actual = sum(plains)
        if length < 0:
            length = actual - offset
        if offset < 0 or length < 0 or offset + length > actual:
            raise S3Error("InvalidRange", resource=f"/{bucket}/{key}")

        get = self.obj.get_object

        def gen():
            pos = 0        # plaintext cursor at current part start
            enc_pos = 0    # stored-byte cursor at current part start
            for (_, stored), plain in zip(pre.parts, plains):
                lo = max(offset - pos, 0)
                hi = min(offset + length - pos, plain)
                if hi > lo:
                    enc_off, enc_len, skip = sse.decrypted_range(
                        lo, hi - lo, plain)
                    if enc_off == 0:
                        # Nonce and data are adjacent: one backend read,
                        # peel the 12-byte nonce off the front.
                        _, raw = get(bucket, key, enc_pos,
                                     sse.NONCE_SIZE + enc_len, opts)
                        estream, nonce = _peel_prefix(raw, sse.NONCE_SIZE)
                        estream = _CloseProxy(estream, raw)
                    else:
                        _, nstream = get(bucket, key, enc_pos,
                                         sse.NONCE_SIZE, opts)
                        nonce = bytearray()
                        for piece in nstream:
                            nonce += piece
                        if len(nonce) != sse.NONCE_SIZE:
                            raise sse.SSEError(
                                f"part nonce truncated: {len(nonce)} bytes")
                        _, estream = get(
                            bucket, key, enc_pos + sse.NONCE_SIZE + enc_off,
                            enc_len, opts)
                    dec = sse.DecryptReader(
                        estream, sse.derive_part_key(object_key, nonce),
                        nonce, start_chunk=enc_off // sse.ENC_CHUNK,
                        total_chunks=sse.total_chunks(plain))
                    yield from _trim_iter(dec, skip, hi - lo, estream)
                pos += plain
                enc_pos += stored
                if pos >= offset + length:
                    return

        return pre, gen(), actual

    def _sse_object_key(self, request, bucket: str, key: str, meta: dict,
                        copy_source: bool = False) -> bytes:
        """Unseal the per-object data key; verifies SSE-C key headers."""
        algo = meta.get(sse.META_ALGO, "")
        aad = f"{bucket}/{key}"
        try:
            if algo == "SSE-C":
                ssec_key = sse.parse_ssec_headers(request.headers,
                                                  copy_source=copy_source)
                if ssec_key is None:
                    raise S3Error("InvalidRequest",
                                  "object is SSE-C encrypted: key required")
                return sse.unseal_key(
                    meta[sse.META_SEALED_KEY], ssec_key, aad)
            if algo == "SSE-KMS":
                from minio_tpu.crypto.kms import KMSError

                try:
                    return self.kms.decrypt_data_key(
                        meta[sse.META_SEALED_KEY], context=aad)
                except KMSError as e:
                    raise S3Error("AccessDenied", f"KMS: {e}") from None
            return sse.unseal_key(
                meta[sse.META_SEALED_KEY], self._sse_master_key(), aad)
        except sse.SSEError as e:
            raise S3Error("AccessDenied", str(e)) from None

    def _sse_unseal(self, request, bucket: str, key: str, meta: dict,
                    copy_source: bool = False) -> tuple:
        """(object_key, nonce, actual_size) for an encrypted object;
        verifies SSE-C key headers match."""
        import base64 as _b64

        object_key = self._sse_object_key(request, bucket, key, meta,
                                          copy_source=copy_source)
        nonce = (_b64.b64decode(meta[sse.META_NONCE])
                 if sse.META_NONCE in meta else b"")
        actual = int(meta.get(sse.META_ACTUAL_SIZE, "0"))
        return object_key, nonce, actual

    def _get_reader(self, bucket, key, opts):
        """(info, open_range) from the layer — via its single-quorum-read
        get_object_reader when it has one, else the two-call fallback
        (gateways and other duck-typed layers)."""
        gr = getattr(self.obj, "get_object_reader", None)
        if gr is not None:
            return gr(bucket, key, opts)
        info = self.obj.get_object_info(bucket, key, opts)

        def open_range(offset=0, length=-1):
            return self.obj.get_object(bucket, key, offset, length, opts)[1]

        return info, open_range

    def _open_stream_sync(self, request, bucket, key, opts, offset, length,
                          copy_source=False, pre=None, open_range=None):
        """Blocking core of the object read path: get_object_reader (ONE
        quorum metadata read) + transparent SSE/compression unwrap. Runs in
        a single executor hop — the previous shape paid a quorum read for
        the info and a second for the data, plus an executor round trip for
        each. Returns (info, iterator, plaintext_size)."""
        if pre is None:
            pre, open_range = self._get_reader(bucket, key, opts)

        def open_plain(off, ln):
            if open_range is not None:
                return open_range(off, ln)
            # Caller passed a pre-fetched info without a reader: fall back
            # to the two-call path for the data bytes.
            return self.obj.get_object(bucket, key, off, ln, opts)[1]

        if czip.META_COMPRESSION in pre.user_defined:
            actual = int(pre.user_defined.get(czip.META_ACTUAL_SIZE, "-1"))
            if length < 0:
                length = (actual - offset) if actual >= 0 else -1
            stream = open_plain(0, -1)
            return (pre,
                    czip.decompress_iter(
                        stream, offset, length,
                        scheme=pre.user_defined[czip.META_COMPRESSION]),
                    actual if actual >= 0 else pre.size)
        if sse.META_ALGO not in pre.user_defined:
            if length < 0:
                length = pre.size - offset
            return pre, open_plain(offset, length), pre.size
        if sse.META_NONCE not in pre.user_defined and pre.parts:
            # Multipart SSE: no object-level nonce; parts are independent
            # [nonce | DARE] streams.
            return self._mp_sse_stream(request, bucket, key, opts, pre,
                                       offset, length, copy_source)
        object_key, nonce, actual = self._sse_unseal(
            request, bucket, key, pre.user_defined, copy_source=copy_source)
        if length < 0:
            length = actual - offset
        if offset < 0 or length < 0 or offset + length > actual:
            raise S3Error("InvalidRange", resource=f"/{bucket}/{key}")
        if length == 0:
            return pre, iter([]), actual
        enc_off, enc_len, skip = sse.decrypted_range(offset, length, actual)
        enc_stream = open_plain(enc_off, enc_len)
        dec = sse.DecryptReader(
            enc_stream, object_key, nonce,
            start_chunk=enc_off // sse.ENC_CHUNK,
            total_chunks=sse.total_chunks(actual))
        return pre, _trim_iter(dec, skip, length, enc_stream), actual

    async def _open_object_stream(self, request, bucket, key, opts,
                                  offset, length, run, copy_source=False,
                                  pre=None):
        """Async wrapper: one executor hop around _open_stream_sync. Pass
        `pre` when the caller already paid the quorum metadata read."""
        return await run(self._open_stream_sync, request, bucket, key,
                         opts, offset, length, copy_source, pre)

    def _apply_object_lock(self, request, bucket: str, opts) -> None:
        """Stamp retention/legal-hold from request headers, falling back to
        the bucket's default retention (putOpts from object lock config,
        cmd/bucket-object-lock.go getObjectRetentionMeta)."""
        import time as _time

        mode = request.headers.get("x-amz-object-lock-mode", "").upper()
        until = request.headers.get("x-amz-object-lock-retain-until-date", "")
        hold = request.headers.get("x-amz-object-lock-legal-hold", "").upper()
        if mode and until:
            opts.user_defined[olock.KEY_MODE] = mode
            opts.user_defined[olock.KEY_UNTIL] = until
        else:
            default = olock.parse_default_retention(
                self.bucket_meta.get(bucket).object_lock_xml)
            if default is not None:
                dmode, seconds = default
                opts.user_defined[olock.KEY_MODE] = dmode
                opts.user_defined[olock.KEY_UNTIL] = olock.to_iso(
                    _time.time() + seconds)
        if hold:
            opts.user_defined[olock.KEY_HOLD] = hold

    # ------------------------------------------------------------------
    # eventing glue (reference sendEvent calls at the end of each handler)
    # ------------------------------------------------------------------

    def _ensure_rules(self, bucket: str) -> None:
        if bucket in self._rules_loaded:
            return
        self._rules_loaded.add(bucket)
        xml_cfg = self.bucket_meta.get(bucket).notification_xml
        if xml_cfg:
            try:
                self.notifier.set_bucket_rules(bucket, xml_cfg)
            except ValueError:
                pass  # stored config references a target gone from config

    def _emit(self, request, event_name: str, bucket: str, key: str,
              size: int = 0, etag: str = "", version_id: str = "") -> None:
        self._ensure_rules(bucket)
        if not self.notifier.has_rules(bucket):
            return
        ident = request.get("identity")
        self.notifier.send(new_object_event(
            event_name, bucket, key, size=size, etag=etag,
            version_id=version_id,
            user=getattr(ident, "access_key", "") or "anonymous",
            host=request.remote or "", region=self.region))

    # ------------------------------------------------------------------

    async def _spool_body(self, request, payload_hash, auth_sig,
                          bucket: str = ""):
        """Stream the request body into a spooled temp file, verifying the
        content sha256 or per-chunk streaming signatures. Returns
        (spool, size); caller closes the spool. `bucket` engages the
        per-bucket ingest bandwidth limiter."""
        if request.content_length is None and \
                "x-amz-decoded-content-length" not in request.headers:
            raise S3Error("MissingContentLength")
        size = request.content_length or 0
        decoded_len = request.headers.get("x-amz-decoded-content-length")
        streaming = payload_hash == sigv4.STREAMING_PAYLOAD
        if streaming:
            if auth_sig is None:
                # Chunk signatures chain off the header-auth seed signature;
                # a presigned URL has none, so streaming is undefined there.
                raise S3Error("InvalidArgument",
                              "streaming payload requires header authorization")
            if decoded_len is None:
                raise S3Error("MissingContentLength")
            try:
                size = int(decoded_len)
            except ValueError:
                raise S3Error("InvalidArgument",
                              "malformed x-amz-decoded-content-length") from None
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")

        spool = tempfile.SpooledTemporaryFile(max_size=SPOOL_LIMIT)
        sha = hashlib.sha256() if payload_hash not in (
            sigv4.UNSIGNED_PAYLOAD, sigv4.STREAMING_PAYLOAD) else None
        chunked = None
        if streaming:
            amz_date = request.headers.get("x-amz-date", "")
            # The chunk signing key derives from the *requester's* secret
            # (reference calculateSeedSignature, streaming-signature-v4.go:77),
            # not the root credential — otherwise every aws-chunked PUT by a
            # non-root IAM/STS user fails with SignatureDoesNotMatch.
            req_creds = self._lookup(auth_sig.access_key) or self.creds
            chunked = sigv4.ChunkedSigV4Reader(
                req_creds, auth_sig.signature, amz_date, auth_sig.scope_date,
                auth_sig.region, auth_sig.service)
        try:
            async for chunk in request.content.iter_chunked(1 << 20):
                delay = self.bw_throttle.delay(bucket, len(chunk), "rx")
                if delay > 0:
                    await asyncio.sleep(delay)
                if chunked is not None:
                    # Verified chunk views stream straight to the spool
                    # (valid until the next feed — written before it).
                    for piece in chunked.feed(chunk):
                        spool.write(piece)
                else:
                    if sha is not None:
                        sha.update(chunk)
                    spool.write(chunk)
            if chunked is not None and not chunked.done:
                raise S3Error("IncompleteBody")
            if sha is not None and sha.hexdigest() != payload_hash:
                raise S3Error("XAmzContentSHA256Mismatch")
        except BaseException:
            spool.close()
            raise
        spool.seek(0)
        return spool, size

    async def _put_object(self, request, bucket, key, opts, hdr,
                          payload_hash, auth_sig, run):
        opts.user_defined = _metadata_headers(request)
        if "content-type" not in opts.user_defined:
            # Extension-based inference (the pkg/mimedb role).
            import mimetypes

            guessed, _ = mimetypes.guess_type(key)
            opts.user_defined["content-type"] = (
                guessed or "application/octet-stream")
        self._apply_object_lock(request, bucket, opts)
        repl_cfg = self.replication.config_for(bucket)
        if repl_cfg is not None and repl_cfg.rule_for(key) is not None:
            from minio_tpu.replication.rules import META_STATUS, STATUS_PENDING
            opts.user_defined[META_STATUS] = STATUS_PENDING
        spool, size = await self._spool_body(request, payload_hash,
                                             auth_sig, bucket)
        reader, size2 = self._maybe_compress_put(
            request, bucket, key, opts, spool, size)
        reader, stored_size = self._maybe_encrypt_put(
            request, bucket, key, opts, reader, size2)
        try:
            # PUT always hops to the executor — even an inline-sized write
            # takes the namespace WRITE lock (30s timeout under contention)
            # and fsyncs; either on the event loop would stall every
            # connection on the server. (GET's on-loop fast path is safe
            # because reads are lockless and cache-backed.)
            info = await run(self.obj.put_object, bucket, key, reader,
                             stored_size, opts)
        finally:
            spool.close()
        extra = {"ETag": f'"{info.etag}"'}
        if info.version_id:
            extra["x-amz-version-id"] = info.version_id
        self.update_tracker.mark(bucket)
        self._emit(request, evt.OBJECT_CREATED_PUT, bucket, key,
                   size=info.size, etag=info.etag, version_id=info.version_id)
        if repl_cfg is not None:
            from minio_tpu.replication.pool import OP_PUT, ReplicationTask
            self.replication.queue_task(ReplicationTask(
                bucket, key, info.version_id, op=OP_PUT))
        return web.Response(status=200, headers={**hdr, **extra})

    async def _put_part(self, request, bucket, key, upload_id, part_number,
                        hdr, payload_hash, auth_sig, run):
        spool, size = await self._spool_body(request, payload_hash,
                                             auth_sig, bucket)
        try:
            reader, stored_size = await run(
                self._maybe_encrypt_part, request, bucket, key, upload_id,
                spool, size)
            res = await run(self.obj.put_object_part, bucket, key, upload_id,
                            part_number, reader, stored_size)
        finally:
            spool.close()
        return web.Response(status=200, headers={**hdr, "ETag": f'"{res.etag}"'})

    async def _upload_part_copy(self, request, bucket, key, upload_id,
                                part_number, src, hdr, run):
        src_bucket, src_key, src_opts = _parse_copy_source(src)
        # Read the *client-visible* bytes — decrypt/decompress the source
        # (the reference decrypts the source in CopyObjectPartHandler;
        # reading raw shards here would store ciphertext as a plain part).
        rng = request.headers.get("x-amz-copy-source-range")
        if rng:
            pre = await run(self.obj.get_object_info, src_bucket, src_key,
                            src_opts)
            offset, length = _parse_range(rng, self._visible_size(pre))
        else:
            pre, offset, length = None, 0, -1
        info, stream, visible_size = await self._open_object_stream(
            request, src_bucket, src_key, src_opts, offset, length, run,
            copy_source=True, pre=pre)
        if length < 0:
            length = visible_size - offset
        try:
            reader, stored_size = await run(
                self._maybe_encrypt_part, request, bucket, key, upload_id,
                _IterReader(stream), length)
            res = await run(self.obj.put_object_part, bucket, key, upload_id,
                            part_number, reader, stored_size)
        finally:
            close = getattr(stream, "close", None)
            if close is not None:
                await run(close)
        return web.Response(
            body=xmlutil.copy_object_xml(res.etag, res.last_modified),
            content_type=XML_TYPE, headers=hdr)

    async def _copy_object(self, request, bucket, key, src, opts, hdr, run):
        src_bucket, src_key, src_opts = _parse_copy_source(src)
        info, stream, src_visible = await self._open_object_stream(
            request, src_bucket, src_key, src_opts, 0, -1, run,
            copy_source=True)
        directive = request.headers.get("x-amz-metadata-directive", "COPY")
        user_defined = dict(info.user_defined)
        user_defined["content-type"] = info.content_type
        if directive == "REPLACE":
            user_defined = sanitize_user_meta({
                hk.lower(): hv for hk, hv in request.headers.items()
                if hk.lower().startswith("x-amz-meta-")
            })
            if request.headers.get("Content-Type"):
                user_defined["content-type"] = request.headers["Content-Type"]
        # Strip source encryption bookkeeping; destination re-encrypts per
        # its own headers/bucket config.
        for k in (sse.META_ALGO, sse.META_SEALED_KEY, sse.META_NONCE,
                  sse.META_KEY_MD5, sse.META_ACTUAL_SIZE,
                  sse.META_KMS_KEY_ID):
            user_defined.pop(k, None)
        opts.user_defined = user_defined

        reader, stored_size = self._maybe_encrypt_put(
            request, bucket, key, opts, _IterReader(stream), src_visible)
        try:
            new_info = await run(self.obj.put_object, bucket, key, reader,
                                 stored_size, opts)
        finally:
            # put_object reads exactly info.size bytes, leaving the source
            # generator paused before its cleanup — drive close() so shard
            # readers release and heal triggers fire.
            close = getattr(stream, "close", None)
            if close is not None:
                await run(close)
        return web.Response(body=xmlutil.copy_object_xml(new_info.etag,
                                                         new_info.mod_time),
                            content_type=XML_TYPE, headers=hdr)

    # Objects at or below this client-visible size are drained inside the
    # same executor hop that opened them and returned as one body — the
    # per-chunk executor round trips dominate small-object GET latency.
    _GET_DRAIN_LIMIT = 256 << 10

    async def _get_object(self, request, bucket, key, opts, hdr, run):
        rng = request.headers.get("Range")

        def open_sync(drain_all):
            """Quorum read + range math + stream open in one call; for
            small responses, the full drain too. `drain_all=False` (the
            on-loop fast path) only drains zero-IO inline streams."""
            status = 200
            if rng:
                # Range needs the size before the read — with the single
                # reader the info and the data still cost ONE quorum round.
                pre, open_range = self._get_reader(bucket, key, opts)
                offset, length = _parse_range(rng, self._visible_size(pre))
                status = 206
                info, stream, visible = self._open_stream_sync(
                    request, bucket, key, opts, offset, length,
                    pre=pre, open_range=open_range)
            else:
                offset, length = 0, -1
                info, stream, visible = self._open_stream_sync(
                    request, bucket, key, opts, 0, -1)
            if length < 0:
                length = visible
            body = None
            if length <= self._GET_DRAIN_LIMIT \
                    and (drain_all or type(stream) is _LIST_ITER) \
                    and not _check_conditional(request, info):
                # Drain to a chunk LIST, not one joined buffer: the
                # chunks flow to the socket as-is (zero coalesce pass).
                body = list(stream)
            return status, offset, length, info, stream, visible, body

        if getattr(self.obj, "fast_local_reads", False):
            # All-local fast media: the open is ~100us of cached metadata
            # work — cheaper than an executor round trip, so run it on the
            # loop (inline streams drain here too; anything with real IO
            # still hops below).
            status, offset, length, info, stream, visible, body = \
                open_sync(False)
            if body is None and length <= self._GET_DRAIN_LIMIT \
                    and not _check_conditional(request, info):
                body = await run(lambda: list(stream))
        else:
            status, offset, length, info, stream, visible, body = \
                await run(open_sync, True)
        if _check_conditional(request, info):
            return web.Response(status=304, headers={
                **hdr, "ETag": f'"{info.etag}"',
            })
        headers = {**hdr, **_object_headers(info)}
        headers["Content-Length"] = str(length)
        if status == 206:
            headers["Content-Range"] = f"bytes {offset}-{offset + length - 1}/{visible}"
        if body is not None:
            delay = self.bw_throttle.delay(bucket, length)
            if delay > 0:
                await asyncio.sleep(delay)
            if len(body) == 1:
                return web.Response(status=status, body=body[0],
                                    headers=headers)
            # Multi-chunk drained body: write each chunk through the
            # stream writer (Content-Length is already set above) —
            # payload bytes go socket-ward without ever being joined.
            resp = web.StreamResponse(status=status, headers=headers)
            await resp.prepare(request)
            for c in body:
                await resp.write(c)
            await resp.write_eof()
            return resp
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        # First response bytes (the headers) just flushed: this is the
        # stream's TTFB, picked up by _entry's finally.
        t0_req = request.get("mtpu-t0")
        if t0_req is not None:
            request["mtpu-ttfb"] = time.perf_counter() - t0_req
        loop = asyncio.get_running_loop()
        it = iter(stream)
        # One context copy for the whole drain (the awaits are
        # sequential, so the copy is never entered concurrently): shard
        # reads run inside next() on the executor and their storage/RPC
        # records must keep this request's trace id.
        drain_next = obs.ctx_wrap(lambda: next(it, None))
        while True:
            chunk = await loop.run_in_executor(None, drain_next)
            if chunk is None:
                break
            delay = self.bw_throttle.delay(bucket, len(chunk))
            if delay > 0:
                await asyncio.sleep(delay)
            await resp.write(chunk)
        await resp.write_eof()
        return resp

    async def _delete_objects(self, request, bucket, hdr, run):
        body = await request.read()
        objects, quiet = xmlutil.parse_delete_xml(body)
        identity = request.get("identity")

        base_ctx = request.get("cond-ctx") or self._condition_context(
            request, identity)

        def authorize():
            ok, den = [], []
            for k, v in objects:
                action = ("s3:DeleteObjectVersion" if v
                          else "s3:DeleteObject")
                ctx = base_ctx
                if v:  # per-key version scope (s3:versionid conditions)
                    # NormalizedContext copy keeps the already-normalized
                    # marker — a plain {**base_ctx} would make every
                    # PolicyArgs re-normalize the full context per key.
                    from minio_tpu.iam.condition import NormalizedContext
                    ctx = NormalizedContext(base_ctx)
                    ctx["s3:versionid"] = [v]
                try:
                    self._check_access(identity, action, bucket, k, ctx)
                    ok.append((k, v))
                except S3Error:
                    den.append((k, "AccessDenied", "Access Denied."))
            return ok, den

        # Off the event loop: N policy evaluations for N keys.
        authorized, denied = await run(authorize)
        objects = authorized
        todo = [ObjectToDelete(k, v) for k, v in objects]
        results = await run(self.obj.delete_objects, bucket, todo,
                            ObjectOptions(versioned=self.versioned_buckets))
        deleted, errors = [], list(denied)
        for (k, v), r in zip(objects, results):
            if isinstance(r, Exception):
                s3e = from_exception(r, k)
                if s3e.api.code == "NoSuchKey":
                    # S3 semantics: deleting a missing key succeeds.
                    if not quiet:
                        from minio_tpu.erasure.types import DeletedObject
                        deleted.append(DeletedObject(object_name=k, version_id=v))
                else:
                    errors.append((k, s3e.api.code, s3e.message))
            elif not quiet:
                deleted.append(r)
        return web.Response(body=xmlutil.delete_result_xml(deleted, errors),
                            content_type=XML_TYPE, headers=hdr)


class _CloseProxy:
    """Iterator wrapper whose close() also closes the underlying source
    stream (generators can't carry extra attributes)."""

    def __init__(self, it, source):
        self._it = iter(it)
        self._source = source

    def __iter__(self):
        return self._it

    def close(self) -> None:
        close = getattr(self._source, "close", None)
        if close is not None:
            close()


def _peel_prefix(stream, n: int):
    """Take the first n bytes off a bytes-iterator; returns (rest_iter,
    prefix memoryview). rest_iter preserves the remaining bytes and
    close(); nothing is re-joined — the accumulated head is sliced as
    memoryviews (the backing bytearray is never resized after export)."""
    it = iter(stream)
    acc = bytearray()
    while len(acc) < n:
        try:
            acc += next(it)
        except StopIteration:
            # PEP 479: letting this escape into a consuming generator
            # becomes RuntimeError mid-response; surface a clean error.
            raise sse.SSEError(
                f"stream truncated: {len(acc)} of {n} prefix bytes"
            ) from None
    mv = memoryview(acc)
    prefix, rest = mv[:n], mv[n:]

    def gen():
        if len(rest):
            yield rest
        yield from it

    return gen(), prefix


def _trim_iter(it, skip: int, length: int, source=None):
    """Yield `length` bytes from `it` after dropping the first `skip`
    (chunk-aligned decrypt streams overshoot a byte range on both ends);
    closes `source` when done."""
    remaining = length
    drop = skip
    for chunk in it:
        cv = memoryview(chunk)
        if drop:
            if len(cv) <= drop:
                drop -= len(cv)
                continue
            cv = cv[drop:]
            drop = 0
        if len(cv) >= remaining:
            yield cv[:remaining]
            remaining = 0
            break
        remaining -= len(cv)
        yield cv
    close = getattr(source, "close", None)
    if close is not None:
        close()


class _PrefixReader:
    """File-like that serves a fixed prefix, then an inner reader — carries
    a part's random nonce at the head of its encrypted stream."""

    def __init__(self, prefix: bytes, inner):
        self._prefix = prefix
        self._inner = inner

    def read(self, n: int = -1) -> bytes:
        if self._prefix:
            if n < 0 or n >= len(self._prefix):
                out, self._prefix = self._prefix, b""
                rest = self._inner.read(n - len(out) if n >= 0 else -1)
                return out + rest
            out, self._prefix = self._prefix[:n], self._prefix[n:]
            return out
        return self._inner.read(n)

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


# File-like over a bytes iterator — canonical home: utils/streams.py.
from minio_tpu.utils.streams import IterReader as _IterReader  # noqa: E402

_BROWSER_HTML: bytes | None = None


def _browser_page() -> bytes:
    """browser.html, read once (immutable bytes; no per-request disk IO)."""
    global _BROWSER_HTML
    if _BROWSER_HTML is None:
        import importlib.resources as _res

        _BROWSER_HTML = (_res.files("minio_tpu.s3")
                         / "browser.html").read_bytes()
    return _BROWSER_HTML


def _validate_xml(body: bytes) -> None:
    import xml.etree.ElementTree as _ET

    try:
        _ET.fromstring(body)
    except _ET.ParseError:
        raise S3Error("MalformedXML") from None


def _metadata_headers(request) -> dict:
    """User-controlled object metadata extracted from request headers."""
    user_defined = {}
    ct = request.headers.get("Content-Type")
    if ct:
        user_defined["content-type"] = ct
    sc = request.headers.get("x-amz-storage-class")
    if sc:
        user_defined["x-amz-storage-class"] = sc
    tags = request.headers.get("x-amz-tagging")
    if tags:
        user_defined["x-amz-tagging"] = tags
    repl = request.headers.get("x-amz-replication-status")
    if repl:
        user_defined["x-amz-replication-status"] = repl
    for hk, hv in request.headers.items():
        lk = hk.lower()
        if lk.startswith("x-amz-meta-") and not _is_reserved_meta(lk):
            user_defined[lk] = hv
    return user_defined


def _is_reserved_meta(key: str) -> bool:
    """Reserved-metadata filter (reference filterReservedMetadata,
    cmd/generic-handlers.go): internal bookkeeping namespaces must never be
    client-settable — a crafted header could otherwise forge SSE/transition
    state, including via the gateway's packed meta key (whose payload
    unpack_internal_meta would inject as x-mtpu-internal-*)."""
    lk = key.lower()
    suffix = lk[len("x-amz-meta-"):] if lk.startswith("x-amz-meta-") else lk
    return suffix.startswith(("mtpu", "x-mtpu")) or "mtpu-internal" in suffix


def sanitize_user_meta(meta: dict) -> dict:
    """Drop reserved-namespace keys from client-supplied metadata — the
    single sanitizer every metadata ingestion path (PUT headers, CopyObject
    REPLACE, POST-policy forms) runs through."""
    return {k: v for k, v in meta.items() if not _is_reserved_meta(k)}


def _parse_copy_source(src: str):
    """x-amz-copy-source → (bucket, key, ObjectOptions with versionId)."""
    src = urllib.parse.unquote(src)
    src_vid = ""
    if "?versionId=" in src:
        src, src_vid = src.split("?versionId=", 1)
    src = src.lstrip("/")
    if "/" not in src:
        raise S3Error("InvalidArgument", "bad x-amz-copy-source")
    src_bucket, src_key = src.split("/", 1)
    return src_bucket, src_key, ObjectOptions(version_id=src_vid)


def _object_headers(info) -> dict:
    size = S3Server._visible_size(info)
    h = {
        "ETag": f'"{info.etag}"',
        "Last-Modified": _http_time(info.mod_time),
        "Content-Type": info.content_type or "binary/octet-stream",
        "Accept-Ranges": "bytes",
        "Content-Length": str(size),
    }
    h.update(sse.sse_headers_for(info.user_defined))
    if info.version_id:
        h["x-amz-version-id"] = info.version_id
    for k, v in info.user_defined.items():
        if k.startswith("x-amz-meta-"):
            h[k] = v
    repl = info.user_defined.get("x-amz-replication-status")
    if repl:
        h["x-amz-replication-status"] = repl
    tags = info.user_defined.get("x-amz-tagging")
    if tags:
        h["x-amz-tagging-count"] = str(len(urllib.parse.parse_qsl(tags)))
    return h


def _http_time(ts: float) -> str:
    import email.utils

    return email.utils.formatdate(ts, usegmt=True)


def _parse_range(value: str, size: int) -> tuple[int, int]:
    if not value.startswith("bytes="):
        raise S3Error("InvalidRange")
    spec = value[6:].split(",")[0].strip()
    try:
        if spec.startswith("-"):
            suffix = int(spec[1:])
            if suffix == 0:
                raise S3Error("InvalidRange")
            start = max(0, size - suffix)
            end = size - 1
        else:
            se_ = spec.split("-")
            start = int(se_[0])
            end = int(se_[1]) if len(se_) > 1 and se_[1] else size - 1
    except ValueError:
        raise S3Error("InvalidRange") from None
    if start >= size or end < start:
        raise S3Error("InvalidRange")
    end = min(end, size - 1)
    return start, end - start + 1


def _check_conditional(request, info) -> bool:
    """Returns True for a 304 Not Modified outcome; raises for 412."""
    im = request.headers.get("If-Match")
    if im and im != "*" and im.strip('"') != info.etag:
        raise S3Error("PreconditionFailed", "ETag does not match If-Match")
    inm = request.headers.get("If-None-Match")
    if inm and (inm == "*" or inm.strip('"') == info.etag):
        if request.method in ("GET", "HEAD"):
            return True  # cache revalidation hit
        raise S3Error("PreconditionFailed", "ETag matches If-None-Match")
    return False


# ----------------------------------------------------------------------


def build_server(drive_paths: list[str], access_key: str, secret_key: str,
                 versioned: bool = False, parity: int | None = None,
                 set_drive_count: int | None = None,
                 enable_mrf: bool = True,
                 server_addr: str = "", certs_dir: str = "") -> S3Server:
    """Assemble the full backend stack: drives → sets (sipHash routing) →
    pools (capacity placement) → S3 front door (reference newObjectLayer,
    cmd/server-main.go:557). URL endpoints (http://host/disk) boot the
    distributed path: RPC fabric + bootstrap handshake + dsync locks
    (reference serverMain distributed branch, cmd/server-main.go:484-500)."""
    from minio_tpu.erasure.pools import ErasureServerPools
    from minio_tpu.erasure.sets import ErasureSets

    # Single plain path -> FS backend (reference newObjectLayer: one
    # endpoint means NewFSObjectLayer, cmd/server-main.go:557).
    if len(drive_paths) == 1 and "://" not in drive_paths[0]:
        from minio_tpu.fs import FSObjects

        layer = FSObjects(drive_paths[0])
        return S3Server(layer, sigv4.Credentials(access_key, secret_key),
                        versioned_buckets=versioned)

    if any("://" in p for p in drive_paths):
        from minio_tpu.dist.cluster import ClusterNode
        from minio_tpu.logger import get_logger as _get_logger

        host, _, port = server_addr.rpartition(":")
        node = ClusterNode([drive_paths], host=host or "127.0.0.1",
                           port=int(port or 9000), secret=secret_key,
                           set_drive_count=set_drive_count or 0,
                           parity=parity, certs_dir=certs_dir)
        # The reference retries cluster bootstrap until the fleet
        # converges (verifyServerSystemConfig / waitForFormatErs loop)
        # rather than dying when peers boot slowly or out of order; a
        # node that crashed here would just be restarted by the
        # supervisor anyway. Same for the first config/IAM quorum reads:
        # peers may be seconds away from serving their drives.
        boot_deadline = time.monotonic() + float(
            os.environ.get("MTPU_BOOT_TIMEOUT", "600"))
        while True:
            layer = None
            try:
                node.wait_for_peers()
                layer = node.build_object_layer(enable_mrf=enable_mrf)
                srv = S3Server(layer,
                               sigv4.Credentials(access_key, secret_key),
                               versioned_buckets=versioned,
                               notification_sys=node.notification)
                break
            except (se.OperationTimedOut, se.InsufficientReadQuorum,
                    se.InsufficientWriteQuorum) as e:
                if layer is not None:
                    try:
                        layer.close()
                    except Exception:  # noqa: BLE001 — teardown only
                        pass
                if time.monotonic() > boot_deadline:
                    raise
                _get_logger().warning(
                    f"boot: waiting for cluster quorum ({e}); retrying")
                time.sleep(2.0)
        srv.attach_cluster(node)
        return srv

    # Drives sharing one physical device lose failure independence
    # (pkg/mountinfo CheckCrossDevice role) — warn loudly, keep serving.
    from minio_tpu.logger import get_logger
    from minio_tpu.utils.mounts import check_cross_device

    for w in check_cross_device(drive_paths):
        get_logger().warning(w)

    drives = [LocalDrive(p) for p in drive_paths]
    # Calibration profile on drive 0 (docs/SLO.md): write-or-compare
    # the host fingerprint + tuned gates; a mismatch raises
    # minio_tpu_calibration_stale instead of silently serving gates
    # tuned for other hardware.
    from minio_tpu.obs import calibration as _calibration

    _calibration.boot(drive_paths[0])
    sets = ErasureSets(drives, set_drive_count=set_drive_count, parity=parity,
                       enable_mrf=enable_mrf)
    layer = ErasureServerPools([sets])
    return S3Server(layer, sigv4.Credentials(access_key, secret_key),
                    versioned_buckets=versioned)


def build_gateway_server(kind: str, target: str, access_key: str,
                         secret_key: str,
                         remote_access: str = "", remote_secret: str = ""
                         ) -> S3Server:
    """Gateway modes (reference StartGateway, cmd/gateway-main.go:155):
    nas <path> | s3 <endpoint> | gcs [<endpoint>] | azure <endpoint>
    | hdfs <namenode endpoint>. Remote credentials come from
    MTPU_GATEWAY_ACCESS_KEY/SECRET_KEY (azure: account/base64 key;
    hdfs: access=user)."""
    from minio_tpu.gateway import (
        AzureGateway,
        HDFSGateway,
        S3Gateway,
        gcs_gateway,
        nas_gateway,
    )

    if kind == "nas":
        layer = nas_gateway(target)
    elif kind == "s3":
        layer = S3Gateway(target, remote_access or access_key,
                          remote_secret or secret_key)
    elif kind == "gcs":
        layer = gcs_gateway(remote_access or access_key,
                            remote_secret or secret_key,
                            endpoint=target or
                            "https://storage.googleapis.com")
    elif kind == "azure":
        layer = AzureGateway(target, remote_access or access_key,
                             remote_secret or secret_key)
    elif kind == "hdfs":
        layer = HDFSGateway(target, user=remote_access or "minio")
    else:
        raise ValueError(f"unknown gateway {kind!r} (nas|s3|gcs|azure|hdfs)")
    return S3Server(layer, sigv4.Credentials(access_key, secret_key))


def main(argv=None):
    ap = argparse.ArgumentParser(description="minio_tpu S3 server")
    ap.add_argument("drives", nargs="+", help="drive directories")
    ap.add_argument("--gateway", default="",
                    help="gateway mode: nas|s3 (drives arg becomes the "
                         "path/endpoint)")
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--versioned", action="store_true")
    ap.add_argument("--parity", type=int, default=None)
    ap.add_argument("--set-drives", type=int, default=None,
                    help="drives per erasure set (default: all drives, one set)")
    ap.add_argument("--scan-interval", type=float, default=60.0,
                    help="background scanner cycle pause (seconds; 0 disables)")
    ap.add_argument("--cache-dir", default="",
                    help="local SSD cache directory (enables the disk cache)")
    ap.add_argument("--cache-quota", type=int, default=1 << 30,
                    help="disk cache quota in bytes")
    ap.add_argument("--certs-dir", default=os.environ.get("MTPU_CERTS_DIR", ""),
                    help="TLS certs dir (public.crt + private.key, "
                         "hot-reloaded); empty serves plaintext HTTP")
    args = ap.parse_args(argv)
    import sys as _sys

    # Pin the JAX backend before first device use (the env var alone can
    # be overridden by site hooks that force-register accelerator
    # plugins). Cluster harness tests run many server processes on CPU;
    # an accelerator is single-tenant and must not be grabbed by each.
    plat = os.environ.get("MTPU_JAX_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    # Raise the fd soft limit to the hard limit (reference pkg/sys
    # setMaxResources) — a drive fleet + RPC fan-out outgrows the default
    # 1024 fast.
    from minio_tpu.utils import sysres

    sysres.maximize_nofile()

    # The exact re-exec line `admin service restart` uses (module entry —
    # script-mode exec would lose the package root from sys.path).
    restart_cmd = [_sys.executable, "-m", "minio_tpu.s3.server"] + (
        list(argv) if argv is not None else _sys.argv[1:])
    host, _, port = args.address.rpartition(":")
    access = os.environ.get("MTPU_ROOT_USER", "minioadmin")
    secret = os.environ.get("MTPU_ROOT_PASSWORD", "minioadmin")
    if args.gateway:
        srv = build_gateway_server(
            args.gateway, args.drives[0], access, secret,
            remote_access=os.environ.get("MTPU_GATEWAY_ACCESS_KEY", ""),
            remote_secret=os.environ.get("MTPU_GATEWAY_SECRET_KEY", ""))
        srv.restart_cmd = restart_cmd
        web.run_app(srv.app, host=(args.address.rpartition(":")[0]
                                   or "0.0.0.0"),
                    port=int(args.address.rpartition(":")[2]))
        return
    srv = build_server(args.drives, access, secret,
                       versioned=args.versioned, parity=args.parity,
                       set_drive_count=args.set_drives,
                       server_addr=args.address,
                       certs_dir=args.certs_dir or "")
    srv.restart_cmd = restart_cmd
    if args.cache_dir:
        from minio_tpu.cache import CacheObjects

        srv.obj = CacheObjects(
            srv.obj, args.cache_dir, quota_bytes=args.cache_quota,
            commit=os.environ.get("MTPU_CACHE_COMMIT", "writethrough"))
    if args.scan_interval > 0:
        srv.start_scanner(interval=args.scan_interval)
    srv.start_auto_heal()
    ssl_context = None
    if args.certs_dir:
        from minio_tpu.utils.certs import CertManager

        ssl_context = CertManager(args.certs_dir).ssl_context
    web.run_app(srv.app, host=host or "0.0.0.0", port=int(port),
                ssl_context=ssl_context)


if __name__ == "__main__":
    main()
