"""AWS Signature Version 4 verification.

Reference: cmd/signature-v4.go (doesSignatureMatch :332, presigned :206),
cmd/streaming-signature-v4.go (aws-chunked payload), cmd/auth-handler.go:102
(request classification). Implemented from the public SigV4 specification —
canonical request -> string-to-sign -> HMAC chain — not translated from the
reference.

Supported: header auth (signed or UNSIGNED-PAYLOAD), presigned URLs,
streaming aws-chunked bodies (per-chunk signature chain). SigV2 is legacy
and intentionally omitted.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import re
import time
import urllib.parse
from dataclasses import dataclass

from minio_tpu.s3.errors import S3Error

ALGORITHM = "AWS4-HMAC-SHA256"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
# sha256("") — the reference's default payload hash for HEADER-signed
# requests that omit x-amz-content-sha256 (getContentSha256Cksum,
# cmd/signature-v4-utils.go:62; presigned requests default to
# UNSIGNED-PAYLOAD instead). Generic SigV4 clients (curl --aws-sigv4)
# sign bodyless requests with exactly this value and send no header.
EMPTY_SHA256 = ("e3b0c44298fc1c149afbf4c8996fb924"
                "27ae41e4649b934ca495991b7852b855")
MAX_SKEW_SECONDS = 15 * 60


@dataclass
class Credentials:
    access_key: str
    secret_key: str


@dataclass
class ParsedAuth:
    access_key: str
    scope_date: str      # yyyymmdd
    region: str
    service: str
    signed_headers: list[str]
    signature: str


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


_KEY_CACHE: dict[tuple[str, str, str, str], bytes] = {}


def signing_key(secret: str, scope_date: str, region: str, service: str) -> bytes:
    """Derived signing key (4 chained HMACs), served from a cache that only
    ever holds VERIFIED scopes: lookups are free for all callers, but
    entries are inserted by _remember_signing_key after a signature over
    the derived key actually matches. An unauthenticated requester can
    therefore recompute but never insert — fabricated region/service
    scopes can't thrash the cache."""
    k = _KEY_CACHE.get((secret, scope_date, region, service))
    if k is None:
        k = _hmac(("AWS4" + secret).encode(), scope_date)
        k = _hmac(k, region)
        k = _hmac(k, service)
        k = _hmac(k, "aws4_request")
    return k


def _remember_signing_key(secret: str, scope_date: str, region: str,
                          service: str, key: bytes) -> None:
    """Cache a derived key AFTER its signature verified. Bound is one
    entry per live (credential, day, region) combination in practice;
    4096 is a generous ceiling for multi-tenant IAM."""
    if len(_KEY_CACHE) >= 4096:
        _KEY_CACHE.clear()
    _KEY_CACHE[(secret, scope_date, region, service)] = key


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query_items: list[tuple[str, str]],
                    drop_signature: bool = False) -> str:
    items = []
    for k, v in query_items:
        if drop_signature and k == "X-Amz-Signature":
            continue
        items.append((uri_encode(k), uri_encode(v)))
    items.sort()
    return "&".join(f"{k}={v}" for k, v in items)


def parse_auth_header(value: str) -> ParsedAuth:
    if not value.startswith(ALGORITHM + " "):
        raise S3Error("AuthorizationHeaderMalformed")
    parts: dict[str, str] = {}
    for item in value[len(ALGORITHM):].split(","):
        item = item.strip()
        if "=" not in item:
            raise S3Error("AuthorizationHeaderMalformed")
        k, v = item.split("=", 1)
        parts[k] = v
    try:
        cred = parts["Credential"].split("/")
        access_key = "/".join(cred[:-4])
        scope_date, region, service, terminal = cred[-4:]
        if terminal != "aws4_request":
            raise S3Error("AuthorizationHeaderMalformed")
        return ParsedAuth(
            access_key=access_key,
            scope_date=scope_date,
            region=region,
            service=service,
            signed_headers=parts["SignedHeaders"].lower().split(";"),
            signature=parts["Signature"],
        )
    except (KeyError, ValueError):
        raise S3Error("AuthorizationHeaderMalformed") from None


def _canonical_request(method: str, path: str, query: str, headers,
                       signed_headers: list[str], payload_hash: str) -> str:
    canon_headers = []
    for h in signed_headers:
        v = headers.get(h, "")
        canon_headers.append(f"{h}:{' '.join(v.split())}\n")
    return "\n".join([
        method,
        uri_encode(path, encode_slash=False),
        query,
        "".join(canon_headers),
        ";".join(signed_headers),
        payload_hash,
    ])


def _string_to_sign(amz_date: str, scope: str, canonical: str) -> str:
    return "\n".join([
        ALGORITHM,
        amz_date,
        scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])


_AMZ_DATE_RE = re.compile(r"\A\d{8}T\d{6}Z\Z", re.ASCII)


def _check_skew(amz_date: str) -> None:
    # Manual parse of the fixed "YYYYMMDDTHHMMSSZ" layout: strptime costs
    # ~50us per call (format-string recompile + locale machinery), which
    # was the single biggest line of request authentication. The ASCII
    # regex + explicit range checks keep strptime's strictness (int()
    # alone would admit unicode digits; timegm alone would silently
    # normalize Feb 30 or minute 99 into a nearby valid time).
    if _AMZ_DATE_RE.match(amz_date) is None:
        raise S3Error("AccessDenied", "invalid x-amz-date")
    try:
        t = datetime.datetime(
            int(amz_date[0:4]), int(amz_date[4:6]), int(amz_date[6:8]),
            int(amz_date[9:11]), int(amz_date[11:13]), int(amz_date[13:15]),
            tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        raise S3Error("AccessDenied", "invalid x-amz-date") from None
    if abs(time.time() - t) > MAX_SKEW_SECONDS:
        raise S3Error("RequestTimeTooSkewed")


def verify_header_auth(
    method: str,
    path: str,
    query_items: list[tuple[str, str]],
    headers,
    creds_lookup,
) -> tuple[Credentials, str]:
    """Verify an Authorization-header signed request.

    Returns (credentials, payload_hash_declared). Raises S3Error on any
    mismatch. `headers` needs case-insensitive .get (aiohttp provides it).
    """
    auth = parse_auth_header(headers.get("Authorization", ""))
    creds = creds_lookup(auth.access_key)
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    amz_date = headers.get("x-amz-date") or headers.get("Date", "")
    _check_skew(amz_date)
    if not amz_date.startswith(auth.scope_date):
        raise S3Error("SignatureDoesNotMatch")
    payload_hash = headers.get("x-amz-content-sha256", EMPTY_SHA256)
    scope = f"{auth.scope_date}/{auth.region}/{auth.service}/aws4_request"
    canonical = _canonical_request(
        method, path, canonical_query(query_items), headers,
        auth.signed_headers, payload_hash,
    )
    sts = _string_to_sign(amz_date, scope, canonical)
    key = signing_key(creds.secret_key, auth.scope_date, auth.region, auth.service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, auth.signature):
        raise S3Error("SignatureDoesNotMatch")
    _remember_signing_key(creds.secret_key, auth.scope_date, auth.region,
                          auth.service, key)
    return creds, payload_hash


def verify_presigned(
    method: str,
    path: str,
    query_items: list[tuple[str, str]],
    headers,
    creds_lookup,
) -> Credentials:
    """Verify a presigned-URL request (X-Amz-* query auth)."""
    q = dict(query_items)
    if q.get("X-Amz-Algorithm") != ALGORITHM:
        raise S3Error("AuthorizationHeaderMalformed")
    try:
        cred = q["X-Amz-Credential"].split("/")
        access_key = "/".join(cred[:-4])
        scope_date, region, service, _ = cred[-4:]
        amz_date = q["X-Amz-Date"]
        expires = int(q.get("X-Amz-Expires", "604800"))
        signed_headers = q["X-Amz-SignedHeaders"].lower().split(";")
        signature = q["X-Amz-Signature"]
    except (KeyError, ValueError):
        raise S3Error("AuthorizationHeaderMalformed") from None
    creds = creds_lookup(access_key)
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    try:
        t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
    except ValueError:
        raise S3Error("AuthorizationHeaderMalformed", "invalid X-Amz-Date") from None
    now = datetime.datetime.now(datetime.timezone.utc)
    if now > t + datetime.timedelta(seconds=expires):
        raise S3Error("AccessDenied", "Request has expired")
    scope = f"{scope_date}/{region}/{service}/aws4_request"
    canonical = _canonical_request(
        method, path, canonical_query(query_items, drop_signature=True),
        headers, signed_headers, q.get("X-Amz-Content-Sha256", UNSIGNED_PAYLOAD),
    )
    sts = _string_to_sign(amz_date, scope, canonical)
    key = signing_key(creds.secret_key, scope_date, region, service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3Error("SignatureDoesNotMatch")
    _remember_signing_key(creds.secret_key, scope_date, region, service, key)
    return creds


class ChunkedSigV4Reader:
    """Decodes + verifies a STREAMING-AWS4-HMAC-SHA256-PAYLOAD body
    (aws-chunked: <hex-len>;chunk-signature=<sig>\\r\\n<data>\\r\\n ...,
    terminated by a 0-length chunk). Reference:
    cmd/streaming-signature-v4.go.

    Zero-copy pipeline: `feed(data)` returns memoryviews into the
    internal buffer — one per verified chunk — that the caller streams
    straight to its sink (spool/encoder). The views are valid only
    until the NEXT feed() call: feed releases them and compacts the
    consumed prefix before appending, so verified payload bytes are
    hashed and written exactly once and never re-joined."""

    def __init__(self, creds: Credentials, auth_signature: str, amz_date: str,
                 scope_date: str, region: str, service: str):
        self._key = signing_key(creds.secret_key, scope_date, region, service)
        self._prev_sig = auth_signature
        self._amz_date = amz_date
        self._scope = f"{scope_date}/{region}/{service}/aws4_request"
        self._buf = bytearray()
        self._consumed = 0
        self._views: list = []
        self._done = False

    def _chunk_string_to_sign(self, chunk) -> str:
        return "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD",
            self._amz_date,
            self._scope,
            self._prev_sig,
            hashlib.sha256(b"").hexdigest(),
            hashlib.sha256(chunk).hexdigest(),
        ])

    def feed(self, data) -> list:
        """Append wire bytes; returns the newly verified payload chunks
        as memoryviews (valid until the next feed)."""
        for v in self._views:
            v.release()
        self._views = []
        if self._consumed:
            del self._buf[:self._consumed]
            self._consumed = 0
        self._buf += data
        out: list = []
        base = None
        while not self._done:
            nl = self._buf.find(b"\r\n", self._consumed)
            if nl < 0:
                break
            header = self._buf[self._consumed:nl].decode("latin-1")
            try:
                size_hex, _, rest = header.partition(";")
                size = int(size_hex, 16)
                sig = rest.split("chunk-signature=")[1].strip()
            except (ValueError, IndexError):
                raise S3Error("SignatureDoesNotMatch", "malformed chunk header") from None
            need = nl + 2 + size + 2
            if len(self._buf) < need:
                break
            if base is None:
                base = memoryview(self._buf)
            chunk = base[nl + 2: nl + 2 + size]
            want = hmac.new(self._key, self._chunk_string_to_sign(chunk).encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, sig):
                raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
            self._prev_sig = want
            self._consumed = need
            if size == 0:
                self._done = True
            else:
                out.append(chunk)
        # Keep every exported view (the base too) so the next feed can
        # release them before compacting the bytearray.
        self._views = list(out)
        if base is not None:
            self._views.append(base)
        return out

    @property
    def done(self) -> bool:
        return self._done


def verify_post_policy(form: dict, creds_lookup) -> "Credentials":
    """Verify a browser POST upload's policy signature
    (cmd/signature-v4.go:153 doesPolicySignatureMatch): the string-to-sign
    is the base64 policy document itself."""
    import base64 as _b64
    import json as _json

    policy_b64 = form.get("policy", "")
    credential = form.get("x-amz-credential", "")
    amz_date = form.get("x-amz-date", "")
    signature = form.get("x-amz-signature", "")
    if form.get("x-amz-algorithm") != ALGORITHM:
        raise S3Error("AuthorizationHeaderMalformed")
    try:
        parts = credential.split("/")
        access_key = "/".join(parts[:-4])
        scope_date, region, service, _ = parts[-4:]
    except ValueError:
        raise S3Error("AuthorizationHeaderMalformed") from None
    creds = creds_lookup(access_key)
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    key = signing_key(creds.secret_key, scope_date, region, service)
    want = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3Error("SignatureDoesNotMatch")
    _remember_signing_key(creds.secret_key, scope_date, region, service, key)
    # Expiry check from the policy document itself.
    try:
        doc = _json.loads(_b64.b64decode(policy_b64))
        expiry = doc.get("expiration", "")
        if expiry:
            import datetime as _dt

            exp = _dt.datetime.fromisoformat(
                expiry.replace("Z", "+00:00")).timestamp()
            if exp < _dt.datetime.now(_dt.timezone.utc).timestamp():
                raise S3Error("AccessDenied", "policy has expired")
    except (ValueError, TypeError):
        raise S3Error("AuthorizationHeaderMalformed",
                      "bad policy document") from None
    return creds


def check_post_policy_conditions(policy_b64: str, form: dict,
                                 file_size: int) -> None:
    """Enforce the policy's conditions against the submitted form
    (cmd/postpolicyform.go checkPostPolicy): eq / starts-with /
    content-length-range."""
    import base64 as _b64
    import json as _json

    doc = _json.loads(_b64.b64decode(policy_b64))
    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                have = form.get(k.lower(), "")
                if have != str(v):
                    raise S3Error("AccessDenied",
                                  f"policy condition failed: {k}")
        elif isinstance(cond, list) and len(cond) == 3:
            op, field, value = cond
            name = str(field).lstrip("$").lower()
            if op == "eq":
                if form.get(name, "") != str(value):
                    raise S3Error("AccessDenied",
                                  f"policy condition failed: eq {name}")
            elif op == "starts-with":
                if not form.get(name, "").startswith(str(value)):
                    raise S3Error(
                        "AccessDenied",
                        f"policy condition failed: starts-with {name}")
            elif op == "content-length-range":
                lo, hi = int(field), int(value)
                # shape: ["content-length-range", lo, hi]
                if not lo <= file_size <= hi:
                    raise S3Error("EntityTooLarge" if file_size > hi
                                  else "EntityTooSmall")
