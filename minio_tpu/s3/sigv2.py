"""AWS Signature Version 2 — legacy header and presigned auth.

Role-equivalent of cmd/signature-v2.go: older SDKs/tools (s3cmd classic
mode, old boto) sign with HMAC-SHA1 over a canonicalized string instead of
SigV4's scoped HMAC-SHA256 chain.

    Authorization: AWS <AccessKey>:<base64(HMAC-SHA1(secret, StringToSign))>
    StringToSign  = Method \n Content-MD5 \n Content-Type \n Date \n
                    CanonicalizedAmzHeaders + CanonicalizedResource

Presigned form carries ?AWSAccessKeyId=&Expires=&Signature= with the Expires
epoch in the Date slot (cmd/signature-v2.go doesPresignedSignatureMatchV2).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time

from minio_tpu.s3.errors import S3Error

V2_PREFIX = "AWS "

# Subresources included in the canonical resource, in sorted order
# (cmd/signature-v2.go resourceList).
SUBRESOURCES = (
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type", "response-expires",
    "select", "select-type", "tagging", "torrent", "uploadId", "uploads",
    "versionId", "versioning", "versions", "website", "encryption",
    "object-lock", "retention", "legal-hold", "replication",
)


def is_v2_header(headers) -> bool:
    a = headers.get("Authorization", "")
    return a.startswith(V2_PREFIX) and ":" in a


def is_v2_presigned(q: dict) -> bool:
    return "AWSAccessKeyId" in q and "Signature" in q and "Expires" in q


def _canonical_amz_headers(headers) -> str:
    amz: dict[str, list[str]] = {}
    for k in headers:
        lk = k.lower()
        if lk.startswith("x-amz-"):
            amz.setdefault(lk, []).append(" ".join(str(headers[k]).split()))
    return "".join(f"{k}:{','.join(v)}\n" for k, v in sorted(amz.items()))


def _canonical_resource(path: str, query_items: list[tuple[str, str]]) -> str:
    sub = []
    for k, v in query_items:
        if k in SUBRESOURCES:
            sub.append(f"{k}={v}" if v else k)
    out = path
    if sub:
        out += "?" + "&".join(sorted(sub))
    return out


def _string_to_sign(method: str, headers, path: str,
                    query_items: list[tuple[str, str]],
                    date_slot: str) -> str:
    return "\n".join([
        method,
        headers.get("Content-MD5", ""),
        headers.get("Content-Type", ""),
        date_slot,
    ]) + "\n" + _canonical_amz_headers(headers) + _canonical_resource(
        path, query_items)


def _sign(secret: str, string_to_sign: str) -> str:
    mac = hmac.new(secret.encode(), string_to_sign.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def verify_header_auth(method: str, path: str,
                       query_items: list[tuple[str, str]], headers,
                       creds_lookup):
    """-> Credentials. Raises S3Error on mismatch."""
    auth = headers.get("Authorization", "")
    try:
        access_key, sig = auth[len(V2_PREFIX):].split(":", 1)
    except ValueError:
        raise S3Error("InvalidArgument", "malformed V2 Authorization") from None
    creds = creds_lookup(access_key)
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    # Date slot: empty when x-amz-date is present (it rides in the amz
    # headers instead), else the Date header.
    date_slot = "" if headers.get("x-amz-date") else headers.get("Date", "")
    sts = _string_to_sign(method, headers, path, query_items, date_slot)
    if not hmac.compare_digest(_sign(creds.secret_key, sts), sig):
        raise S3Error("SignatureDoesNotMatch")
    return creds


def verify_presigned(method: str, path: str,
                     query_items: list[tuple[str, str]], headers,
                     creds_lookup):
    q = dict(query_items)
    creds = creds_lookup(q.get("AWSAccessKeyId", ""))
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    try:
        expires = int(q["Expires"])
    except (KeyError, ValueError):
        raise S3Error("InvalidArgument", "bad Expires") from None
    if time.time() > expires:
        raise S3Error("AccessDenied", "presigned URL expired")
    items = [(k, v) for k, v in query_items
             if k not in ("AWSAccessKeyId", "Signature", "Expires")]
    sts = _string_to_sign(method, headers, path, items, str(expires))
    # query_items arrive URL-decoded (parse_qsl) — compare directly.
    sig = q.get("Signature", "")
    if not hmac.compare_digest(_sign(creds.secret_key, sts), sig):
        raise S3Error("SignatureDoesNotMatch")
    return creds
