"""S3 XML response builders (reference cmd/api-response.go)."""

from __future__ import annotations

import datetime
import xml.etree.ElementTree as ET

S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _doc(root_tag: str) -> ET.Element:
    return ET.Element(root_tag, xmlns=S3_NS)


def render(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def error_xml(code: str, message: str, resource: str, request_id: str,
              extra: dict | None = None) -> bytes:
    root = ET.Element("Error")
    _el(root, "Code", code)
    _el(root, "Message", message)
    _el(root, "Resource", resource)
    _el(root, "RequestId", request_id)
    _el(root, "HostId", "minio-tpu")
    for k, v in (extra or {}).items():
        _el(root, k, v)
    return render(root)


# Stable synthetic canonical-user id (the reference's
# globalMinioDefaultOwnerID, cmd/api-utils.go) — there is no per-user
# canonical id space; every resource reports the deployment owner.
DEFAULT_OWNER_ID = (
    "02d6176db174dc93cb1b899f7c6078f08654445fe8cf1b6ce98d8855f66bdbf4")


def acl_xml(display_name: str = "minio-tpu") -> bytes:
    """Canned GetBucketAcl/GetObjectAcl answer (reference acl-handlers.go
    GetBucketACLHandler:120-287): owner with one FULL_CONTROL grant — the
    only ACL state the policy-based access model can express."""
    root = _doc("AccessControlPolicy")
    o = _el(root, "Owner")
    _el(o, "ID", DEFAULT_OWNER_ID)
    _el(o, "DisplayName", display_name)
    lst = _el(root, "AccessControlList")
    g = _el(lst, "Grant")
    grantee = _el(g, "Grantee")
    grantee.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
    grantee.set("xsi:type", "CanonicalUser")
    _el(grantee, "ID", DEFAULT_OWNER_ID)
    _el(grantee, "DisplayName", display_name)
    _el(g, "Permission", "FULL_CONTROL")
    return render(root)


def acl_body_is_private(body: bytes) -> bool:
    """True when a PutAcl XML body expresses the private ACL — at most
    ONE grant, FULL_CONTROL, no group/URI grantee. More than one grant
    (e.g. a cross-account CanonicalUser add) must be refused, not
    silently no-oped with a 200 (the reference rejects any body with
    extra grants with NotImplemented, cmd/acl-handlers.go)."""
    if not body.strip():
        return True
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ValueError("malformed ACL XML") from None
    def tag(el):
        return el.tag.rsplit("}", 1)[-1]
    if tag(root) != "AccessControlPolicy":
        # A foreign document (wrong payload on ?acl) is malformed, not a
        # silently-accepted private ACL.
        raise ValueError("body is not an AccessControlPolicy")
    grants = [el for el in root.iter() if tag(el) == "Grant"]
    if len(grants) > 1:
        return False
    perms = [el.text or "" for el in root.iter() if tag(el) == "Permission"]
    uris = [el for el in root.iter() if tag(el) == "URI"]
    return not uris and all(p == "FULL_CONTROL" for p in perms)


def list_buckets_xml(buckets, owner="minio-tpu") -> bytes:
    root = _doc("ListAllMyBucketsResult")
    o = _el(root, "Owner")
    _el(o, "ID", owner)
    _el(o, "DisplayName", owner)
    bs = _el(root, "Buckets")
    for b in buckets:
        be = _el(bs, "Bucket")
        _el(be, "Name", b.name)
        _el(be, "CreationDate", _iso(b.created))
    return render(root)


def _object_entry(parent, o, tag="Contents"):
    c = _el(parent, tag)
    _el(c, "Key", o.name)
    _el(c, "LastModified", _iso(o.mod_time))
    _el(c, "ETag", f'"{o.etag}"')
    _el(c, "Size", o.size)
    _el(c, "StorageClass", o.storage_class)
    return c


def list_objects_v1_xml(bucket, prefix, marker, delimiter, max_keys, res) -> bytes:
    root = _doc("ListBucketResult")
    _el(root, "Name", bucket)
    _el(root, "Prefix", prefix)
    _el(root, "Marker", marker)
    _el(root, "MaxKeys", max_keys)
    if delimiter:
        _el(root, "Delimiter", delimiter)
    _el(root, "IsTruncated", "true" if res.is_truncated else "false")
    if res.is_truncated and res.next_marker:
        _el(root, "NextMarker", res.next_marker)
    for o in res.objects:
        _object_entry(root, o)
    for p in res.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", p)
    return render(root)


def list_objects_v2_xml(bucket, prefix, token, start_after, delimiter,
                        max_keys, res) -> bytes:
    root = _doc("ListBucketResult")
    _el(root, "Name", bucket)
    _el(root, "Prefix", prefix)
    _el(root, "MaxKeys", max_keys)
    if delimiter:
        _el(root, "Delimiter", delimiter)
    _el(root, "KeyCount", len(res.objects) + len(res.prefixes))
    _el(root, "IsTruncated", "true" if res.is_truncated else "false")
    if token:
        _el(root, "ContinuationToken", token)
    if start_after:
        _el(root, "StartAfter", start_after)
    if res.is_truncated and res.next_marker:
        _el(root, "NextContinuationToken", res.next_marker)
    for o in res.objects:
        _object_entry(root, o)
    for p in res.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", p)
    return render(root)


def list_versions_xml(bucket, prefix, res) -> bytes:
    root = _doc("ListVersionsResult")
    _el(root, "Name", bucket)
    _el(root, "Prefix", prefix)
    _el(root, "IsTruncated", "true" if res.is_truncated else "false")
    if res.is_truncated:
        _el(root, "NextKeyMarker", res.next_marker)
        _el(root, "NextVersionIdMarker", res.next_version_id_marker)
    for o in res.objects:
        tag = "DeleteMarker" if o.delete_marker else "Version"
        v = _el(root, tag)
        _el(v, "Key", o.name)
        _el(v, "VersionId", o.version_id or "null")
        _el(v, "IsLatest", "true" if o.is_latest else "false")
        _el(v, "LastModified", _iso(o.mod_time))
        if not o.delete_marker:
            _el(v, "ETag", f'"{o.etag}"')
            _el(v, "Size", o.size)
            _el(v, "StorageClass", o.storage_class)
    for p in res.prefixes:
        cp = _el(root, "CommonPrefixes")
        _el(cp, "Prefix", p)
    return render(root)


def delete_result_xml(deleted, errors) -> bytes:
    root = _doc("DeleteResult")
    for d in deleted:
        e = _el(root, "Deleted")
        _el(e, "Key", d.object_name)
        if d.version_id:
            _el(e, "VersionId", d.version_id)
        if d.delete_marker:
            _el(e, "DeleteMarker", "true")
            _el(e, "DeleteMarkerVersionId", d.delete_marker_version_id)
    for key, code, msg in errors:
        e = _el(root, "Error")
        _el(e, "Key", key)
        _el(e, "Code", code)
        _el(e, "Message", msg)
    return render(root)


def copy_object_xml(etag: str, mod_time: float) -> bytes:
    root = _doc("CopyObjectResult")
    _el(root, "ETag", f'"{etag}"')
    _el(root, "LastModified", _iso(mod_time))
    return render(root)


def tagging_xml(tags: str) -> bytes:
    """tags: url-encoded k=v&k2=v2 string."""
    import urllib.parse

    root = _doc("Tagging")
    ts = _el(root, "TagSet")
    for k, v in urllib.parse.parse_qsl(tags):
        t = _el(ts, "Tag")
        _el(t, "Key", k)
        _el(t, "Value", v)
    return render(root)


def parse_tagging_xml(body: bytes) -> str:
    import urllib.parse

    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        from minio_tpu.s3.errors import S3Error
        raise S3Error("MalformedXML") from None
    ns = {"s3": S3_NS}
    pairs = []
    tagset = root.find("s3:TagSet", ns) or root.find("TagSet")
    if tagset is not None:
        for tag in tagset:
            key = val = None
            for child in tag:
                local = child.tag.rsplit("}", 1)[-1]
                if local == "Key":
                    key = child.text or ""
                elif local == "Value":
                    val = child.text or ""
            if key is not None:
                pairs.append((key, val or ""))
    return urllib.parse.urlencode(pairs)


def parse_delete_xml(body: bytes):
    """-> (objects: list[(key, version_id)], quiet: bool)"""
    from minio_tpu.s3.errors import S3Error

    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise S3Error("MalformedXML") from None
    out = []
    quiet = False
    for child in root:
        local = child.tag.rsplit("}", 1)[-1]
        if local == "Quiet":
            quiet = (child.text or "").strip().lower() == "true"
        elif local == "Object":
            key = vid = ""
            for c in child:
                l2 = c.tag.rsplit("}", 1)[-1]
                if l2 == "Key":
                    key = c.text or ""
                elif l2 == "VersionId":
                    vid = c.text or ""
            if key:
                out.append((key, vid))
    return out, quiet


def initiate_multipart_xml(bucket: str, key: str, upload_id: str) -> bytes:
    root = _doc("InitiateMultipartUploadResult")
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "UploadId", upload_id)
    return render(root)


def complete_multipart_xml(location: str, bucket: str, key: str, etag: str) -> bytes:
    root = _doc("CompleteMultipartUploadResult")
    _el(root, "Location", location)
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "ETag", f'"{etag}"')
    return render(root)


def parse_complete_multipart_xml(body: bytes):
    """-> list[(part_number, etag)]"""
    from minio_tpu.s3.errors import S3Error

    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise S3Error("MalformedXML") from None
    parts = []
    for child in root:
        if child.tag.rsplit("}", 1)[-1] != "Part":
            continue
        num = etag = None
        for c in child:
            local = c.tag.rsplit("}", 1)[-1]
            if local == "PartNumber":
                try:
                    num = int(c.text)
                except (TypeError, ValueError):
                    raise S3Error("MalformedXML") from None
            elif local == "ETag":
                etag = (c.text or "").strip('"')
        if num is not None and etag is not None:
            parts.append((num, etag))
    return parts


def list_parts_xml(bucket, key, upload_id, parts, truncated=False,
                   next_marker=0) -> bytes:
    root = _doc("ListPartsResult")
    _el(root, "Bucket", bucket)
    _el(root, "Key", key)
    _el(root, "UploadId", upload_id)
    _el(root, "IsTruncated", "true" if truncated else "false")
    if truncated:
        _el(root, "NextPartNumberMarker", next_marker)
    for p in parts:
        e = _el(root, "Part")
        _el(e, "PartNumber", p.part_number)
        _el(e, "ETag", f'"{p.etag}"')
        _el(e, "Size", p.size)
        if p.last_modified:
            _el(e, "LastModified", _iso(p.last_modified))
    return render(root)


def list_uploads_xml(bucket, uploads, truncated=False) -> bytes:
    root = _doc("ListMultipartUploadsResult")
    _el(root, "Bucket", bucket)
    _el(root, "IsTruncated", "true" if truncated else "false")
    for u in uploads:
        e = _el(root, "Upload")
        _el(e, "Key", u.object)
        _el(e, "UploadId", u.upload_id)
        _el(e, "Initiated", _iso(u.initiated))
    return render(root)


def versioning_xml(status: str) -> bytes:
    root = _doc("VersioningConfiguration")
    if status:
        _el(root, "Status", status)
    return render(root)


def parse_versioning_xml(body: bytes) -> str:
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ValueError("malformed XML") from None
    status = root.findtext("{*}Status") or root.findtext("Status") or ""
    if status not in ("Enabled", "Suspended"):
        raise ValueError(f"bad versioning status {status!r}")
    return status


def sts_assume_role_xml(access_key: str, secret_key: str,
                        session_token: str, expiry_iso: str,
                        request_id: str, action: str = "AssumeRole",
                        subject: str = "") -> bytes:
    """STS response document for AssumeRole and its federated variants
    (AssumeRoleWithWebIdentity / AssumeRoleWithClientGrants,
    cmd/sts-handlers.go response types)."""
    ns = "https://sts.amazonaws.com/doc/2011-06-15/"
    root = ET.Element(f"{action}Response", xmlns=ns)
    result = _el(root, f"{action}Result")
    creds = _el(result, "Credentials")
    _el(creds, "AccessKeyId", access_key)
    _el(creds, "SecretAccessKey", secret_key)
    _el(creds, "SessionToken", session_token)
    _el(creds, "Expiration", expiry_iso)
    if subject and action == "AssumeRoleWithWebIdentity":
        _el(result, "SubjectFromWebIdentityToken", subject)
    meta = _el(root, "ResponseMetadata")
    _el(meta, "RequestId", request_id)
    return render(root)
