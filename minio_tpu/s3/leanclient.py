"""LeanS3 — a minimal raw-socket SigV4 S3 client.

Purpose-built for benchmarking and in-tree conformance drives: requests/
urllib3 cost ~1ms per call (session machinery, header canonicalization,
response object construction), which would dominate any small-object ops/s
measurement of the server. This client keeps one persistent connection,
precomputes the SigV4 signing key, and parses responses with plain bytes
ops — per-op overhead is ~60-80us.

Independent client-side implementation of the wire protocol (the reference
signs requests in cmd/test-utils_test.go for the same reason): server
verification is cross-checked against a second signer, not mirrored.

Supports serial request/response and HTTP/1.1 pipelining (`pipeline`),
which is how the concurrent axis of the small-object benchmark is driven
without spawning client threads that would steal the server's CPU.
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import time


class LeanS3:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, region: str = "us-east-1"):
        self.host, self.port, self.ak = host, port, access_key
        self.region = region
        scope_date = time.strftime("%Y%m%d", time.gmtime())
        key = ("AWS4" + secret_key).encode()
        for part in (scope_date, region, "s3", "aws4_request"):
            key = hmac.new(key, part.encode(), hashlib.sha256).digest()
        self.signing_key = key
        self.scope = f"{scope_date}/{region}/s3/aws4_request"
        self.hosthdr = f"{host}:{port}"
        self.sock = socket.create_connection((host, port))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # ---------- request building ----------

    def build(self, method: str, path: str, body: bytes = b"") -> bytes:
        """A fully signed HTTP/1.1 request as bytes (for pipelining)."""
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        payload_hash = hashlib.sha256(body).hexdigest()
        canonical = (
            f"{method}\n{path}\n\n"
            f"host:{self.hosthdr}\n"
            f"x-amz-content-sha256:{payload_hash}\n"
            f"x-amz-date:{amz_date}\n\n"
            "host;x-amz-content-sha256;x-amz-date\n"
            f"{payload_hash}"
        )
        sts = ("AWS4-HMAC-SHA256\n" + amz_date + "\n" + self.scope + "\n"
               + hashlib.sha256(canonical.encode()).hexdigest())
        sig = hmac.new(self.signing_key, sts.encode(),
                       hashlib.sha256).hexdigest()
        return (
            f"{method} {path} HTTP/1.1\r\n"
            f"Host: {self.hosthdr}\r\n"
            f"x-amz-date: {amz_date}\r\n"
            f"x-amz-content-sha256: {payload_hash}\r\n"
            f"Authorization: AWS4-HMAC-SHA256 Credential={self.ak}/"
            f"{self.scope}, SignedHeaders=host;x-amz-content-sha256;"
            f"x-amz-date, Signature={sig}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body

    # ---------- wire ----------

    def _read_response(self, read_body: bool = True) -> tuple[int, bytes]:
        while b"\r\n\r\n" not in self.buf:
            d = self.sock.recv(65536)
            if not d:
                raise ConnectionError("server closed connection")
            self.buf += d
        head, _, self.buf = self.buf.partition(b"\r\n\r\n")
        status = int(head[9:12])
        clen = 0
        chunked = False
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            lk = k.lower()
            if lk == b"content-length":
                clen = int(v)
            elif lk == b"transfer-encoding" and b"chunked" in v.lower():
                chunked = True
        if not read_body:
            # HEAD: Content-Length describes the entity that WOULD be sent;
            # no body follows.
            return status, b""
        if chunked:
            body = bytearray()
            while True:
                while b"\r\n" not in self.buf:
                    self.buf += self.sock.recv(65536)
                szline, _, self.buf = self.buf.partition(b"\r\n")
                sz = int(szline.split(b";")[0], 16)
                while len(self.buf) < sz + 2:
                    self.buf += self.sock.recv(65536)
                body += self.buf[:sz]
                self.buf = self.buf[sz + 2:]
                if sz == 0:
                    break
            return status, bytes(body)
        while len(self.buf) < clen:
            d = self.sock.recv(65536)
            if not d:
                raise ConnectionError("server closed connection")
            self.buf += d
        body, self.buf = self.buf[:clen], self.buf[clen:]
        return status, body

    def request(self, method: str, path: str,
                body: bytes = b"") -> tuple[int, bytes]:
        self.sock.sendall(self.build(method, path, body))
        return self._read_response(read_body=method != "HEAD")

    def put(self, path: str, body: bytes = b"") -> tuple[int, bytes]:
        return self.request("PUT", path, body)

    def get(self, path: str) -> tuple[int, bytes]:
        return self.request("GET", path)

    def head(self, path: str) -> tuple[int, bytes]:
        return self.request("HEAD", path)

    def delete(self, path: str) -> tuple[int, bytes]:
        return self.request("DELETE", path)

    def pipeline(self, reqs: list[bytes],
                 window: int = 16) -> list[tuple[int, bytes]]:
        """Issue pre-built requests keeping up to `window` in flight —
        the concurrent-clients axis without client-side threads."""
        out: list[tuple[int, bytes]] = []
        sent = 0
        for req in reqs:
            self.sock.sendall(req)
            sent += 1
            if sent - len(out) >= window:
                out.append(self._read_response())
        while len(out) < sent:
            out.append(self._read_response())
        return out
