"""S3-compatible HTTP surface (reference L5-L6: cmd/api-router.go,
cmd/object-handlers.go, cmd/bucket-handlers.go) on aiohttp.

The handler chain mirrors the reference's middleware stack
(cmd/routers.go:41-83) in compressed form: request classification ->
signature verification -> handler -> XML/streaming response, with every
response carrying x-amz-request-id and the S3 error XML schema.
"""
