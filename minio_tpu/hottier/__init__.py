"""HBM-resident hot-object tier (docs/HOTTIER.md).

The dataplane ring (PR 8) made device memory a *staging* detail: every
byte still round-trips drives on each GET. This tier makes it a
*serving* tier — the hottest objects' encoded data shards (+ their
mxsum bitrot digests) stay resident in pre-allocated device arrays, so
a hot GET is one device-side gather+digest launch and one D2H DMA:
zero drive opens, no quorum fan-out, no per-request host reassembly.

Gate: `MTPU_HOTTIER=1` (opt-in). The drive path is never removed — it
is the fallback on every miss AND the bit-exactness oracle
(tests/test_hottier.py, bench.py hot_get). Correctness never rests on
invalidation timeliness: a tier hit requires the *freshly elected*
FileInfo (signature-validated by the metaplane set cache when armed)
to match the resident entry's identity exactly, so a stale entry can
only ever miss, never serve.

The process-global tier is created lazily on first use. In the
multi-process front door the real tier lives in worker 0 beside the
LaneServer; sibling workers install a router (set_router) whose client
rides the shm ring's OP_HOTGET so every worker's hot GETs coalesce
into worker 0's launches (minio_tpu/frontdoor/laneserver.py).
"""

from __future__ import annotations

import os
import threading

ENABLE_ENV = "MTPU_HOTTIER"

_global_mu = threading.Lock()
_global_tier = None
# Optional tier router (the multi-process front door installs one so
# non-owner workers route hot GETs over the shm ring — OP_HOTGET).
_router = None
# Optional process-global admit reader: fn(bucket, obj) -> (info,
# byte-iterator). Registered by servers that own a full object layer
# (frontdoor worker 0); per-miss readers from the erasure sets are
# used when a note carries one.
_reader = None


def enabled() -> bool:
    """Read the env gate live — opt-IN (the tier pins device memory)."""
    return os.environ.get(ENABLE_ENV, "0") in ("1", "true", "on")


def get_tier():
    """The process-global tier, created on first use."""
    global _global_tier
    with _global_mu:
        if _global_tier is None or _global_tier.closed:
            from minio_tpu.hottier.tier import HotObjectTier

            _global_tier = HotObjectTier()
        return _global_tier


def set_router(fn) -> None:
    """Install (or clear, with None) a tier router consulted by
    maybe_tier before the process-local tier."""
    global _router
    _router = fn


def set_reader(fn) -> None:
    """Register the process-global admit reader (or clear with None)."""
    global _reader
    _reader = fn


def default_reader():
    return _reader


def maybe_tier():
    """The serving tier when the gate is on, else None (drive path).
    The GET integration point calls this per request."""
    if not enabled():
        return None
    if _router is not None:
        tier = _router()
        if tier is not None:
            return tier
    return get_tier()


def reset_global() -> None:
    """Close and drop the global tier (tests; safe when never built)."""
    global _global_tier
    with _global_mu:
        tier, _global_tier = _global_tier, None
    if tier is not None:
        tier.close()
