"""HotObjectTier — the device-resident hot-object serving tier.

Residency model (docs/HOTTIER.md): an admitted object's payload is
split on its own erasure grid — block_size blocks, each block split
into its k data-shard chunks (systematic RS: the data shards ARE
contiguous block slices) — and staged into one pow2-bucketed device
array per object (hottier/arena.py), together with a per-chunk mxsum
digest baseline. A hot GET then:

  1. elects FileInfo exactly as today (set-cache signature-validated),
  2. matches the elected identity (version, etag, size, mod_time)
     against the resident entry — any mismatch is a miss, never a
     stale serve,
  3. launches ONE device kernel (gather the requested block window +
     fused mxsum digests of exactly the rows being served),
  4. DMAs the window out, compares digests to the admit baseline, and
     streams memoryview slices straight to the response.

Zero drive opens, zero quorum fan-out, zero host reassembly. Every
miss (absent, cold, identity-changed, digest-rotted, saturated) falls
back to the drive path, which stays the bit-exactness oracle.

Heat/admission: a per-object exponential-decay EWMA fed by the GET
serving path (the same request stream behind
minio_tpu_s3_requests_total{api="GetObject"}). A key whose heat
crosses MTPU_HOTTIER_MIN_HEAT is queued for admission; one background
thread (mtpu-hottier-admit) re-reads it through the drive path — the
oracle — stages, digests, and installs. Admission is epoch-fenced:
every invalidation bumps the key's epoch, and an admit only installs
if the epoch it captured before reading is still current, so a PUT
racing an admit can never leave stale bytes resident. Eviction drops
the coldest entries when the byte budget needs room.

Coherence: every mutating path that invalidates the FileInfo set
cache (PUT, DELETE, heal, multipart complete, tags/metadata writes)
invalidates here through the same hook (_meta_invalidate); a hot key
re-admits after the drop (write-through). None of that is load-
bearing for correctness — step 2 above is.
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

from minio_tpu import obs
from minio_tpu.hottier import arena
from minio_tpu.obs import flight
from minio_tpu.logger import get_logger
from minio_tpu.utils import errors as se

_HITS = obs.counter(
    "minio_tpu_hottier_hits_total",
    "Hot-tier GETs served from device-resident shards (zero drive I/O)"
).labels()
_MISSES = obs.counter(
    "minio_tpu_hottier_misses_total",
    "Hot-tier lookups that fell back to the drive path "
    "(absent, cold, identity-changed, digest-mismatch, or oversize)"
).labels()
_ADMITS = obs.counter(
    "minio_tpu_hottier_admits_total",
    "Objects admitted (or re-admitted) into device residence").labels()
_EVICTIONS = obs.counter(
    "minio_tpu_hottier_evictions_total",
    "Resident entries dropped (budget pressure, invalidation, or "
    "digest mismatch)").labels()
_BYTES = obs.gauge(
    "minio_tpu_hottier_bytes",
    "Device bytes currently charged to resident hot objects")
_HIT_RATIO = obs.gauge(
    "minio_tpu_hottier_hit_ratio",
    "Hot-tier hit ratio (hits / lookups) since process start")
_HEAT = obs.gauge(
    "minio_tpu_hottier_heat",
    "Tracked keys whose decayed heat is <= le (cumulative buckets; "
    "+Inf = all tracked keys) — the admission-threshold tuning view",
    ("le",))
# Fixed bucket bounds bracketing the admission threshold's practical
# range (DEFAULT_MIN_HEAT=1.5): where the population sits relative to
# MTPU_HOTTIER_MIN_HEAT is exactly what admission tuning needs to see.
_HEAT_BOUNDS = (0.5, 1.0, 2.0, 4.0, 8.0, 16.0)

DEFAULT_MAX_OBJECT = 8 << 20
# One GET scores ~1.0 heat; the default threshold sits between the
# first GET (1.0) and the second (just under 2.0 after decay), so a
# key admits on its second read inside the halflife window.
DEFAULT_MIN_HEAT = 1.5
DEFAULT_HALFLIFE_S = 60.0
# Eviction hysteresis: a victim must be this factor colder than the
# admitting key. Without it a uniform round-robin scan thrashes the
# whole arena — the key just read is always epsilon-hotter than the
# oldest resident, so every miss would evict a resident that was
# about to hit (classic sequential-scan cache pollution).
EVICT_MARGIN = 1.5
# Per-key admission cooldown: an admit is a full oracle read, and a
# hot key being overwritten continuously (write-through re-admit after
# every invalidation) or a hot key that keeps losing _make_room would
# otherwise re-read itself on every GET — background load that
# competes with foreground serving and heal on small hosts. One
# attempt per key per cooldown bounds it.
DEFAULT_ADMIT_COOLDOWN_S = 2.0

# The admit thread must not re-note its own oracle reads: its GET runs
# through the same _open_fi_range hook that feeds heat.
_tl = threading.local()


def fi_ident(fi) -> tuple:
    """The generation identity of an elected FileInfo: what must match
    for resident bytes to be the bytes this election describes."""
    return (fi.version_id or "", fi.metadata.get("etag", ""),
            int(fi.size), float(fi.mod_time))


def info_ident(info) -> tuple:
    """Same identity from an ObjectInfo (the admit reader's view)."""
    return (getattr(info, "version_id", "") or "", info.etag,
            int(info.size), float(info.mod_time))


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class _Entry:
    __slots__ = ("ident", "k", "bs", "size", "nblocks", "shape",
                 "data", "lens_dev", "lens", "digs", "staging")

    def __init__(self, ident, k, bs, size, nblocks, shape, data,
                 lens_dev, lens, digs, staging):
        self.ident = ident
        self.k = k
        self.bs = bs
        self.size = size
        self.nblocks = nblocks
        self.shape = shape
        self.data = data          # device (rows, k, width) u8
        self.lens_dev = lens_dev  # device (rows,) i32 chunk lengths
        self.lens = lens          # host copy of lens_dev
        self.digs = digs          # host (rows, k, 32) admit baseline
        self.staging = staging    # host staging (returned on evict)


class HotObjectTier:
    def __init__(self, *, budget_bytes: int | None = None,
                 max_object: int | None = None,
                 min_heat: float | None = None,
                 halflife_s: float | None = None,
                 verify: bool | None = None):
        env = os.environ.get
        self.max_object = max_object if max_object is not None else int(
            env("MTPU_HOTTIER_MAX_OBJECT", str(DEFAULT_MAX_OBJECT)))
        self.min_heat = min_heat if min_heat is not None else float(
            env("MTPU_HOTTIER_MIN_HEAT", str(DEFAULT_MIN_HEAT)))
        self.halflife = halflife_s if halflife_s is not None else float(
            env("MTPU_HOTTIER_HALFLIFE_S", str(DEFAULT_HALFLIFE_S)))
        self.verify = verify if verify is not None else (
            env("MTPU_HOTTIER_VERIFY", "1") not in ("0", "false", "off"))
        self.admit_cooldown = float(env("MTPU_HOTTIER_ADMIT_COOLDOWN_S",
                                        str(DEFAULT_ADMIT_COOLDOWN_S)))
        budget = budget_bytes if budget_bytes is not None else int(
            env("MTPU_HOTTIER_BYTES", str(arena.DEFAULT_BUDGET_BYTES)))
        self.arena = arena.DeviceArena(budget)
        self._mu = threading.Lock()           # leaf: entries/heat/epochs
        self._entries: dict[tuple, _Entry] = {}
        self._heat: dict[tuple, tuple[float, float]] = {}  # (value, t)
        self._epoch: dict[tuple, int] = {}
        self._pending: set[tuple] = set()
        self._last_attempt: dict[tuple, float] = {}
        self._readers: dict[tuple, object] = {}
        self._q: queue.Queue = queue.Queue(maxsize=256)
        self.closed = False
        self._stats = {"hits": 0, "misses": 0, "admits": 0,
                       "evictions": 0, "admit_errors": 0}
        self._gauge_t = 0.0  # last heat/hit-ratio gauge refresh
        self._admit_t = threading.Thread(
            target=self._admit_loop, daemon=True,
            name="mtpu-hottier-admit")
        self._admit_t.start()

    # ------------------------------------------------------------------
    # heat
    # ------------------------------------------------------------------

    def _touch(self, key: tuple, now: float) -> float:
        """Bump the key's decaying heat; caller holds _mu."""
        val, t = self._heat.get(key, (0.0, now))
        dt = max(0.0, now - t)
        val = val * (0.5 ** (dt / self.halflife)) + 1.0
        self._heat[key] = (val, now)
        if len(self._heat) > 8192:
            # Bound the heat map: drop the coldest half by decayed value.
            items = sorted(self._heat.items(),
                           key=lambda kv: kv[1][0])
            for k, _v in items[:4096]:
                if k not in self._entries:
                    self._heat.pop(k, None)
        return val

    def _heat_of(self, key: tuple, now: float) -> float:
        val, t = self._heat.get(key, (0.0, now))
        return val * (0.5 ** (max(0.0, now - t) / self.halflife))

    def _refresh_gauges(self) -> None:
        """Throttled (1 s) refresh of the heat-distribution and
        hit-ratio gauges from whichever lookup got here first — a
        scrape sees at-most-a-second-old truth without any lookup
        paying a full O(keys) pass."""
        now = time.monotonic()
        with self._mu:
            if now - self._gauge_t < 1.0:
                return
            self._gauge_t = now
            heats = [v * (0.5 ** (max(0.0, now - t) / self.halflife))
                     for v, t in self._heat.values()]
            hits = self._stats["hits"]
            misses = self._stats["misses"]
        for b in _HEAT_BOUNDS:
            _HEAT.labels(le=str(b)).set(
                sum(1 for h in heats if h <= b))
        _HEAT.labels(le="+Inf").set(len(heats))
        if hits + misses:
            _HIT_RATIO.set(hits / (hits + misses))

    # ------------------------------------------------------------------
    # the serving path
    # ------------------------------------------------------------------

    def serve(self, bucket: str, obj: str, fi, offset: int, length: int):
        """Serve [offset, offset+length) from device residence, or None
        (drive path). `fi` is the caller's freshly elected FileInfo —
        its identity gates the hit."""
        return self.serve_ident(bucket, obj, fi_ident(fi), offset,
                                length)

    def serve_ident(self, bucket: str, obj: str, ident: tuple,
                    offset: int, length: int):
        if length <= 0:
            return None
        key = (bucket, obj)
        drop = None
        with self._mu:
            entry = self._entries.get(key)
            if entry is not None and entry.ident != ident:
                # Identity moved under the entry (a mutation this
                # process never saw — e.g. a sibling worker's PUT):
                # the entry can only mislead future heat, drop it now.
                drop = self._entries.pop(key)
            if drop is not None or entry is None:
                entry = None
            else:
                self._touch(key, time.monotonic())
        if drop is not None:
            self._release(drop)
            _EVICTIONS.inc()
            self._stats["evictions"] += 1
        if entry is None:
            return None
        t0 = time.perf_counter()
        out = self._serve_entry(entry, offset, length)
        if out is None:
            # Digest mismatch: resident bits rotted — evict; the
            # caller's note_miss accounts the fallback.
            self.invalidate(bucket, obj)
            return None
        dt = time.perf_counter() - t0
        _HITS.inc()
        self._stats["hits"] += 1
        # Attribution: the device serve lands on the request timeline
        # (it replaces the drive read inside the response-drain stage)
        # and, when watched, on the trace bus.
        flight.stamp("hottier_serve", dt, "hottier")
        if obs.has_subscribers():
            obs.publish({"type": "hottier", "plane": "hottier",
                         "event": "hit", "bucket": bucket, "obj": obj,
                         "bytes": length, "time": time.time(),
                         "durationNs": int(dt * 1e9)})
        self._refresh_gauges()
        return out

    def _serve_entry(self, entry: _Entry, offset: int, length: int):
        rows, k, width = entry.shape
        b0 = offset // entry.bs
        b1 = (offset + length - 1) // entry.bs + 1
        nb = arena.rows_bucket(b1 - b0)
        start = min(b0, rows - nb)
        kern = arena.serve_kernel(rows, k, width, nb, self.verify)
        win, digs = kern(entry.data, entry.lens_dev, start)
        mat = np.asarray(win)          # the one D2H sync (the DMA)
        if digs is not None:
            got = np.asarray(digs)
            for b in range(b0, min(b1, entry.nblocks)):
                if not np.array_equal(got[b - start],
                                      entry.digs[b]):
                    return None
        out: list[memoryview] = []
        end = offset + length
        for b in range(b0, b1):
            blk_start = b * entry.bs
            s = int(entry.lens[b])
            lo = max(offset, blk_start) - blk_start
            hi = min(end, blk_start + min(entry.bs,
                                          entry.size - blk_start))
            hi -= blk_start
            if hi <= lo:
                continue
            # Walk the block's k resident chunks, memoryview slices
            # only (the _yield_block_range discipline).
            pos = 0
            row = mat[b - start]
            for i in range(k):
                if pos >= hi:
                    break
                cend = pos + s
                a = max(lo, pos)
                z = min(hi, cend)
                if z > a:
                    out.append(memoryview(row[i])[a - pos:z - pos])
                pos = cend
        return iter(out)

    # ------------------------------------------------------------------
    # heat feed + admission
    # ------------------------------------------------------------------

    def note_miss(self, bucket: str, obj: str, size: int,
                  reader=None, grid: tuple | None = None) -> None:
        """Feed heat for a GET the drive path served; queue admission
        once the key is provably hot. `reader` is a zero-arg callable
        returning (ObjectInfo, byte-iterator) through the oracle path;
        None uses the process-global reader (hottier.set_reader).
        `grid` is the object's (data_blocks, block_size) — it only
        shapes the resident layout, bytes served are grid-independent."""
        if getattr(_tl, "in_admit", False):
            return  # the admit thread's own oracle read is not demand
        _MISSES.inc()
        self._stats["misses"] += 1
        self._refresh_gauges()
        if size <= 0 or size > self.max_object:
            return
        key = (bucket, obj)
        enqueue = False
        with self._mu:
            heat = self._touch(key, time.monotonic())
            prev = self._readers.get(key)
            self._readers[key] = (
                reader if reader is not None else
                (prev[0] if prev else None),
                grid if grid is not None else (prev[1] if prev else None),
                size or (prev[2] if prev else 0))
            if len(self._readers) > 8192:
                self._readers.pop(next(iter(self._readers)))
            if (heat >= self.min_heat and key not in self._entries
                    and key not in self._pending):
                self._pending.add(key)
                epoch = self._epoch.get(key, 0)
                enqueue = True
        if enqueue:
            try:
                self._q.put_nowait((key, epoch))
            except queue.Full:
                with self._mu:
                    self._pending.discard(key)

    def invalidate(self, bucket: str, obj: str) -> None:
        """Drop residence for a mutated key (PUT/DELETE/heal/multipart
        complete ride this through _meta_invalidate) and bump its
        epoch so an in-flight admission cannot install stale bytes.
        A key that was resident re-admits (write-through) once the
        mutation settles."""
        key = (bucket, obj)
        readmit = False
        with self._mu:
            self._epoch[key] = self._epoch.get(key, 0) + 1
            entry = self._entries.pop(key, None)
            if (entry is not None and key not in self._pending
                    and self._heat_of(key, time.monotonic())
                    >= self.min_heat and key in self._readers):
                self._pending.add(key)
                epoch = self._epoch[key]
                readmit = True
        if entry is not None:
            self._release(entry)
            _EVICTIONS.inc()
            self._stats["evictions"] += 1
        if readmit:
            try:
                self._q.put_nowait((key, epoch))
            except queue.Full:
                with self._mu:
                    self._pending.discard(key)

    def invalidate_bucket(self, bucket: str) -> None:
        with self._mu:
            victims = [k for k in self._entries if k[0] == bucket]
            entries = [self._entries.pop(k) for k in victims]
            for k in victims:
                self._epoch[k] = self._epoch.get(k, 0) + 1
        for e in entries:
            self._release(e)
            _EVICTIONS.inc()
            self._stats["evictions"] += 1

    # ------------------------------------------------------------------
    # the admit thread
    # ------------------------------------------------------------------

    def _admit_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            key, epoch = item
            try:
                self._admit_one(key, epoch)
            except (se.StorageError, se.ObjectError, OSError) as e:
                # The oracle read failed (object gone, quorum lost,
                # drive fault): nothing resident changes; the key can
                # re-heat later.
                get_logger().debug(
                    f"hottier admit {key[0]}/{key[1]}: {e}")
                self._stats["admit_errors"] += 1
            except Exception as e:  # noqa: BLE001 - admit is advisory;
                # a bug here must degrade to drive-path serving, not
                # kill the thread.
                get_logger().warning(
                    f"hottier admit {key[0]}/{key[1]}: "
                    f"{type(e).__name__}: {e}")
                self._stats["admit_errors"] += 1
            finally:
                with self._mu:
                    self._pending.discard(key)

    def _admit_one(self, key: tuple, epoch: int) -> None:
        from minio_tpu import hottier as _ht

        bucket, obj = key
        now = time.monotonic()
        with self._mu:
            if self._epoch.get(key, 0) != epoch or self.closed:
                return
            if now - self._last_attempt.get(key, -1e9) \
                    < self.admit_cooldown:
                return  # churny key: one oracle read per cooldown
            self._last_attempt[key] = now
            if len(self._last_attempt) > 8192:
                cut = now - max(self.admit_cooldown, 1.0)
                self._last_attempt = {
                    k2: t for k2, t in self._last_attempt.items()
                    if t >= cut}
            rec = self._readers.get(key)
        reader, grid, noted_size = rec if rec is not None else (None,) * 3
        if noted_size:
            # Doomed-admission pre-check on the NOTED size: skip the
            # whole oracle read when the entry could not be installed
            # anyway (over budget, or no victim cold enough to evict).
            k_est, bs_est = self._grid(grid)
            est = arena.entry_shape(
                _ceil_div(noted_size, bs_est), k_est,
                _ceil_div(min(bs_est, noted_size), k_est))
            if not self._room_likely(key, est):
                return
        if reader is None:
            default = _ht.default_reader()
            if default is None:
                return
            reader = (lambda r=default, b=bucket, o=obj: r(b, o))
        _tl.in_admit = True
        try:
            info, stream = reader()
        finally:
            _tl.in_admit = False
        ident = info_ident(info)
        size = int(info.size)
        if size <= 0 or size > self.max_object:
            self._drain(stream)
            return
        k, bs = self._grid(grid)
        if k <= 0 or bs <= 0:
            self._drain(stream)
            return
        nblocks = _ceil_div(size, bs)
        chunk_len = _ceil_div(min(bs, size), k)
        shape = arena.entry_shape(nblocks, k, chunk_len)
        if not self._make_room(key, shape):
            self._drain(stream)
            return
        staging = self.arena.acquire(shape)
        lens = np.zeros((shape[0],), dtype=np.int32)
        ok = self._stage(staging, lens, stream, size, k, bs, nblocks)
        if not ok:
            self.arena.recycle_staging(shape, staging)
            return
        # Ownership transfer, not an escape: on success the sealed
        # _Entry OWNS this staging array (entry.staging) for its whole
        # resident lifetime — it returns to the arena free list only at
        # eviction, via _release -> recycle_staging. Every failure path
        # below recycles it here instead.
        # mtpu: allow(MTPU008)
        entry = self._seal(ident, k, bs, size, nblocks, shape, staging,
                           lens)
        if entry is None:
            self.arena.recycle_staging(shape, staging)
            return
        displaced = None
        with self._mu:
            if self._epoch.get(key, 0) != epoch or self.closed:
                installed = False
            else:
                displaced = self._entries.get(key)
                self._entries[key] = entry
                installed = True
        if not installed:
            self.arena.release(shape)
            self.arena.recycle_staging(shape, staging)
            return
        if displaced is not None:
            self._release(displaced)
        _ADMITS.inc()
        self._stats["admits"] += 1
        _BYTES.set(self.arena.used_bytes)

    def _grid(self, grid: tuple | None) -> tuple[int, int]:
        """(k, block_size) — the object's erasure grid, from the miss
        note when the erasure layer supplied it, else the deployment
        defaults (e.g. ring-noted keys). The grid only shapes the
        resident layout; served bytes are grid-independent."""
        if grid is not None and grid[0] and grid[1]:
            return int(grid[0]), int(grid[1])
        from minio_tpu.erasure.codec import DEFAULT_BLOCK_SIZE

        return 4, DEFAULT_BLOCK_SIZE

    def _stage(self, staging: np.ndarray, lens: np.ndarray, stream,
               size: int, k: int, bs: int, nblocks: int) -> bool:
        """Fold the oracle stream into the arena staging layout. The
        flat payload lands once (np copy per stream chunk), then each
        block's k data-shard chunks alias into their lane rows."""
        flat = np.empty(size, dtype=np.uint8)
        pos = 0
        for piece in stream:
            ln = len(piece)
            if pos + ln > size:
                return False  # stream longer than the elected size
            flat[pos:pos + ln] = np.frombuffer(piece, dtype=np.uint8)
            pos += ln
        if pos != size:
            return False
        for b in range(nblocks):
            blk = flat[b * bs:min((b + 1) * bs, size)]
            s = _ceil_div(len(blk), k)
            lens[b] = s
            for i in range(k):
                c = blk[i * s:(i + 1) * s]
                if len(c):
                    staging[b, i, :len(c)] = c
        return True

    def _seal(self, ident, k, bs, size, nblocks, shape, staging, lens):
        """Device_put + admit-time digest baseline. The baseline is
        hashed from the HOST staging bytes (fused.digest_chunks_host —
        its own device launch over a separate transfer), then the serve
        kernel re-hashes the RESIDENT copy; a mismatch means the admit
        transfer itself corrupted and the entry is refused."""
        from minio_tpu.ops import fused

        rows, _k, width = shape
        chunks = []
        for b in range(nblocks):
            s = int(lens[b])
            for i in range(k):
                chunks.append(staging[b, i, :s])
        base = fused.digest_chunks_host(chunks, width)
        digs = np.zeros((rows, k, 32), dtype=np.uint8)
        ci = 0
        for b in range(nblocks):
            for i in range(k):
                digs[b, i] = np.frombuffer(base[ci], dtype=np.uint8)
                ci += 1
        data_dev = self.arena.seal(shape, staging)
        import jax

        lens_dev = jax.device_put(lens)
        if self.verify:
            kern = arena.serve_kernel(rows, k, width, rows, True)
            _win, dv = kern(data_dev, lens_dev, 0)
            got = np.asarray(dv)
            for b in range(nblocks):
                if not np.array_equal(got[b], digs[b]):
                    self.arena.release(shape)
                    return None
        return _Entry(ident, k, bs, size, nblocks, shape, data_dev,
                      lens_dev, lens, digs, staging)

    def _room_likely(self, key: tuple, shape: tuple) -> bool:
        """Non-destructive preview of _make_room: would the eviction
        policy find enough margin-colder victims? Run BEFORE the admit
        pays its oracle read — evicting nothing, promising nothing."""
        need = arena.shape_bytes(shape)
        if need > self.arena.budget:
            return False
        if self.arena.fits(shape):
            return True
        now = time.monotonic()
        with self._mu:
            my_heat = self._heat_of(key, now)
            freeable = 0
            for k2, e2 in self._entries.items():
                if k2 == key:
                    continue
                if self._heat_of(k2, now) * EVICT_MARGIN < my_heat:
                    freeable += arena.shape_bytes(e2.shape)
        return self.arena.used_bytes - freeable + need <= self.arena.budget

    def _make_room(self, key: tuple, shape: tuple) -> bool:
        """Evict the coldest entries until `shape` fits the budget.
        Victims must be EVICT_MARGIN colder than the admitting key —
        a resident never yields to an equal-heat admission, so a
        uniform scan over a working set larger than the budget leaves
        the resident subset stable (and hitting) instead of churning
        every entry through the arena."""
        if arena.shape_bytes(shape) > self.arena.budget:
            return False
        while not self.arena.fits(shape):
            now = time.monotonic()
            with self._mu:
                my_heat = self._heat_of(key, now)
                victims = sorted(
                    ((self._heat_of(k2, now), k2)
                     for k2 in self._entries if k2 != key))
                if not victims or victims[0][0] * EVICT_MARGIN >= my_heat:
                    return False
                vkey = victims[0][1]
                entry = self._entries.pop(vkey)
                self._epoch[vkey] = self._epoch.get(vkey, 0) + 1
            self._release(entry)
            _EVICTIONS.inc()
            self._stats["evictions"] += 1
        return True

    def _drain(self, stream) -> None:
        for _ in stream:
            pass

    def _release(self, entry: _Entry) -> None:
        self.arena.release(entry.shape)
        self.arena.recycle_staging(entry.shape, entry.staging)
        _BYTES.set(self.arena.used_bytes)

    # ------------------------------------------------------------------
    # lifecycle / introspection
    # ------------------------------------------------------------------

    def drain(self, timeout: float = 10.0) -> bool:
        """Tests: wait until no admission is queued or in flight."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                idle = not self._pending
            if idle and self._q.empty():
                return True
            time.sleep(0.01)
        return False

    def resident(self, bucket: str, obj: str) -> bool:
        with self._mu:
            return (bucket, obj) in self._entries

    def stats(self) -> dict:
        with self._mu:
            st = dict(self._stats)
            st["resident_objects"] = len(self._entries)
            st["pending"] = len(self._pending)
        st["resident_bytes"] = self.arena.used_bytes
        return st

    def close(self, timeout: float = 10.0) -> None:
        self.closed = True
        self._q.put(None)
        self._admit_t.join(timeout)
        with self._mu:
            entries = list(self._entries.values())
            self._entries.clear()
            self._heat.clear()
            self._pending.clear()
            self._readers.clear()
        for e in entries:
            self._release(e)
        self.arena.clear()
        _BYTES.set(0)
