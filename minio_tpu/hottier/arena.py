"""Per-lane device arenas + the hot-GET serve kernel.

A resident object occupies one device array of shape
(rows, k, width) — its payload split on the object's own erasure grid
(block_size blocks, each block split into its k data-shard chunks),
staged exactly like a dataplane ring slot: `rows` is the pow-2 bucket
of the block count and `width` the pow-2 bucket of the chunk length
(utils/shardmath.pow2_bucket — THE rule shared with codec staging and
the lane keys), so the whole tier lives on a bounded *shape set*. That
is what makes the arena behave like `dataplane/ring.py`'s slot rings on
a real accelerator: XLA's device allocator recycles freed same-shape
HBM buffers, and the jit cache for the serve kernel below is bounded
to the same lane keys instead of churning per object size.

Host staging buffers (the admit-time memcpy target for the H2D
transfer) recycle through a per-shape free list — steady-state
admission allocates nothing on the host. The byte budget
(MTPU_HOTTIER_BYTES) is accounted on the device arrays; eviction frees
the arrays (their HBM returns to the allocator's same-shape pool) and
returns the staging buffer to the free list.

Serve kernel: one jitted launch per (rows, k, width, window, verify)
lane — `dynamic_slice` gathers the requested block window out of the
resident array and, with verify on, fuses the window's mxsum digests
into the SAME launch (ops/fused.verify_digests — the digest kernel the
codec and heal lanes already fuse). The host compares those digests to
the admit-time baseline before a single byte reaches the response:
resident bits that rotted in device memory fall back to the drive
path, exactly like on-disk bitrot. Decoding from the k resident data
shards of a systematic RS code is the identity solve, so the "gather"
IS the reconstruct — no GF work is needed until shards are lost, which
is the drive path's job.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

DEFAULT_BUDGET_BYTES = 256 << 20   # device-resident byte budget
_MIN_WIDTH = 512                   # narrowest staged chunk width


def width_bucket(s: int) -> int:
    from minio_tpu.utils.shardmath import pow2_bucket

    return pow2_bucket(s, floor=_MIN_WIDTH)


def rows_bucket(n: int) -> int:
    from minio_tpu.utils.shardmath import pow2_bucket

    return pow2_bucket(max(1, n))


def entry_shape(nblocks: int, k: int, chunk_len: int) -> tuple:
    """The pow2-bucketed arena shape for an object of `nblocks` erasure
    blocks with data-chunk length `chunk_len`."""
    return (rows_bucket(nblocks), k, width_bucket(chunk_len))


def shape_bytes(shape: tuple) -> int:
    r, k, w = shape
    # data + per-block lens (i32) + per-chunk digest baseline (32 B).
    return r * k * w + r * 4 + r * k * 32


class DeviceArena:
    """Budget-bounded device residence accounting + host staging reuse.

    acquire() hands out a zeroed host staging array of the requested
    shape (recycled when possible); seal() device_puts it and charges
    the budget; release() uncharges and recycles the staging buffer.
    All bookkeeping is a leaf lock — no device work happens under it.
    """

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES):
        self.budget = budget_bytes
        self._mu = threading.Lock()
        self._used = 0
        self._free: dict[tuple, list[np.ndarray]] = {}

    @property
    def used_bytes(self) -> int:
        return self._used

    def fits(self, shape: tuple) -> bool:
        with self._mu:
            return self._used + shape_bytes(shape) <= self.budget

    def would_free(self, shapes) -> int:
        return sum(shape_bytes(s) for s in shapes)

    def acquire(self, shape: tuple) -> np.ndarray:
        """A zeroed host staging array (NOT yet charged to the budget —
        seal() charges when the device copy lands)."""
        with self._mu:
            pool = self._free.get(shape)
            buf = pool.pop() if pool else None
        if buf is None:
            return np.zeros(shape, dtype=np.uint8)
        buf[:] = 0
        return buf

    def seal(self, shape: tuple, staging: np.ndarray):
        """Device_put the staged bytes and charge the budget. Returns
        the device array; the staging buffer stays with the caller
        until release() (its reuse contract mirrors ring.Slot: the
        transfer reads straight out of it)."""
        import jax

        dev = jax.device_put(staging)
        with self._mu:
            self._used += shape_bytes(shape)
        return dev

    def recycle_staging(self, shape: tuple, staging: np.ndarray) -> None:
        with self._mu:
            self._free.setdefault(shape, []).append(staging)
            # Bound the per-shape free list: staging reuse is a fast
            # path, not a second cache.
            del self._free[shape][4:]

    def release(self, shape: tuple) -> None:
        with self._mu:
            self._used = max(0, self._used - shape_bytes(shape))

    def clear(self) -> None:
        with self._mu:
            self._used = 0
            self._free.clear()


@functools.lru_cache(maxsize=256)
def serve_kernel(rows: int, k: int, width: int, window: int,
                 verify: bool):
    """The hot-GET launch for one arena lane: gather `window` blocks
    starting at a (traced) row offset out of the resident (rows, k,
    width) array, with the window's mxsum digests fused into the same
    launch when verify is on. Shapes are pow2-bucketed on every axis,
    so the compiled-program set is bounded per lane (probe:
    trace_count())."""
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import fused

    if verify:
        def launch(data, lens, start):
            win = jax.lax.dynamic_slice(data, (start, 0, 0),
                                        (window, k, width))
            wl = jax.lax.dynamic_slice(lens, (start,), (window,))
            digs = fused.verify_digests(win.reshape(window * k, width),
                                        jnp.repeat(wl, k))
            return win, digs.reshape(window, k, 32)
    else:
        def launch(data, lens, start):
            del lens
            return jax.lax.dynamic_slice(data, (start, 0, 0),
                                         (window, k, width)), None
    return jax.jit(launch)


def trace_count() -> int:
    """Compiled serve-program count (recompilation probe for tests)."""
    return serve_kernel.cache_info().currsize
