"""Input readers: CSV and JSON record sources (pkg/s3select/csv, /json).

Each reader yields dict rows. CSV rows also carry positional _1.._N keys
(the dialect used when FileHeaderInfo is NONE/IGNORE); JSON documents
flatten one level of nesting with dotted keys, matching how the reference
addresses nested fields.
"""

from __future__ import annotations

import bz2
import csv
import gzip
import io
import json
from typing import Iterator

from minio_tpu.s3select.sql import SelectError


def decompress(stream: io.BufferedIOBase, kind: str) -> io.BufferedIOBase:
    kind = (kind or "NONE").upper()
    if kind == "NONE":
        return stream
    if kind == "GZIP":
        return gzip.GzipFile(fileobj=stream)
    if kind == "BZIP2":
        return bz2.BZ2File(stream)
    raise SelectError(f"unsupported CompressionType {kind}")


def csv_rows(stream, *, header: str = "USE", delimiter: str = ",",
             quote: str = '"', record_delimiter: str = "\n",
             comments: str = "") -> Iterator[dict]:
    """header: USE (first row names columns) | IGNORE | NONE."""
    header = (header or "USE").upper()
    text = io.TextIOWrapper(stream, encoding="utf-8", newline="")
    reader = csv.reader(text, delimiter=delimiter or ",",
                        quotechar=quote or '"')
    names: list[str] | None = None
    for rec in reader:
        if not rec or (comments and rec[0].startswith(comments)):
            continue
        if names is None and header in ("USE", "IGNORE"):
            names = rec if header == "USE" else []
            if header == "IGNORE":
                names = []
            if header == "USE":
                continue
        row: dict = {}
        for i, v in enumerate(rec):
            row[f"_{i + 1}"] = v
            if names and i < len(names):
                row[names[i]] = v
        yield row


def json_rows(stream, *, json_type: str = "LINES") -> Iterator[dict]:
    """LINES: one JSON value per line; DOCUMENT: a single value (or a
    top-level array, which selects each element)."""
    json_type = (json_type or "LINES").upper()
    if json_type == "LINES":
        text = io.TextIOWrapper(stream, encoding="utf-8")
        for line in text:
            line = line.strip()
            if not line:
                continue
            yield _as_row(_loads(line))
        return
    if json_type == "DOCUMENT":
        raw = stream.read()
        doc = _loads(raw.decode("utf-8") if isinstance(raw, bytes) else raw)
        if isinstance(doc, list):
            for item in doc:
                yield _as_row(item)
        else:
            yield _as_row(doc)
        return
    raise SelectError(f"unsupported JSON Type {json_type}")


def _loads(s: str):
    try:
        return json.loads(s)
    except ValueError as e:
        raise SelectError(f"malformed JSON record: {e}") from None


def _as_row(doc) -> dict:
    if not isinstance(doc, dict):
        return {"_1": doc}
    row: dict = {}
    for k, v in doc.items():
        row[k] = v
        if isinstance(v, dict):
            for k2, v2 in v.items():
                row[f"{k}.{k2}"] = v2
    return row
