"""The select engine: request XML → reader → sql → output events.

Role-equivalent of pkg/s3select/select.go (NewS3Select:541 + Evaluate):
parse the SelectObjectContent request document, stream the object through
the chosen reader, filter/project with the SQL evaluator, and serialize
matching records into the event-stream the handler writes back.
"""

from __future__ import annotations

import csv
import io
import zlib
import json
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from datetime import datetime
from typing import Iterator

from minio_tpu.s3select import eventstream as es
from minio_tpu.s3select import readers
from minio_tpu.s3select.sql import MISSING, Evaluator, SelectError, parse
from minio_tpu.s3select.timestamps import format_sql_timestamp

RECORDS_FLUSH = 128 << 10     # flush a Records event at ~128 KiB


def _strip(tag: str) -> str:
    return tag.split("}")[-1]


def _find(node, *path):
    for name in path:
        nxt = None
        if node is None:
            return None
        for child in node:
            if _strip(child.tag) == name:
                nxt = child
                break
        node = nxt
    return node


def _text(node, *path, default: str = "") -> str:
    n = _find(node, *path)
    return (n.text or "").strip() if n is not None and n.text else default


@dataclass
class S3SelectRequest:
    expression: str
    input_format: str            # CSV | JSON
    output_format: str           # CSV | JSON
    compression: str = "NONE"
    csv_header: str = "USE"
    csv_delimiter: str = ","
    csv_quote: str = '"'
    csv_comments: str = ""
    json_type: str = "LINES"
    out_csv_delimiter: str = ","
    out_record_delimiter: str = "\n"

    @classmethod
    def parse_xml(cls, body: bytes) -> "S3SelectRequest":
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise SelectError("malformed SelectObjectContent XML") from None
        expr = _text(root, "Expression")
        etype = _text(root, "ExpressionType", default="SQL").upper()
        if etype != "SQL" or not expr:
            raise SelectError("ExpressionType must be SQL with an Expression")
        inp = _find(root, "InputSerialization")
        out = _find(root, "OutputSerialization")
        if inp is None or out is None:
            raise SelectError("Input/OutputSerialization required")
        in_parquet = _find(inp, "Parquet")
        in_csv = _find(inp, "CSV")
        in_json = _find(inp, "JSON")
        if in_csv is None and in_json is None and in_parquet is None:
            raise SelectError("input must be CSV, JSON or Parquet")
        out_csv = _find(out, "CSV")
        out_json = _find(out, "JSON")
        return cls(
            expression=expr,
            input_format=("PARQUET" if in_parquet is not None
                          else "CSV" if in_csv is not None else "JSON"),
            output_format="JSON" if out_json is not None else "CSV",
            compression=_text(inp, "CompressionType", default="NONE"),
            csv_header=_text(in_csv, "FileHeaderInfo", default="USE")
            if in_csv is not None else "USE",
            csv_delimiter=_text(in_csv, "FieldDelimiter", default=",")
            if in_csv is not None else ",",
            csv_quote=_text(in_csv, "QuoteCharacter", default='"')
            if in_csv is not None else '"',
            csv_comments=_text(in_csv, "Comments", default="")
            if in_csv is not None else "",
            json_type=_text(in_json, "Type", default="LINES")
            if in_json is not None else "LINES",
            out_csv_delimiter=_text(out_csv, "FieldDelimiter", default=",")
            if out_csv is not None else ",",
            out_record_delimiter=_text(out_csv, "RecordDelimiter",
                                       default="\n")
            if out_csv is not None else "\n",
        )


def _json_default(v):
    if isinstance(v, datetime):
        return format_sql_timestamp(v)
    return str(v)


def _csv_cell(v):
    if v in (None, MISSING):
        return ""
    if isinstance(v, datetime):
        return format_sql_timestamp(v)
    if isinstance(v, (list, dict)):     # JSONPath wildcard results
        return json.dumps(v, default=_json_default)
    return v


def _serialize(row: dict, req: S3SelectRequest, header_order: list[str]) -> str:
    if req.output_format == "JSON":
        # Positional _N keys duplicate named CSV columns — prefer names.
        named = {k: v for k, v in row.items()
                 if not (k.startswith("_") and k[1:].isdigit())}
        use = named if named else row
        clean = {k: (None if v is MISSING else v) for k, v in use.items()}
        return json.dumps(clean, default=_json_default) + "\n"
    buf = io.StringIO()
    w = csv.writer(buf, delimiter=req.out_csv_delimiter,
                   lineterminator=req.out_record_delimiter)
    keys = header_order or list(row)
    w.writerow([_csv_cell(row.get(k)) for k in keys])
    return buf.getvalue()


def run_select(body_stream, request: S3SelectRequest
               ) -> Iterator[bytes]:
    """Evaluate and yield event-stream frames (Records*, Stats, End)."""
    query = parse(request.expression)
    ev = Evaluator(query)

    if request.input_format == "CSV":
        # Vector fast lane: native CSV indexing + columnar WHERE/aggregate
        # evaluation (s3select/vector.py); row-engine-exact or declined.
        from minio_tpu.s3select import vector

        plan = vector.compile_plan(query, request)
        if plan is not None:
            raw = readers.decompress(body_stream, request.compression)
            yield from vector.run_vectorized(plan, raw, request, query)
            return

    if request.input_format == "JSON":
        # JSON-LINES vector lane: native depth-1 key extraction; odd
        # rows re-evaluate through json.loads + the row evaluator.
        from minio_tpu.s3select import vector

        jplan = vector.compile_plan_json(query, request)
        if jplan is not None:
            raw = readers.decompress(body_stream, request.compression)
            yield from vector.run_vectorized_json(jplan, raw, request,
                                                  query)
            return

    if request.input_format == "PARQUET":
        import struct as _struct

        from minio_tpu.s3select.parquet import (
            ParquetError,
            ParquetReader,
            iter_parquet_records,
        )

        # Column-chunk vector lane (vector.py ParquetVectorPlan): masks
        # over decoded columns, row dicts only for surviving rows.
        from minio_tpu.s3select import vector as _vec

        pplan = _vec.compile_plan_parquet(query, request)
        if pplan is not None:
            # Decode inside the malformed-input guard (exactly the scope
            # the row path wraps); EVALUATION errors propagate distinctly.
            try:
                raw_pq = (body_stream.read()
                          if hasattr(body_stream, "read")
                          else bytes(body_stream))
                reader = ParquetReader(raw_pq)
                want = pplan.needed_columns([c.name for c in reader.columns])
                groups = list(reader.iter_column_groups(want))
            except ParquetError as e:
                raise SelectError(f"parquet: {e}") from None
            except (_struct.error, zlib.error, IndexError,
                    KeyError, ValueError, OverflowError, MemoryError) as e:
                raise SelectError(
                    f"parquet: malformed input ({e})") from None
            yield from pplan.run(reader, groups, request, query)
            return

        try:
            rows = iter(list(iter_parquet_records(body_stream)))
        except ParquetError as e:
            raise SelectError(f"parquet: {e}") from None
        except (_struct.error, zlib.error, IndexError,
                KeyError, ValueError, OverflowError, MemoryError) as e:
            # Corrupt/truncated input must die as a clean Select error,
            # not an unhandled 500 mid-stream.
            raise SelectError(f"parquet: malformed input ({e})") from None
    else:
        raw = readers.decompress(body_stream, request.compression)
        if request.input_format == "CSV":
            rows = readers.csv_rows(
                raw, header=request.csv_header,
                delimiter=request.csv_delimiter,
                quote=request.csv_quote, comments=request.csv_comments)
        else:
            rows = readers.json_rows(raw, json_type=request.json_type)

    scanned = 0
    returned = 0
    emitted = 0
    pending = io.BytesIO()

    def flush() -> bytes | None:
        nonlocal returned
        data = pending.getvalue()
        if not data:
            return None
        pending.seek(0)
        pending.truncate()
        returned += len(data)
        return es.records_message(data)

    if ev.is_aggregate:
        for row in rows:
            scanned += 1
            if ev.where_matches(row):
                ev.accumulate(row)
        out_row = ev.project({})
        pending.write(_serialize(out_row, request, list(out_row)).encode())
        msg = flush()
        if msg:
            yield msg
    else:
        header_order: list[str] = []
        for row in rows:
            scanned += 1
            if not ev.where_matches(row):
                continue
            out = ev.project(row)
            if not header_order:
                header_order = [k for k in out
                                if not (k.startswith("_")
                                        and k[1:].isdigit())] or list(out)
            pending.write(_serialize(out, request, header_order).encode())
            emitted += 1
            if pending.tell() >= RECORDS_FLUSH:
                msg = flush()
                if msg:
                    yield msg
            if query.limit is not None and emitted >= query.limit:
                break
        msg = flush()
        if msg:
            yield msg

    yield es.stats_message(scanned, scanned, returned)
    yield es.end_message()
