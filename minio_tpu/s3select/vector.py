"""Vectorized S3 Select execution over the native CSV indexer.

The simdjson-go / csvparser role (SURVEY §2.3) re-designed columnar: the
C++ tokenizer (native/mtpu_native.cc mtpu_csv_index) turns each chunk
into a flat (offset, length) field table, WHERE predicates evaluate as
numpy masks over natively-parsed float columns, and aggregates reduce
whole columns — no per-row dict, no per-row Python eval on the hot path.

Exactness contract: rows whose fields defeat the bulk float parser
(non-numeric strings, exotic spellings) are re-evaluated ROW-WISE with
the ordinary `sql.Evaluator` on the original parsed values, so results
match the row engine bit-for-bit; the vector path is a fast lane for the
common shape, not a second dialect. LIKE and IN predicates are
vectorized (masks over the indexed field table); queries outside the
supported shape (expressions in projections, multi-char delimiters,
comment lines, WHERE nodes _compile_where can't lower) return None from
compile_plan and take the row engine.
"""

from __future__ import annotations

import numpy as np

from minio_tpu.native import lib as nativelib
from minio_tpu.s3select.sql import (
    Binary,
    Col,
    Evaluator,
    Func,
    InList,
    Like,
    Lit,
    Query,
    Unary,
)

CHUNK = 16 << 20


class _Unsupported(Exception):
    pass


# --- predicate tree ----------------------------------------------------------

class _Cmp:
    __slots__ = ("col", "op", "lit", "node")

    def __init__(self, col: str, op: str, lit, node):
        self.col = col
        self.op = op          # one of = <> < <= > >=
        self.lit = lit        # int/float (numeric compare) or str (eq only)
        self.node = node      # original AST node, for exact fallback


class _Bool:
    __slots__ = ("op", "kids")

    def __init__(self, op: str, kids: list):
        self.op = op          # AND | OR | NOT
        self.kids = kids


def _eval_bool_tree(node, n: int, leaf_eval):
    """Shared three-valued (value, known) mask algebra over the compiled
    predicate tree; `leaf_eval(_Cmp) -> (value, known)` supplies the
    comparison masks (CSV and JSON batches differ only there)."""
    if node is None:
        return np.ones(n, bool), np.ones(n, bool)
    if isinstance(node, _Bool):
        if node.op == "LIT_TRUE":
            return np.ones(n, bool), np.ones(n, bool)
        if node.op == "LIT_FALSE":
            return np.zeros(n, bool), np.ones(n, bool)
        if node.op == "NOT":
            v, k = _eval_bool_tree(node.kids[0], n, leaf_eval)
            return ~v, k
        lv, lk = _eval_bool_tree(node.kids[0], n, leaf_eval)
        rv, rk = _eval_bool_tree(node.kids[1], n, leaf_eval)
        if node.op == "AND":
            value = lv & rv
            known = (lk & rk) | (lk & ~lv) | (rk & ~rv)
        else:
            value = lv | rv
            known = (lk & rk) | (lk & lv) | (rk & rv)
        return value & known, known
    return leaf_eval(node)


def _name_candidates(name: str) -> list[str]:
    """Column-name resolution candidates (ONE copy of the rule every
    lane must share with the Evaluator): exact, alias-segment dropped,
    last segment."""
    return ([name] + ([name.split(".", 1)[1], name.rsplit(".", 1)[-1]]
                      if "." in name else []))


_FLOAT_CASTS = {"FLOAT", "DOUBLE", "DECIMAL", "NUMERIC", "REAL"}
_SWAP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>"}


def _as_col(node) -> str | None:
    if isinstance(node, Col) and node.name and node.steps is None:
        return node.name
    if (isinstance(node, Func) and node.name == "CAST"
            and node.cast_type.upper() in _FLOAT_CASTS
            and len(node.args) == 1 and isinstance(node.args[0], Col)
            and node.args[0].name and node.args[0].steps is None):
        # CAST(col AS FLOAT): identical numeric lane; non-numeric fields
        # go to the row fallback, which raises exactly as CAST does.
        return node.args[0].name
    return None


def _bare_col(node) -> str | None:
    """String-compare leaves must anchor on a BARE column: a CAST-wrapped
    column (which _as_col accepts for the numeric lane) carries cast
    semantics — erroring on non-castable values — that a raw byte compare
    would silently bypass."""
    if isinstance(node, Col) and node.name and node.steps is None:
        return node.name
    return None


def _compile_like(node):
    """LIKE 'prefix%' (literal ASCII pattern) -> the like-pfx leaf.
    Anything else — mid-string %, _, ESCAPE, and wildcard-free patterns
    (whose '$'-anchored regex ALSO matches a trailing-newline value, so
    they are not byte equality) — row-falls-back."""
    col = _bare_col(node.e)
    if (col is None or node.escape or not isinstance(node.pattern, Lit)
            or not isinstance(node.pattern.value, str)):
        raise _Unsupported("like shape")
    pat = node.pattern.value
    if not pat.isascii() or "_" in pat:
        raise _Unsupported("like wildcard shape")
    if pat.endswith("%") and "%" not in pat[:-1]:
        leaf = _Cmp(col, "like-pfx", pat[:-1], node)
    else:
        raise _Unsupported("general like pattern")
    return _Bool("NOT", [leaf]) if node.negate else leaf


def _compile_in(node):
    """IN (literals...) -> an OR-chain of the same eq leaves '=' compiles
    to, reusing each lane's equality path; three-valued OR reproduces the
    row engine's NULL propagation."""
    col = _bare_col(node.e)
    if col is None or not node.items:
        raise _Unsupported("in shape")
    kids = []
    for item in node.items:
        if not isinstance(item, Lit):
            raise _Unsupported("non-literal IN item")
        v = item.value
        eq_node = Binary("=", node.e, item)
        if isinstance(v, bool) or v is None:
            raise _Unsupported("bool/null IN item")
        if isinstance(v, (int, float)):
            kids.append(_Cmp(col, "=", v, eq_node))
            continue
        if not isinstance(v, str):
            raise _Unsupported("exotic IN item")
        try:
            float(v)
        except ValueError:
            if v.isascii():
                kids.append(_Cmp(col, "=", v, eq_node))
                continue
        raise _Unsupported("numeric-ish/non-ascii IN string")
    leaf = kids[0]
    for k in kids[1:]:
        leaf = _Bool("OR", [leaf, k])
    return _Bool("NOT", [leaf]) if node.negate else leaf


def _compile_where(node):
    if node is None:
        return None
    if isinstance(node, Like):
        return _compile_like(node)
    if isinstance(node, InList):
        return _compile_in(node)
    if isinstance(node, Binary):
        if node.op in ("AND", "OR"):
            return _Bool(node.op, [_compile_where(node.l),
                                   _compile_where(node.r)])
        if node.op in ("=", "<>", "<", "<=", ">", ">="):
            for l, r, op in ((node.l, node.r, node.op),
                             (node.r, node.l, _SWAP[node.op])):
                col = _as_col(l)
                if col is not None and isinstance(r, Lit):
                    v = r.value
                    if isinstance(v, bool):
                        raise _Unsupported("bool literal")
                    if isinstance(v, (int, float)):
                        return _Cmp(col, op, v, node)
                    if (isinstance(v, str) and op in ("=", "<>")
                            and _bare_col(l) is not None):
                        # Bare column only: CAST(col AS FLOAT) = 'str'
                        # must keep the cast's error semantics (row path).
                        try:
                            float(v)
                        except ValueError:
                            if v.isascii():
                                return _Cmp(col, op, v, node)
                        raise _Unsupported("numeric-ish string literal")
            raise _Unsupported(f"comparison shape {node!r}")
        raise _Unsupported(f"operator {node.op}")
    if isinstance(node, Unary) and node.op == "NOT":
        return _Bool("NOT", [_compile_where(node.e)])
    if isinstance(node, Lit) and isinstance(node.value, bool):
        return _Bool("LIT_TRUE" if node.value else "LIT_FALSE", [])
    raise _Unsupported(f"node {type(node).__name__}")


def compile_plan(query: Query, request) -> "VectorPlan | None":
    """A VectorPlan when (query, request) fits the vector shape, else
    None (row engine)."""
    if not nativelib.csv_index_available():
        return None
    if request.input_format != "CSV":
        return None
    if (request.csv_comments or len(request.csv_delimiter or ",") != 1
            or len(request.csv_quote or '"') != 1
            or (request.csv_header or "USE").upper()
            not in ("USE", "NONE", "IGNORE")):
        return None
    try:
        where = _compile_where(query.where)
    except _Unsupported:
        return None
    if query.aggregates:
        # Every projection must be one of the collected aggregate Funcs.
        for p in query.projections:
            if not (isinstance(p.expr, Func)
                    and p.expr in query.aggregates):
                return None
        for f in query.aggregates:
            if not f.star and not (len(f.args) == 1
                                   and isinstance(f.args[0], Col)
                                   and f.args[0].name
                                   and f.args[0].steps is None):
                return None
    else:
        for p in query.projections:
            if p.expr is None:
                continue
            if not (isinstance(p.expr, Col) and p.expr.name
                    and p.expr.steps is None):
                return None
    return VectorPlan(query, where, request)


# --- execution ---------------------------------------------------------------

class _Batch:
    """One indexed chunk: lazy column materialization.

    Kept rows are addressed through `rfirst` (first-field index per row)
    + `nfields`; BLANK records (one zero-length field — empty lines,
    and the stray records CRLF splitting can produce at chunk seams) are
    filtered out everywhere, exactly as csv.reader skips blank lines in
    the row engine."""

    def __init__(self, data: bytes, plan: "VectorPlan"):
        self.data = data
        delim = (plan.request.csv_delimiter or ",").encode()
        self.quote = (plan.request.csv_quote or '"').encode()
        row_start, self.foff, self.flen = nativelib.csv_index(
            data, delim, self.quote)
        self.rfirst = row_start[:-1]
        self.nfields = row_start[1:] - row_start[:-1]
        blank = (self.nfields == 1) & (self.flen[self.rfirst] == 0)
        if blank.any():
            keep = ~blank
            self.rfirst = self.rfirst[keep]
            self.nfields = self.nfields[keep]
        self.nrows = len(self.rfirst)
        self._floats: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def drop_first_row(self) -> None:
        self.rfirst = self.rfirst[1:]
        self.nfields = self.nfields[1:]
        self.nrows -= 1

    def col_field_idx(self, ci: int) -> tuple[np.ndarray, np.ndarray]:
        """(field table indices, present mask) for column ci."""
        present = self.nfields > ci
        idx = self.rfirst + ci
        return np.where(present, idx, 0), present

    def floats(self, ci: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(values f64, numeric mask, present mask) for column ci."""
        got = self._floats.get(ci)
        if got is None:
            idx, present = self.col_field_idx(ci)
            vals = nativelib.csv_parse_floats(
                self.data, self.foff[idx], self.flen[idx], self.quote)
            ok = ~np.isnan(vals) & present
            got = self._floats[ci] = (vals, ok, present)
        return got

    def field_str(self, ri: int, ci: int) -> str:
        f = self.rfirst[ri] + ci
        off, ln = self.foff[f], self.flen[f]
        raw = self.data[off:off + ln]
        q = self.quote
        if ln >= 2 and raw[:1] == q and raw[-1:] == q:
            raw = raw[1:-1].replace(q + q, q)
        return raw.decode("utf-8", "replace")

    def row_dict(self, ri: int, names: list[str]) -> dict:
        row: dict = {}
        for ci in range(int(self.nfields[ri])):
            v = self.field_str(ri, ci)
            row[f"_{ci + 1}"] = v
            if ci < len(names):
                row[names[ci]] = v
        return row

    def record_bytes(self, ri: int) -> bytes:
        first = self.rfirst[ri]
        last = first + self.nfields[ri] - 1
        return self.data[self.foff[first]:
                         self.foff[last] + self.flen[last]]


class VectorPlan:
    def __init__(self, query: Query, where, request):
        self.query = query
        self.where = where
        self.request = request
        self.names: list[str] = []
        self._col_idx: dict[str, int] = {}
        self._header_done = (request.csv_header or "USE").upper() == "NONE"
        # Field count the row engine's header_order implies for SELECT *
        # output (ragged rows are truncated/padded to it) — set from the
        # header row, or the first data row when there is none.
        self.expected_fields: int | None = None

    # -- column resolution --

    def _ci(self, name: str) -> int | None:
        """Mirror Evaluator's Col resolution: exact name, then with the
        leading table-alias segment dropped, then the last segment."""
        for cand in _name_candidates(name):
            if cand.startswith("_") and cand[1:].isdigit():
                return int(cand[1:]) - 1
            ci = self._col_idx.get(cand)
            if ci is not None:
                return ci
        return None

    # -- predicate evaluation: three-valued (value, known) masks --

    def _eval(self, node, batch: _Batch, ev: Evaluator):
        return _eval_bool_tree(
            node, batch.nrows, lambda c: self._leaf(c, batch, ev))

    def _leaf(self, node, batch: _Batch, ev: Evaluator):
        n = batch.nrows
        ci = self._ci(node.col)
        if ci is None:  # unknown column -> MISSING -> NULL comparison
            return np.zeros(n, bool), np.zeros(n, bool)
        if isinstance(node.lit, str):
            # = / <> / like-pfx against a non-numeric ASCII literal: pure
            # bytes equality (or prefix equality) on the unquoted field
            # (the row engine string-compares exactly this way for
            # non-numeric literals; LIKE 'p%' is a prefix test on str).
            idx, present = batch.col_field_idx(ci)
            lit = node.lit.encode()
            L = len(lit)
            pfx = node.op == "like-pfx"
            eq = np.zeros(n, bool)
            cand = np.nonzero(present)[0]
            offs, lens = batch.foff[idx], batch.flen[idx]
            q = batch.quote[0]
            for ri in cand:
                off, ln = offs[ri], lens[ri]
                raw = batch.data[off:off + ln]
                if ln >= 2 and raw[0] == q and raw[-1] == q:
                    raw = raw[1:-1].replace(batch.quote * 2, batch.quote)
                eq[ri] = raw[:L] == lit if pfx else raw == lit
            value = (~eq & present) if node.op == "<>" else eq
            return value & present, present
        vals, ok, present = batch.floats(ci)
        lit = float(node.lit)
        if node.op == "=":
            value = vals == lit
        elif node.op == "<>":
            value = vals != lit
        elif node.op == "<":
            value = vals < lit
        elif node.op == "<=":
            value = vals <= lit
        elif node.op == ">":
            value = vals > lit
        else:
            value = vals >= lit
        value = value & ok
        known = ok.copy()
        # Exact fallback for present-but-non-numeric fields: evaluate the
        # ORIGINAL AST node row-wise (string/exotic coercion rules).
        odd = np.nonzero(present & ~ok)[0]
        for ri in odd:
            res = ev.eval(node.node, batch.row_dict(int(ri), self.names))
            if res is None:
                continue
            known[ri] = True
            value[ri] = bool(res)
        return value, known

    def match_mask(self, batch: _Batch, ev: Evaluator) -> np.ndarray:
        v, k = self._eval(self.where, batch, ev)
        return v & k

    # -- chunked streaming split on record boundaries --

    def chunks(self, stream):
        carry = b""
        q = (self.request.csv_quote or '"').encode()
        clean = True  # no quote char seen yet (carry included)
        while True:
            buf = stream.read(CHUNK)
            if not buf:
                if carry:
                    yield carry
                return
            data = carry + buf
            # Clean-data fast path: with no quote anywhere, every
            # terminator is a record boundary — skip the quote-parity
            # rescan of the whole chunk (one memchr vs one count pass).
            if clean and q not in buf:
                cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
                if cut < 0:
                    carry = data
                    continue
                yield data[:cut + 1]
                carry = data[cut + 1:]
                continue
            clean = False
            cut = len(data)
            while True:
                # A record terminator is \n, \r or \r\n: split at the
                # last one with even quote parity (an unbalanced quote
                # means it sits inside a quoted field). A CRLF split
                # between \r and \n leaves a blank record at the next
                # chunk's head, which _Batch filters.
                cut = max(data.rfind(b"\n", 0, cut),
                          data.rfind(b"\r", 0, cut))
                if cut < 0:
                    break
                if data.count(q, 0, cut + 1) % 2 == 0:
                    break
            if cut < 0:
                carry = data
                continue
            yield data[:cut + 1]
            carry = data[cut + 1:]

    # -- fused native aggregate lane --------------------------------------

    _FUSED_OPS = {">": 1, ">=": 2, "<": 3, "<=": 4, "=": 5, "<>": 6}

    def fused_agg_shape(self) -> bool:
        """True when the query fits the one-pass native aggregate scan:
        aggregate-only projections and a WHERE that is absent or a single
        numeric comparison. The scan itself still aborts per chunk on any
        data construct whose exact semantics belong to the slow path."""
        if not self.query.aggregates:
            return False
        if self.where is None:
            return True
        return (isinstance(self.where, _Cmp)
                and not isinstance(self.where.lit, str))

    def _bootstrap_header(self, chunk: bytes) -> bool:
        """Resolve column names from the first line WITHOUT building a
        batch (the fused lane never tokenizes). False -> fall back."""
        if self._header_done:
            return True
        # The header is the first NON-blank record (blank records are
        # filtered everywhere, including by the native scan).
        pos = 0
        line = b""
        while pos < len(chunk):
            ends = [i for i in (chunk.find(b"\n", pos),
                                chunk.find(b"\r", pos)) if i >= 0]
            if not ends:
                return False
            end = min(ends)
            line = chunk[pos:end]
            if line:
                break
            pos = end + 1
        if not line:
            return False
        q = (self.request.csv_quote or '"').encode()
        if q in line:
            return False  # quoted header: exact path parses it
        if (self.request.csv_header or "USE").upper() == "USE":
            delim = (self.request.csv_delimiter or ",").encode()
            self.names = [f.decode("utf-8", "replace")
                          for f in line.split(delim)]
            self._col_idx = {nm: i for i, nm in enumerate(self.names)}
        return True

    def try_fused_chunk(self, chunk: bytes, ev: Evaluator) -> int | None:
        """Run the native fused aggregate scan over one chunk and fold the
        results into ev.agg_state exactly as the vector loop would.
        Returns rows scanned, or None -> caller uses the exact path."""
        if not self._bootstrap_header(chunk):
            return None
        if self.where is not None:
            pred_ci = self._ci(self.where.col)
            if pred_ci is None:
                return None  # unknown column: NULL semantics, slow path
            pred_op = self._FUSED_OPS[self.where.op]
            pred_rhs = float(self.where.lit)
        else:
            pred_ci, pred_op, pred_rhs = -1, 0, 0.0
        agg_cols = []
        for f in self.query.aggregates:
            if f.star:
                agg_cols.append(-1)
            else:
                ci = self._ci(f.args[0].name)
                agg_cols.append(-1 if ci is None else ci)
        skip_header = (not self._header_done
                       and (self.request.csv_header or "USE").upper()
                       in ("USE", "IGNORE"))
        res = nativelib.csv_agg_fused(
            chunk, (self.request.csv_delimiter or ",").encode(),
            (self.request.csv_quote or '"').encode(), skip_header,
            pred_ci, pred_op, pred_rhs, agg_cols)
        if res is None:
            return None
        self._header_done = True
        for f, st, agg in zip(self.query.aggregates, ev.agg_state,
                              res["aggs"]):
            if f.star:
                st["count"] += res["matched"]
                continue
            st["count"] += agg["count"]
            if agg["num"]:
                st["sum"] += agg["sum"]
                for fld in (agg["min_field"], agg["max_field"]):
                    nv = _num_py(fld.decode("utf-8", "replace"))
                    if nv is None:
                        continue
                    st["min"] = nv if st["min"] is None else min(st["min"], nv)
                    st["max"] = nv if st["max"] is None else max(st["max"], nv)
        return res["scanned"]

    def consume_header(self, batch: _Batch) -> None:
        """Resolve column names from the first row of the first batch."""
        hdr = (self.request.csv_header or "USE").upper()
        if self._header_done:
            return
        if batch.nrows and hdr == "USE":
            self.names = [batch.field_str(0, ci)
                          for ci in range(int(batch.nfields[0]))]
            self._col_idx = {nm: i for i, nm in enumerate(self.names)}
        if batch.nrows:
            batch.drop_first_row()
            self._header_done = True


def _num_py(v):
    from minio_tpu.s3select import sql as _sql

    return _sql._num(v)


# --- JSON-lines plan ---------------------------------------------------------

def compile_plan_json(query: Query, request) -> "JSONVectorPlan | None":
    """Vector plan for JSON LINES input (native depth-1 key extraction;
    simdjson role). Same query-shape gate as the CSV plan."""
    if not nativelib.csv_index_available():
        return None
    if request.input_format != "JSON" or (
            request.json_type or "LINES").upper() != "LINES":
        return None
    try:
        where = _compile_where(query.where)
    except _Unsupported:
        return None
    cols: set[str] = set()

    def _collect(nd):
        if isinstance(nd, _Cmp):
            cols.add(nd.col)
        elif isinstance(nd, _Bool):
            for k in nd.kids:
                _collect(k)

    _collect(where)
    if query.aggregates:
        for p in query.projections:
            if not (isinstance(p.expr, Func) and p.expr in query.aggregates):
                return None
        for f in query.aggregates:
            if not f.star:
                if not (len(f.args) == 1 and isinstance(f.args[0], Col)
                        and f.args[0].name
                        and f.args[0].steps is None):
                    return None
                cols.add(f.args[0].name)
    else:
        for p in query.projections:
            if p.expr is None:
                continue
            if not (isinstance(p.expr, Col) and p.expr.name
                    and p.expr.steps is None):
                return None
    return JSONVectorPlan(query, where, request)


def _key_candidates(name: str) -> list[bytes]:
    """Candidate top-level JSON keys, in the evaluator's resolution order
    (exact name, alias-stripped, last segment)."""
    cands = [name]
    if "." in name:
        cands += [name.split(".", 1)[1], name.rsplit(".", 1)[-1]]
    out, seen = [], set()
    for c in cands:
        if c not in seen:
            seen.add(c)
            out.append(c.encode())
    return out


class _JsonBatch:
    """One chunk of JSON lines, with lazy per-column extraction."""

    def __init__(self, data: bytes):
        self.data = data
        # Zero-length key matches nothing: gives the line table + the
        # structural python-fallback flags shared by every column.
        lo, ll, _vo, _vl, kind = nativelib.jsonl_extract(data, b"")
        self.line_off = lo
        self.line_len = ll
        self.pyrow = kind == -2
        self.nrows = len(lo)
        self._cols: dict[str, tuple] = {}
        self._parsed: dict[int, dict] = {}

    def col(self, name: str):
        """(kind i8, val_off, val_len) with candidate keys merged in the
        evaluator's resolution order. kind -3 marks rows that must go to
        the row evaluator because a DOTTED column may address a NESTED
        field the depth-1 extractor cannot see (_as_row flattens one
        level, e.g. {"s": {"price": 1}} answers to "s.price")."""
        got = self._cols.get(name)
        if got is None:
            kinds = voff = vlen = None
            cands = _key_candidates(name)
            for key in cands:
                _lo, _ll, vo, vl, k = nativelib.jsonl_extract(self.data, key)
                if kinds is None:
                    kinds, voff, vlen = k.copy(), vo.copy(), vl.copy()
                else:
                    take = (kinds == 0) & (k != 0)
                    kinds[take] = k[take]
                    voff[take] = vo[take]
                    vlen[take] = vl[take]
            if "." in name:
                # Chunk-level probe: if any dotted candidate's FIRST
                # segment appears as a key anywhere in the chunk,
                # flattening could produce the column — and the flattened
                # (exact-name) value SHADOWS top-level candidate matches
                # in the evaluator's order, so EVERY row of the chunk
                # must re-check row-wise, not just the misses.
                needles = {c.decode().split(".", 1)[0]
                           for c in cands if b"." in c}
                if any(f'"{seg}"'.encode() in self.data
                       for seg in needles):
                    kinds = np.full_like(kinds, -3)
            got = self._cols[name] = (kinds, voff, vlen)
        return got

    def floats(self, name: str):
        """(vals f64, numeric-ok mask, kinds) — numbers + numeric strings
        parsed natively, booleans as 1/0."""
        kinds, voff, vlen = self.col(name)
        vals = nativelib.csv_parse_floats(self.data, voff, vlen)
        ok = ~np.isnan(vals) & ((kinds == 1) | (kinds == 2))
        vals = vals.copy()
        vals[kinds == 3] = 1.0
        vals[kinds == 4] = 0.0
        ok = ok | (kinds == 3) | (kinds == 4)
        return vals, ok, kinds

    def value_text(self, ri: int, name: str) -> str:
        kinds, voff, vlen = self.col(name)
        return self.data[voff[ri]:voff[ri] + vlen[ri]].decode(
            "utf-8", "replace")

    def row_dict(self, ri: int) -> dict:
        row = self._parsed.get(ri)
        if row is None:
            from minio_tpu.s3select.readers import _as_row, _loads

            line = self.data[self.line_off[ri]:
                             self.line_off[ri] + self.line_len[ri]]
            row = self._parsed[ri] = _as_row(_loads(line.decode("utf-8")))
        return row


class JSONVectorPlan:
    def __init__(self, query: Query, where, request):
        self.query = query
        self.where = where
        self.request = request

    def chunks(self, stream):
        carry = b""
        while True:
            buf = stream.read(CHUNK)
            if not buf:
                if carry:
                    yield carry
                return
            data = carry + buf
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            yield data[:cut + 1]
            carry = data[cut + 1:]

    def _eval(self, node, batch: _JsonBatch, ev: Evaluator):
        return _eval_bool_tree(
            node, batch.nrows, lambda c: self._leaf(c, batch, ev))

    def _leaf(self, node, batch: _JsonBatch, ev: Evaluator):
        n = batch.nrows
        kinds, voff, vlen = batch.col(node.col)
        value = np.zeros(n, bool)
        known = np.zeros(n, bool)
        if isinstance(node.lit, str):
            # Vector lane: real JSON strings, byte-compared (escape-free
            # by construction); like-pfx is a bytes prefix test.
            # Everything else odd -> row fallback.
            lit = node.lit.encode()
            L = len(lit)
            pfx = node.op == "like-pfx"
            svals = kinds == 2
            for ri in np.nonzero(svals & ~batch.pyrow)[0]:
                raw = batch.data[voff[ri]:voff[ri] + vlen[ri]]
                eq = raw[:L] == lit if pfx else raw == lit
                value[ri] = (not eq) if node.op == "<>" else eq
                known[ri] = True
            odd = (~svals & (kinds != 0) & (kinds != 5)) | batch.pyrow
        else:
            vals, ok, _k = self.floats_cache(batch, node.col)
            lit = float(node.lit)
            if node.op == "=":
                value = vals == lit
            elif node.op == "<>":
                value = vals != lit
            elif node.op == "<":
                value = vals < lit
            elif node.op == "<=":
                value = vals <= lit
            elif node.op == ">":
                value = vals > lit
            else:
                value = vals >= lit
            value = value & ok & ~batch.pyrow
            known = ok & ~batch.pyrow
            # non-numeric strings, complex values, possible-nested rows,
            # pyrows: exact fallback
            odd = (((kinds == 1) | (kinds == 2)) & ~ok) | (kinds == -1) \
                | (kinds == -3) | batch.pyrow
        for ri in np.nonzero(odd)[0]:
            res = ev.eval(node.node, batch.row_dict(int(ri)))
            if res is None:
                continue
            known[ri] = True
            value[ri] = bool(res)
        return value, known

    def floats_cache(self, batch: _JsonBatch, name: str):
        key = f"__f_{name}"
        got = batch._cols.get(key)
        if got is None:
            got = batch._cols[key] = batch.floats(name)
        return got

    def match_mask(self, batch: _JsonBatch, ev: Evaluator) -> np.ndarray:
        v, k = self._eval(self.where, batch, ev)
        return v & k


def run_vectorized_json(plan: JSONVectorPlan, raw_stream, request,
                        query: Query):
    """JSON-LINES twin of run_vectorized: same frames, same exactness
    contract (odd rows re-evaluated through json.loads + the row
    evaluator)."""
    import io

    from minio_tpu.s3select import eventstream as es
    from minio_tpu.s3select.engine import RECORDS_FLUSH, _serialize

    ev = Evaluator(query)
    scanned = 0
    returned = 0
    emitted = 0
    pending = io.BytesIO()

    def flush():
        nonlocal returned
        data = pending.getvalue()
        if not data:
            return None
        pending.seek(0)
        pending.truncate()
        returned += len(data)
        return es.records_message(data)

    header_order: list[str] = []
    done = False
    for chunk in plan.chunks(raw_stream):
        if done:
            break
        batch = _JsonBatch(chunk)
        if batch.nrows == 0:
            continue
        scanned += batch.nrows
        mask = plan.match_mask(batch, ev)

        if ev.is_aggregate:
            for f, st in zip(query.aggregates, ev.agg_state):
                if f.star:
                    st["count"] += int(mask.sum())
                    continue
                name = f.args[0].name
                vals, ok, kinds = plan.floats_cache(batch, name)
                fb = batch.pyrow | (kinds == -3)
                sel = mask & ~fb
                # count: any non-null, non-missing value
                present = sel & (kinds != 0) & (kinds != 5)
                st["count"] += int(present.sum())
                num = sel & ok
                cands: list[tuple[int, object]] = []
                if num.any():
                    s = vals[num]
                    st["sum"] += float(s.sum())
                    rows_idx = np.nonzero(num)[0]
                    for pos in (int(np.argmin(s)), int(np.argmax(s))):
                        ri = int(rows_idx[pos])
                        k = int(kinds[ri])
                        n_exact = (1 if k == 3 else 0 if k == 4
                                   else _num_py(batch.value_text(ri, name)))
                        cands.append((ri, n_exact))
                # python-fallback rows contribute through the evaluator
                for ri in np.nonzero(mask & fb)[0]:
                    row = batch.row_dict(int(ri))
                    v = ev.eval(f.args[0], row)
                    from minio_tpu.s3select.sql import MISSING
                    if v is None or v is MISSING:
                        continue
                    st["count"] += 1
                    n_exact = _num_py(v)
                    if n_exact is not None:
                        st["sum"] += n_exact
                        cands.append((int(ri), n_exact))
                for _ri, nv in sorted(cands, key=lambda c: c[0]):
                    if nv is None:
                        continue
                    st["min"] = nv if st["min"] is None else min(st["min"], nv)
                    st["max"] = nv if st["max"] is None else max(st["max"], nv)
            continue

        for ri in np.nonzero(mask)[0]:
            ri = int(ri)
            out = ev.project(batch.row_dict(ri))
            if not header_order:
                header_order = [k for k in out
                                if not (k.startswith("_")
                                        and k[1:].isdigit())] or list(out)
            pending.write(_serialize(out, request, header_order).encode())
            emitted += 1
            if pending.tell() >= RECORDS_FLUSH:
                msg = flush()
                if msg:
                    yield msg
            if query.limit is not None and emitted >= query.limit:
                scanned -= batch.nrows - (ri + 1)
                done = True
                break

    if ev.is_aggregate:
        out_row = ev.project({})
        pending.write(_serialize(out_row, request, list(out_row)).encode())
    msg = flush()
    if msg:
        yield msg
    yield es.stats_message(scanned, scanned, returned)
    yield es.end_message()


def run_vectorized(plan: VectorPlan, raw_stream, request,
                   query: Query):
    """Evaluate the plan over the (decompressed) stream, yielding the same
    event-stream frames run_select's row loop produces."""
    import io

    from minio_tpu.s3select import eventstream as es
    from minio_tpu.s3select.engine import RECORDS_FLUSH, _serialize

    ev = Evaluator(query)
    scanned = 0
    returned = 0
    emitted = 0
    pending = io.BytesIO()

    def flush():
        nonlocal returned
        data = pending.getvalue()
        if not data:
            return None
        pending.seek(0)
        pending.truncate()
        returned += len(data)
        return es.records_message(data)

    select_star = all(p.expr is None for p in query.projections)
    raw_ok = (not query.aggregates and select_star
              and request.output_format == "CSV"
              and request.out_csv_delimiter == (request.csv_delimiter or ",")
              and request.out_record_delimiter == "\n")
    header_order: list[str] = []
    done = False

    fused_ok = ev.is_aggregate and plan.fused_agg_shape()
    for chunk in plan.chunks(raw_stream):
        if done:
            break
        if fused_ok:
            # Native one-pass lane: predicate + aggregates with no field
            # table at all; per-chunk exact fallback on any odd construct.
            got = plan.try_fused_chunk(chunk, ev)
            if got is not None:
                scanned += got
                continue
        batch = _Batch(chunk, plan)
        plan.consume_header(batch)
        if batch.nrows == 0:
            continue
        scanned += batch.nrows
        mask = plan.match_mask(batch, ev)

        if ev.is_aggregate:
            for f, st in zip(query.aggregates, ev.agg_state):
                if f.star:
                    st["count"] += int(mask.sum())
                    continue
                ci = plan._ci(f.args[0].name)
                if ci is None:
                    continue  # column MISSING everywhere
                vals, ok, present = batch.floats(ci)
                sel = mask & present
                st["count"] += int(sel.sum())
                num = sel & ok
                # min/max candidates re-read through _num so Python
                # number types (int vs float) match the row engine's
                # serialization exactly; merged with the exotic-row
                # fallbacks IN ROW ORDER so tie-breaking matches too.
                cands: list[tuple[int, object]] = []
                if num.any():
                    s = vals[num]
                    st["sum"] += float(s.sum())
                    rows_idx = np.nonzero(num)[0]
                    for pos in (int(np.argmin(s)), int(np.argmax(s))):
                        ri = int(rows_idx[pos])
                        cands.append((ri, _num_py(batch.field_str(ri, ci))))
                for ri in np.nonzero(sel & ~ok)[0]:
                    n = _num_py(batch.field_str(int(ri), ci))
                    if n is not None:
                        st["sum"] += n
                        cands.append((int(ri), n))
                for _ri, n in sorted(cands, key=lambda c: c[0]):
                    if n is None:
                        continue
                    st["min"] = n if st["min"] is None else min(st["min"], n)
                    st["max"] = n if st["max"] is None else max(st["max"], n)
            continue

        q = batch.quote[0]
        for ri in np.nonzero(mask)[0]:
            ri = int(ri)
            rec = None
            if raw_ok and header_order:
                # Raw pass-through only for rows shaped exactly like the
                # row engine's header_order (it truncates/pads ragged
                # rows) and free of quoting/CR re-encoding concerns.
                if int(batch.nfields[ri]) == plan.expected_fields:
                    rb = batch.record_bytes(ri)
                    if q not in rb and b"\r" not in rb:
                        rec = rb
            if rec is not None:
                pending.write(rec + b"\n")
            else:
                row = batch.row_dict(ri, plan.names)
                out = ev.project(row)
                if not header_order:
                    header_order = [k for k in out
                                    if not (k.startswith("_")
                                            and k[1:].isdigit())] \
                        or list(out)
                    plan.expected_fields = len(header_order)
                pending.write(
                    _serialize(out, request, header_order).encode())
            emitted += 1
            if pending.tell() >= RECORDS_FLUSH:
                msg = flush()
                if msg:
                    yield msg
            if query.limit is not None and emitted >= query.limit:
                # Mirror the row engine's stats: it stops pulling rows at
                # the limit-th match, so rows after it are never scanned.
                scanned -= batch.nrows - (ri + 1)
                done = True
                break

    if ev.is_aggregate:
        out_row = ev.project({})
        pending.write(_serialize(out_row, request, list(out_row)).encode())
    msg = flush()
    if msg:
        yield msg
    yield es.stats_message(scanned, scanned, returned)
    yield es.end_message()


# --- Parquet column-chunk lane ----------------------------------------------

def compile_plan_parquet(query: Query, request) -> "ParquetVectorPlan | None":
    """Column-chunk evaluation for Parquet (the vector lane's third input
    format): WHERE evaluates as masks over the decoded column chunks and
    row dicts materialize ONLY for surviving rows; aggregates accumulate
    sequentially in row order over typed values — the row engine's exact
    arithmetic, minus its per-row dict builds and AST walks."""
    if request.input_format != "PARQUET":
        return None
    try:
        where = _compile_where(query.where)
    except _Unsupported:
        return None
    if query.aggregates:
        for p in query.projections:
            if not (isinstance(p.expr, Func) and p.expr in query.aggregates):
                return None
        for f in query.aggregates:
            if not f.star and not (len(f.args) == 1
                                   and isinstance(f.args[0], Col)
                                   and f.args[0].name
                                   and f.args[0].steps is None):
                return None
    else:
        for p in query.projections:
            if p.expr is None:
                continue
            if not (isinstance(p.expr, Col) and p.expr.name
                    and p.expr.steps is None):
                return None
    return ParquetVectorPlan(query, where, request)


_TWO53 = 1 << 53


class _PqCol:
    """One column chunk classified for vector evaluation: float64 values
    where exact, with present/numeric masks and the indices of rows whose
    values need exact row-wise handling (big ints, exotic types)."""

    __slots__ = ("vals", "numeric", "present", "odd")

    def __init__(self, raw):
        from minio_tpu.s3select.parquet import DecodedColumn

        n = len(raw)
        if isinstance(raw, DecodedColumn) and raw.np_vals is not None \
                and raw.np_vals.dtype.kind in "iufb":
            # Typed chunk from the native/numpy decoder: classify without
            # touching a single Python object. Bool chunks stay exact via
            # the row path (odd), matching the slow loop's behavior.
            arr = raw.np_vals
            present = (raw.np_present.copy() if raw.np_present is not None
                       else np.ones(n, bool))
            self.present = present
            if arr.dtype.kind == "b":
                self.vals = np.zeros(n, np.float64)
                self.numeric = np.zeros(n, bool)
                self.odd = np.nonzero(present)[0].tolist()
                return
            self.vals = arr.astype(np.float64)
            if arr.dtype.kind == "i" and arr.dtype.itemsize == 8:
                big = (arr > _TWO53) | (arr < -_TWO53)
                self.numeric = present & ~big
                self.odd = np.nonzero(present & big)[0].tolist()
            else:
                self.numeric = present.copy()
                self.odd = []
            self.vals[~self.numeric] = 0.0
            return
        self.vals = np.zeros(n, np.float64)
        self.numeric = np.zeros(n, bool)
        self.present = np.zeros(n, bool)
        odd = []
        for i, v in enumerate(raw):
            if v is None:
                continue
            self.present[i] = True
            t = type(v)
            if t is float:
                self.vals[i] = v
                self.numeric[i] = True
            elif t is int:
                if -_TWO53 <= v <= _TWO53:
                    self.vals[i] = v
                    self.numeric[i] = True
                else:
                    odd.append(i)  # exact big-int semantics: row-wise
            else:
                # bool / str / anything exotic: the row engine's coercion
                # rules decide (e.g. numeric strings under CAST) — never
                # guess in the fast lane.
                odd.append(i)
        self.odd = odd


class ParquetVectorPlan:
    def __init__(self, query: Query, where, request):
        self.query = query
        self.where = where
        self.request = request
        self._names: list[str] = []

    def _colname(self, name: str, data: dict) -> str | None:
        for cand in _name_candidates(name):
            if cand in data:
                return cand
        return None

    def needed_columns(self, file_cols: list) -> "set[str] | None":
        """Projection pushdown: the file columns this plan can possibly
        touch (WHERE leaves + aggregate args + projected columns), or
        None for no pruning (SELECT *). Row-dict fallbacks only ever
        evaluate nodes over these same columns, so pruned chunks are
        never consulted."""
        qcols: set[str] = set()
        for p in self.query.projections:
            if p.expr is None:
                return None
            if isinstance(p.expr, Col):
                qcols.add(p.expr.name)
        for f in self.query.aggregates:
            if not f.star:
                qcols.add(f.args[0].name)

        def walk(nd):
            if isinstance(nd, _Cmp):
                qcols.add(nd.col)
            elif isinstance(nd, _Bool):
                for k in nd.kids:
                    walk(k)

        walk(self.where)
        want: set[str] = set()
        for fc in file_cols:
            for qn in qcols:
                if fc in _name_candidates(qn):
                    want.add(fc)
        return want

    def _leaf(self, node, cols: dict, raw: dict, n: int, ev: Evaluator,
              row_of):
        cn = self._colname(node.col, raw)
        if cn is None:
            return np.zeros(n, bool), np.zeros(n, bool)
        if isinstance(node.lit, str):
            vals = raw[cn]
            from minio_tpu.s3select.parquet import DecodedColumn

            if isinstance(vals, DecodedColumn):
                # Lazy byte-array chunk: bytes-level compare (equality or
                # LIKE-prefix), zero str construction (ASCII pages only —
                # the matcher refuses anything needing per-value utf8 /
                # coercion semantics).
                fast = vals.match_literal(node.lit,
                                          prefix=node.op == "like-pfx")
                if fast is not None:
                    eq, present = fast
                    value = (~eq & present) if node.op == "<>" else eq
                    return value & present, present.copy()
            if node.op == "like-pfx":
                eq = np.fromiter(
                    (isinstance(v, str) and v.startswith(node.lit)
                     for v in vals), bool, n)
            else:
                eq = np.fromiter((isinstance(v, str) and v == node.lit
                                  for v in vals), bool, n)
            present = np.fromiter((v is not None for v in vals), bool, n)
            value = (~eq & present) if node.op == "<>" else eq
            value = value & present
            known = present.copy()
            # Present non-str values (bools, numbers): the row engine's
            # coercion rules decide — evaluate those rows exactly.
            for ri, v in enumerate(vals):
                if v is not None and not isinstance(v, str):
                    res = ev.eval(node.node, row_of(ri))
                    known[ri] = res is not None
                    value[ri] = bool(res) if res is not None else False
            return value, known
        c = cols.setdefault(cn, _PqCol(raw[cn]))
        lit = float(node.lit)
        ops = {"=": np.equal, "<>": np.not_equal, "<": np.less,
               "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}
        value = ops[node.op](c.vals, lit) & c.numeric
        known = c.numeric.copy()
        for ri in c.odd:  # exact row-wise semantics for exotic values
            res = ev.eval(node.node, row_of(ri))
            if res is not None:
                known[ri] = True
                value[ri] = bool(res)
        return value, known

    def _accumulate_fast(self, ev, data, mask) -> bool:
        """Chunk-level aggregate accumulation, bit-identical to the row
        engine or refused (False -> caller row-loops):
        - SUM chains through np.cumsum seeded with the running state —
          numpy's cumsum is the sequential left-to-right float addition,
          exactly the row loop's rounding;
        - MIN/MAX keep the column's own type (int chunks yield Python
          ints, so serialization matches the row engine);
        - refused outright for NaN floats, ints beyond 2^53 (exact
          big-int semantics), bool/string/exotic chunks (COUNT over any
          chunk is still fast — presence needs no values)."""
        from minio_tpu.s3select.parquet import DecodedColumn

        updates = []
        for f, st in zip(self.query.aggregates, ev.agg_state):
            if f.star:
                updates.append((st, None))
                continue
            cn = self._colname(f.args[0].name, data)
            if cn is None:
                updates.append((st, "missing"))
                continue
            chunk = data[cn]
            if not isinstance(chunk, DecodedColumn):
                return False
            if chunk.np_vals is None or chunk.np_vals.dtype.kind not in "if":
                # Untyped (string/exotic) chunk: only COUNT is safe —
                # presence is knowable without materializing values.
                if f.name != "COUNT":
                    return False
                pres = (mask if chunk.np_present is None
                        else mask & chunk.np_present)
                if chunk.np_vals is None and chunk._ba is None \
                        and chunk._list is not None:
                    # Plain list chunk: presence means value is not None.
                    lst = chunk._list
                    cnt = sum(1 for ri in np.nonzero(mask)[0].tolist()
                              if lst[ri] is not None)
                    updates.append((st, ("count", cnt)))
                else:
                    updates.append((st, ("count", int(pres.sum()))))
                continue
            arr = chunk.np_vals
            pres = (mask if chunk.np_present is None
                    else mask & chunk.np_present)
            masked = arr[pres]
            if arr.dtype.kind == "f":
                if masked.size and np.isnan(masked).any():
                    return False
            elif arr.dtype.itemsize == 8 and masked.size and \
                    ((masked > _TWO53) | (masked < -_TWO53)).any():
                return False
            updates.append((st, ("vals", masked)))
        # Validated: apply (two-phase so a refusal never half-updates).
        for st, upd in updates:
            if upd is None:
                st["count"] += int(mask.sum())
            elif upd == "missing":
                continue
            elif upd[0] == "count":
                st["count"] += upd[1]
            else:
                masked = upd[1]
                c = int(masked.size)
                if not c:
                    continue
                st["count"] += c
                seq = np.cumsum(np.concatenate((
                    np.asarray([st["sum"]], np.float64),
                    masked.astype(np.float64))))
                st["sum"] = float(seq[-1])
                mn, mx = masked.min(), masked.max()
                if masked.dtype.kind == "i":
                    mn, mx = int(mn), int(mx)
                else:
                    mn, mx = float(mn), float(mx)
                st["min"] = mn if st["min"] is None else min(st["min"], mn)
                st["max"] = mx if st["max"] is None else max(st["max"], mx)
        return True

    def run(self, reader, groups, request, query) -> "Iterator[bytes]":
        import io as _io

        from minio_tpu.s3select import eventstream as es
        from minio_tpu.s3select.engine import RECORDS_FLUSH, _serialize

        ev = Evaluator(query)
        scanned = 0
        returned = 0
        emitted = 0
        pending = _io.BytesIO()
        header_order: list[str] = []
        done = False

        def flush():
            nonlocal returned
            data = pending.getvalue()
            if not data:
                return None
            pending.seek(0)
            pending.truncate()
            returned += len(data)
            return es.records_message(data)

        for n_rows, data in groups:
            if done:
                break
            if n_rows == 0:
                continue
            scanned += n_rows
            cols: dict[str, _PqCol] = {}
            row_of = lambda ri: reader.row_dict(data, n_rows, ri)  # noqa: E731
            v, k = _eval_bool_tree(
                self.where, n_rows,
                lambda nd: self._leaf(nd, cols, data, n_rows, ev, row_of))
            mask = v & k
            if ev.is_aggregate:
                # Vectorized accumulation when provably bit-identical to
                # the row engine (typed chunks, no NaN, no >2^53 ints:
                # np.cumsum IS the sequential float chain); otherwise the
                # exact row-by-row path.
                if not self._accumulate_fast(ev, data, mask):
                    for ri in np.nonzero(mask)[0]:
                        ev.accumulate(row_of(int(ri)))
                continue
            for ri in np.nonzero(mask)[0]:
                out = ev.project(row_of(int(ri)))
                if not header_order:
                    header_order = [kk for kk in out
                                    if not (kk.startswith("_")
                                            and kk[1:].isdigit())] or list(out)
                pending.write(_serialize(out, request, header_order).encode())
                emitted += 1
                if pending.tell() >= RECORDS_FLUSH:
                    msg = flush()
                    if msg:
                        yield msg
                if query.limit is not None and emitted >= query.limit:
                    scanned -= n_rows - (int(ri) + 1)
                    done = True
                    break
        if ev.is_aggregate:
            out_row = ev.project({})
            pending.write(_serialize(out_row, request, list(out_row)).encode())
        msg = flush()
        if msg:
            yield msg
        yield es.stats_message(scanned, scanned, returned)
        yield es.end_message()
