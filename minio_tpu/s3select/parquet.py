"""Pure-Python Parquet reader for S3 Select.

Role-equivalent of pkg/s3select's Parquet input (the reference vendors a
full parquet-go, ~22k LoC with codegen); this build implements the format
directly from the Apache Parquet spec — no Arrow, no SDK:

  - Thrift Compact Protocol decoding (the footer/page-header wire format)
  - flat schemas: BOOLEAN, INT32, INT64, FLOAT, DOUBLE, BYTE_ARRAY
    (+ UTF8/DECIMAL-free converted types treated as their physical type)
  - encodings: PLAIN, PLAIN_DICTIONARY / RLE_DICTIONARY,
    RLE/bit-packed hybrid definition levels (optional columns -> NULLs)
  - data pages V1 and V2; codecs UNCOMPRESSED, SNAPPY (pure-Python
    decompressor below), GZIP

Rows come out as ordered dicts feeding the same SQL engine the CSV/JSON
readers use. Validated against the reference's own public parquet test
fixtures (pkg/s3select/testdata.parquet).
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Iterator


class ParquetError(Exception):
    pass


# ---------------------------------------------------------------------------
# snappy (raw block format) — pure-Python decompressor
# ---------------------------------------------------------------------------


def snappy_decompress(data: bytes) -> bytes:
    """Raw snappy block decompress (the framing-free format parquet uses).
    The native codec (native/mtpu_native.cc, same block format) does the
    byte crunching when available; the pure-Python path remains the
    no-toolchain fallback."""
    try:
        from minio_tpu.native.lib import snappy_available, snappy_uncompress

        if snappy_available():
            try:
                # Page sizes are bounded by the column chunk; cap at 1 GiB
                # against a corrupt length header.
                return snappy_uncompress(data, max_len=1 << 30)
            except ValueError as e:
                raise ParquetError(f"snappy: {e}") from None
    except ImportError:
        pass
    pos = 0
    # uncompressed length varint
    shift = out_len = 0
    while True:
        if pos >= len(data):
            raise ParquetError("snappy: truncated length")
        b = data[pos]
        pos += 1
        out_len |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nbytes = ln - 59
                ln = int.from_bytes(data[pos:pos + nbytes], "little")
                pos += nbytes
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ParquetError("snappy: bad copy offset")
        for _ in range(ln):  # overlapping copies are the point — byte-wise
            out.append(out[-off])
    if len(out) != out_len:
        raise ParquetError(f"snappy: length {len(out)} != {out_len}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Thrift Compact Protocol
# ---------------------------------------------------------------------------

_CT_STOP, _CT_TRUE, _CT_FALSE = 0, 1, 2
_CT_BYTE, _CT_I16, _CT_I32, _CT_I64 = 3, 4, 5, 6
_CT_DOUBLE, _CT_BINARY, _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = 7, 8, 9, 10, 11, 12


class _Thrift:
    """Generic compact-protocol reader: structs decode to
    {field_id: value} dicts; callers pick fields by id per parquet.thrift."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.b = buf
        self.pos = pos

    def _u8(self) -> int:
        v = self.b[self.pos]
        self.pos += 1
        return v

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self._u8()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        n = self.varint()
        return (n >> 1) ^ -(n & 1)

    def read_value(self, ctype: int):
        if ctype in (_CT_TRUE, _CT_FALSE):
            return ctype == _CT_TRUE
        if ctype == _CT_BYTE:
            return self.zigzag()
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self.zigzag()
        if ctype == _CT_DOUBLE:
            v = struct.unpack("<d", self.b[self.pos:self.pos + 8])[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self.varint()
            v = self.b[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype in (_CT_LIST, _CT_SET):
            head = self._u8()
            size = head >> 4
            etype = head & 0x0F
            if size == 15:
                size = self.varint()
            return [self.read_value(etype) for _ in range(size)]
        if ctype == _CT_MAP:
            size = self.varint()
            if size == 0:
                return {}
            kv = self._u8()
            kt, vt = kv >> 4, kv & 0x0F
            return {self.read_value(kt): self.read_value(vt)
                    for _ in range(size)}
        if ctype == _CT_STRUCT:
            return self.read_struct()
        raise ParquetError(f"thrift: unknown compact type {ctype}")

    def read_struct(self) -> dict:
        out: dict[int, object] = {}
        fid = 0
        while True:
            head = self._u8()
            if head == _CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            if delta:
                fid += delta
            else:
                fid = self.zigzag()
            # booleans carry their value in the type nibble
            out[fid] = self.read_value(ctype)


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (definition levels + dictionary indices)
# ---------------------------------------------------------------------------


def _unpack_bit_run(raw: bytes, bit_width: int, n_vals: int) -> list[int]:
    """Vectorized little-endian bit-packed decode (the former big-int
    shift loop was O(n^2): each value shifted a run-sized integer)."""
    import numpy as np

    if bit_width <= 0:
        # A 1-entry dictionary legally uses bit-width 0: every index is 0.
        return [0] * n_vals
    bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8),
                         bitorder="little")
    need = n_vals * bit_width
    if len(bits) < need:  # truncated run: zero-fill (the old big-int
        bits = np.pad(bits, (0, need - len(bits)))  # behavior)
    if bit_width == 1:
        return bits[:n_vals].tolist()
    vals = bits[: n_vals * bit_width].reshape(-1, bit_width).astype(np.int64)
    weights = (1 << np.arange(bit_width, dtype=np.int64))
    return (vals @ weights).tolist()


def _rle_bp_hybrid(buf: bytes, pos: int, end: int, bit_width: int,
                   count: int) -> list[int]:
    out: list[int] = []
    byte_width = (bit_width + 7) // 8
    t = _Thrift(buf, pos)
    while len(out) < count and t.pos < end:
        header = t.varint()
        if header & 1:  # bit-packed run: header>>1 groups of 8
            n_groups = header >> 1
            n_vals = min(n_groups * 8, count - len(out))
            raw = buf[t.pos:t.pos + n_groups * bit_width]
            t.pos += n_groups * bit_width
            out.extend(_unpack_bit_run(raw, bit_width, n_vals))
        else:  # RLE run
            n = header >> 1
            v = int.from_bytes(buf[t.pos:t.pos + byte_width], "little") \
                if byte_width else 0
            t.pos += byte_width
            out.extend([v] * min(n, count - len(out)))
    if len(out) < count:
        out.extend([0] * (count - len(out)))
    return out[:count]


# ---------------------------------------------------------------------------
# column data decoding
# ---------------------------------------------------------------------------

_T_BOOLEAN, _T_INT32, _T_INT64, _T_INT96 = 0, 1, 2, 3
_T_FLOAT, _T_DOUBLE, _T_BYTE_ARRAY, _T_FIXED = 4, 5, 6, 7

_ENC_PLAIN, _ENC_PLAIN_DICT, _ENC_RLE = 0, 2, 3
_ENC_RLE_DICT = 8


def _decode_plain(buf: bytes, ptype: int, count: int,
                  type_length: int = 0) -> list:
    out: list = []
    pos = 0
    if ptype == _T_BOOLEAN:
        for i in range(count):
            out.append(bool((buf[i // 8] >> (i % 8)) & 1))
        return out
    if ptype == _T_INT32:
        return list(struct.unpack_from(f"<{count}i", buf, 0))
    if ptype == _T_INT64:
        return list(struct.unpack_from(f"<{count}q", buf, 0))
    if ptype == _T_FLOAT:
        return list(struct.unpack_from(f"<{count}f", buf, 0))
    if ptype == _T_DOUBLE:
        return list(struct.unpack_from(f"<{count}d", buf, 0))
    if ptype == _T_BYTE_ARRAY:
        for _ in range(count):
            n = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
            out.append(buf[pos:pos + n])
            pos += n
        return out
    if ptype == _T_FIXED:
        for _ in range(count):
            out.append(buf[pos:pos + type_length])
            pos += type_length
        return out
    if ptype == _T_INT96:  # legacy timestamps: surface raw bytes
        for _ in range(count):
            out.append(buf[pos:pos + 12])
            pos += 12
        return out
    raise ParquetError(f"unsupported physical type {ptype}")


_NP_DTYPES = {_T_INT32: "<i4", _T_INT64: "<i8",
              _T_FLOAT: "<f4", _T_DOUBLE: "<f8"}


def _rle_bp_np(buf: bytes, pos: int, end: int, bit_width: int, count: int):
    """RLE/bit-packed hybrid decode to a uint32 array — native kernel when
    the .so is present, the Python decoder otherwise."""
    import numpy as np

    nlib = _native_pq()
    if nlib is not None:
        try:
            return nlib.pq_rle_bp(buf[pos:end], bit_width, count)
        except ValueError as e:
            raise ParquetError(str(e)) from None
        except OSError:
            pass
    return np.asarray(_rle_bp_hybrid(buf, pos, end, bit_width, count),
                      dtype=np.uint32)


def _def_levels_np(buf: bytes, pos: int, end: int, n: int):
    """Definition levels (flat schema: bit width 1) as a bool mask."""
    return _rle_bp_np(buf, pos, end, 1, n).astype(bool)


def _decode_plain_typed(data: bytes, pos: int, ptype: int, count: int,
                        type_length: int = 0):
    """PLAIN decode into the typed form: ("np", ndarray) for fixed-width
    numerics and booleans (zero boxing — .tolist() at materialization
    yields exactly the Python values struct.unpack produced), ("list",
    values) for byte-arrays and legacy types. BYTE_ARRAY offsets scan runs
    in the native kernel when available."""
    import numpy as np

    dt = _NP_DTYPES.get(ptype)
    if dt is not None:
        itemsize = int(dt[-1])
        if len(data) - pos < count * itemsize:
            raise ParquetError("PLAIN page truncated")
        return "np", np.frombuffer(data, dtype=dt, count=count, offset=pos)
    if ptype == _T_BOOLEAN:
        if (len(data) - pos) * 8 < count:
            raise ParquetError("PLAIN boolean page truncated")
        nlib = _native_pq()
        if nlib is not None:
            try:
                return "np", nlib.pq_unpack_bools(data[pos:], count)
            except OSError:
                pass
        return "np", np.asarray(
            _decode_plain(data[pos:], ptype, count), dtype=bool)
    if ptype == _T_BYTE_ARRAY:
        nlib = _native_pq()
        if nlib is not None:
            try:
                starts, lens = nlib.pq_plain_byte_array(data[pos:], count)
            except ValueError as e:
                raise ParquetError(str(e)) from None
            except OSError:
                return "list", _decode_plain(data[pos:], ptype, count)
            # Offsets validated; str construction deferred to first touch.
            return "ba", (data, pos, starts, lens)
        return "list", [
            (v.decode("utf-8") if _is_utf8(v) else v)
            for v in _decode_plain(data[pos:], ptype, count)]
    # FIXED / INT96 / exotica: the exact per-value loop.
    return "list", _decode_plain(data[pos:], ptype, count, type_length)


def _ba_to_list(ba) -> list:
    """Materialize a lazy byte-array piece through DecodedColumn's one
    decode loop (dictionaries are small and gathered immediately, so
    laziness buys nothing there)."""
    n = len(ba[2])
    return DecodedColumn(n, ba=ba)._materialize()


def _is_utf8(b: bytes) -> bool:
    try:
        b.decode("utf-8")
        return True
    except UnicodeDecodeError:
        return False


def _decompress(codec: int, data: bytes, uncompressed_size: int) -> bytes:
    if codec == 0:
        return data
    if codec == 1:
        return snappy_decompress(data)
    if codec == 2:
        return zlib.decompress(data, 16 + zlib.MAX_WBITS)  # gzip framing
    raise ParquetError(f"unsupported codec {codec} "
                       "(UNCOMPRESSED/SNAPPY/GZIP implemented)")


class DecodedColumn:
    """One column chunk's values in two forms: a numpy fast form
    (np_vals/np_present) the vector Select lane consumes without boxing a
    single value, and a lazily materialized exact Python list — the row
    engine's shape; .tolist() yields the same Python ints/floats/bools the
    old struct.unpack loops did, so engine semantics are unchanged."""

    __slots__ = ("n", "np_vals", "np_present", "_list", "_ba")

    def __init__(self, n: int, np_vals=None, np_present=None, values=None,
                 ba=None):
        self.n = n
        self.np_vals = np_vals        # dense typed ndarray (len n) or None
        self.np_present = np_present  # bool ndarray; None == all present
        self._list = values           # prebuilt exact list or None
        self._ba = ba                 # (page, base, starts, lens): LAZY
        # byte-array form — str objects only build if the query actually
        # touches this column (offsets were validated at decode time, so
        # corrupt pages still fail inside the engine's malformed guard).

    def _materialize(self) -> list:
        if self._list is None:
            import numpy as np

            if self._ba is not None:
                page, base, starts, lens = self._ba
                ext = None
                nlib = _native_pq()
                if nlib is not None:
                    ext = nlib.pyext()
                if ext is not None:
                    # One C loop building the str list (utf-8 decode with
                    # bytes fallback — convert()'s exact contract).
                    vals = ext.pq_strs(page, base, starts, lens)
                else:
                    vals = []
                    ap = vals.append
                    for s, ln in zip(starts.tolist(), lens.tolist()):
                        b = page[base + s: base + s + ln]
                        try:
                            ap(b.decode("utf-8"))
                        except UnicodeDecodeError:
                            ap(b)
                if self.np_present is not None:
                    out: list = [None] * self.n
                    for i, v in zip(
                            np.nonzero(self.np_present)[0].tolist(), vals):
                        out[i] = v
                    vals = out
                self._list = vals
                self._ba = None
            else:
                lst = self.np_vals.tolist()
                if self.np_present is not None:
                    for i in np.nonzero(~self.np_present)[0].tolist():
                        lst[i] = None
                self._list = lst
        return self._list

    def match_literal(self, lit: str, prefix: bool = False):
        """Bytes-level string match against a literal without building one
        str object: (hit_mask, present_mask) over rows, or None when the
        fast compare can't be trusted (already materialized, no lazy page,
        or non-ASCII bytes present — non-ASCII needs per-value utf8
        validation to preserve the row engine's bytes-vs-str coercion, so
        those pages take the exact path). prefix=True implements
        LIKE 'lit%' (value startswith)."""
        if self._ba is None or self._list is not None:
            return None
        import numpy as np

        page, base, starts, lens = self._ba
        arr = np.frombuffer(page, np.uint8, offset=base)
        # One allocation-free reduction answers the common all-ASCII case
        # (a masked any() would materialize a page-sized temp).
        if arr.size and int(arr.max()) >= 0x80:
            high = arr & 0x80
            # High bytes exist somewhere. They may be legal: the 4-byte
            # length prefixes carry >=0x80 for any value 128-255 chars
            # long. Only then pay the precise per-value range check
            # (cumsum of high-bit counts; value windows exclude the
            # prefixes). The common all-ASCII page skips all of this.
            hb = np.cumsum(high.astype(np.int64))
            s = starts.astype(np.int64)
            e = s + lens.astype(np.int64) - 1
            nonempty = lens > 0
            if nonempty.any():
                hi = hb[e[nonempty]]
                lo = np.where(s[nonempty] > 0, hb[s[nonempty] - 1], 0)
                if (hi - lo).any():
                    return None
        present = (self.np_present if self.np_present is not None
                   else np.ones(self.n, bool))
        hit = np.zeros(self.n, bool)
        try:
            enc = lit.encode("ascii")
        except UnicodeEncodeError:
            # ASCII page can never match a non-ASCII literal.
            return hit, present
        rows = np.nonzero(present)[0]
        L = len(enc)
        cand = np.nonzero(lens >= L if prefix else lens == L)[0]
        if L and cand.size:
            # Cheap first/last-byte prefilter before the window gather:
            # two scalar-compare passes usually drop most candidates, so
            # the fancy-indexed matrix compare touches a fraction of the
            # page.
            st = starts[cand].astype(np.int64)
            keep = (arr[st] == enc[0]) & (arr[st + (L - 1)] == enc[-1])
            cand = cand[keep]
            if cand.size:
                idx = (starts[cand].astype(np.int64)[:, None]
                       + np.arange(L, dtype=np.int64)[None, :])
                win = arr[idx]
                ok = (win == np.frombuffer(enc, np.uint8)[None, :]
                      ).all(axis=1)
                hit[rows[cand[ok]]] = True
        elif not L:
            hit[rows[cand]] = True  # empty literal: eq empty / any prefix
        return hit, present

    def __len__(self) -> int:
        return self.n

    def __bool__(self) -> bool:
        return self.n > 0

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())


def _native_pq():
    """The native decode kernels, or None (pure-Python fallbacks keep the
    reader correct on hosts without the .so)."""
    try:
        from minio_tpu.native import lib as nlib

        if nlib.available():
            return nlib
    except Exception:  # noqa: BLE001
        pass
    return None


class _Column:
    def __init__(self, name: str, ptype: int, type_length: int,
                 optional: bool, utf8: bool):
        self.name = name
        self.ptype = ptype
        self.type_length = type_length
        self.optional = optional
        self.utf8 = utf8

    def convert(self, v):
        if v is None:
            return None
        if self.ptype == _T_BYTE_ARRAY:
            # Old writers omit the UTF8 converted-type on string columns
            # (the reference fixture does); SQL needs str, so decode
            # best-effort and keep raw bytes only for true binary.
            try:
                return v.decode("utf-8")
            except UnicodeDecodeError:
                return v
        return v


class ParquetReader:
    """Reads a whole parquet object (footer-directed, column by column)."""

    def __init__(self, raw: bytes):
        if len(raw) < 12 or raw[:4] != b"PAR1" or raw[-4:] != b"PAR1":
            raise ParquetError("not a parquet file (PAR1 magic missing)")
        self.raw = raw
        flen = int.from_bytes(raw[-8:-4], "little")
        if flen <= 0 or flen > len(raw) - 8:
            raise ParquetError(f"corrupt footer length {flen}")
        meta = _Thrift(raw, len(raw) - 8 - flen).read_struct()
        self.num_rows = meta.get(3, 0)
        self.columns = self._schema(meta.get(2, []))
        self.row_groups = meta.get(4, [])

    def _schema(self, elements: list) -> list[_Column]:
        cols: list[_Column] = []
        # elements[0] is the root; flat schemas only (children of root).
        for el in elements[1:]:
            if el.get(5):  # num_children -> nested group: unsupported
                raise ParquetError("nested parquet schemas not supported")
            name = el.get(4, b"").decode()
            cols.append(_Column(
                name=name,
                ptype=el.get(1, -1),
                type_length=el.get(2, 0),
                optional=el.get(3, 0) == 1,   # OPTIONAL
                utf8=el.get(6, None) == 0,    # ConvertedType UTF8
            ))
        return cols

    def _read_column_chunk(self, col: _Column, cc_meta: dict) -> DecodedColumn:
        import numpy as np

        codec = cc_meta.get(4, 0)
        num_values = cc_meta.get(5, 0)
        start = cc_meta.get(11, None)           # dictionary_page_offset
        if start is None:
            start = cc_meta.get(9, 0)           # data_page_offset
        pos = start
        pieces: list[DecodedColumn] = []
        got = 0
        dictionary = None                       # ('np', arr) | ('list', vals)
        while got < num_values:
            t = _Thrift(self.raw, pos)
            header = t.read_struct()
            page_type = header.get(1, 0)
            comp_size = header.get(3, 0)
            unc_size = header.get(2, 0)
            body = self.raw[t.pos:t.pos + comp_size]
            pos = t.pos + comp_size
            if page_type == 2:                  # DICTIONARY_PAGE
                dph = header.get(7, {})
                n = dph.get(1, 0)
                data = _decompress(codec, body, unc_size)
                dictionary = _decode_plain_typed(data, 0, col.ptype, n,
                                                 col.type_length)
                if dictionary[0] == "ba":
                    dictionary = ("list", _ba_to_list(dictionary[1]))
                continue
            if page_type == 0:                  # DATA_PAGE v1
                dph = header.get(5, {})
                n = dph.get(1, 0)
                enc = dph.get(2, 0)
                data = _decompress(codec, body, unc_size)
                pieces.append(self._decode_data_page(
                    col, data, n, enc, dictionary, v2_def=None))
                got += n
                continue
            if page_type == 3:                  # DATA_PAGE v2
                dph = header.get(8, {})
                n = dph.get(1, 0)
                enc = dph.get(4, 0)
                def_len = dph.get(5, 0)
                rep_len = dph.get(6, 0)
                compressed = dph.get(7, True)
                levels = body[:rep_len + def_len]
                payload = body[rep_len + def_len:]
                if compressed:
                    payload = _decompress(codec, payload,
                                          unc_size - rep_len - def_len)
                defs = (_def_levels_np(levels, rep_len, rep_len + def_len, n)
                        if col.optional and def_len else None)
                pieces.append(self._decode_data_page(
                    col, payload, n, enc, dictionary, v2_def=defs))
                got += n
                continue
            # index/unknown pages: skip
        if len(pieces) == 1 and pieces[0].n >= num_values:
            c = pieces[0]
            if c.n == num_values:
                return c
            return DecodedColumn(num_values, values=list(
                c._materialize()[:num_values]))
        # Multi-page chunk: concatenate, preferring the numpy form when
        # every page produced one of the same dtype.
        np_ok = pieces and all(
            p.np_vals is not None for p in pieces) and len(
            {p.np_vals.dtype for p in pieces}) == 1
        if np_ok:
            vals = np.concatenate([p.np_vals for p in pieces])[:num_values]
            if any(p.np_present is not None for p in pieces):
                present = np.concatenate([
                    p.np_present if p.np_present is not None
                    else np.ones(p.n, bool) for p in pieces])[:num_values]
            else:
                present = None
            return DecodedColumn(num_values, np_vals=vals,
                                 np_present=present)
        flat: list = []
        for p in pieces:
            flat.extend(p._materialize())
        return DecodedColumn(num_values, values=flat[:num_values])

    def _decode_data_page(self, col: _Column, data: bytes, n: int, enc: int,
                          dictionary, v2_def) -> DecodedColumn:
        import numpy as np

        pos = 0
        if v2_def is not None:
            defs = v2_def
        elif col.optional:
            # v1: def levels length-prefixed RLE (bit width 1 for flat)
            dlen = int.from_bytes(data[pos:pos + 4], "little")
            defs = _def_levels_np(data, pos + 4, pos + 4 + dlen, n)
            pos += 4 + dlen
        else:
            defs = None
        present = int(defs.sum()) if defs is not None else n
        if enc in (_ENC_PLAIN_DICT, _ENC_RLE_DICT):
            if dictionary is None:
                raise ParquetError("dictionary-encoded page with no dictionary")
            bit_width = data[pos]
            idx = _rle_bp_np(data, pos + 1, len(data), bit_width, present)
            kind, dvals = dictionary
            if kind == "np":
                if present and idx.max(initial=0) >= len(dvals):
                    raise IndexError("dictionary index out of range")
                piece = ("np", dvals[idx])
            else:
                piece = ("list", [dvals[i] for i in idx.tolist()])
        elif enc == _ENC_PLAIN:
            piece = _decode_plain_typed(data, pos, col.ptype, present,
                                        col.type_length)
        elif enc == _ENC_RLE and col.ptype == _T_BOOLEAN:
            piece = ("np",
                     _rle_bp_np(data, pos + 4, len(data), 1,
                                present).astype(bool))
        else:
            raise ParquetError(f"unsupported encoding {enc}")
        kind, vals = piece
        if defs is None:
            if kind == "np":
                return DecodedColumn(n, np_vals=vals)
            if kind == "ba":
                return DecodedColumn(n, ba=vals)
            return DecodedColumn(n, values=vals)
        # Scatter values into the null skeleton at the defined positions.
        if kind == "ba":
            # Native scan decoded exactly `present` offsets (or raised).
            return DecodedColumn(n, np_present=defs, ba=vals)
        if len(vals) < present:
            # Truncated page: fabricating NULLs for data that exists
            # would silently corrupt SELECT results.
            raise ParquetError(
                f"page has {len(vals)} values for {present} defined rows")
        if kind == "np":
            dense = np.zeros(n, dtype=vals.dtype)
            dense[defs] = vals[:present]
            return DecodedColumn(n, np_vals=dense, np_present=defs)
        out: list = [None] * n
        for i, v in zip(np.nonzero(defs)[0].tolist(), vals):
            out[i] = v
        return DecodedColumn(n, values=out)

    def iter_column_groups(self, want: "set[str] | None" = None
                           ) -> Iterator[tuple[int, dict[str, list]]]:
        """Yield (n_rows, {column: decoded values}) per row group — the
        COLUMN-CHUNK form the vectorized Select lane consumes directly
        (row dicts are only materialized for rows that survive WHERE).
        want: decode only these columns (projection pushdown — a COUNT
        over one predicate column must not pay for the other chunks)."""
        for rg in self.row_groups:
            chunks = rg.get(1, [])
            data: dict[str, list] = {}
            n_rows = rg.get(3, 0)
            for cc in chunks:
                md = cc.get(3, {})
                path = [p.decode() for p in md.get(3, [])]
                name = path[0] if path else ""
                if want is not None and name not in want:
                    continue
                col = next((c for c in self.columns if c.name == name), None)
                if col is None:
                    continue
                data[name] = self._read_column_chunk(col, md)
            yield n_rows, data

    def row_dict(self, data: dict[str, list], n_rows: int, i: int) -> dict:
        return {c.name: (data.get(c.name) or [None] * n_rows)[i]
                for c in self.columns}

    def iter_rows(self) -> Iterator[dict]:
        """Yield rows as {column: value} dicts (the SQL engine's shape)."""
        for n_rows, data in self.iter_column_groups():
            for i in range(n_rows):
                yield self.row_dict(data, n_rows, i)


def iter_parquet_records(stream) -> Iterator[dict]:
    """S3 Select entry: read the (buffered) object and yield row dicts.
    Parquet is footer-directed, so the input must be fully materialized —
    matching the reference, which also requires seekable parquet input."""
    raw = stream.read() if hasattr(stream, "read") else bytes(stream)
    yield from ParquetReader(raw).iter_rows()


# ---------------------------------------------------------------------------
# minimal writer — PLAIN v1 pages, one row group (test vectors + export)
# ---------------------------------------------------------------------------


class _TWrite:
    """Thrift Compact Protocol writer (the footer/page-header format)."""

    def __init__(self):
        self.out = bytearray()

    def varint(self, n: int) -> None:
        while True:
            b = n & 0x7F
            n >>= 7
            self.out.append(b | (0x80 if n else 0))
            if not n:
                return

    def zigzag(self, n: int) -> None:
        self.varint((n << 1) ^ (n >> 63) if n < 0 else n << 1)

    def field(self, last_id: int, fid: int, ctype: int) -> None:
        delta = fid - last_id
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self.zigzag(fid)

    def struct(self, fields: list[tuple[int, str, object]]) -> None:
        """fields: sorted [(id, kind, value)]; kind in
        i32|i64|bool|binary|list_struct|list_i32|list_binary|struct."""
        last = 0
        for fid, kind, val in fields:
            if kind == "bool":
                self.field(last, fid, _CT_TRUE if val else _CT_FALSE)
            elif kind in ("i32", "i64"):
                self.field(last, fid, _CT_I32 if kind == "i32" else _CT_I64)
                self.zigzag(val)
            elif kind == "binary":
                self.field(last, fid, _CT_BINARY)
                data = val.encode() if isinstance(val, str) else val
                self.varint(len(data))
                self.out += data
            elif kind == "struct":
                self.field(last, fid, _CT_STRUCT)
                self.struct(val)
            elif kind.startswith("list_"):
                self.field(last, fid, _CT_LIST)
                etype = {"list_struct": _CT_STRUCT, "list_i32": _CT_I32,
                         "list_binary": _CT_BINARY}[kind]
                n = len(val)
                if n < 15:
                    self.out.append((n << 4) | etype)
                else:
                    self.out.append((15 << 4) | etype)
                    self.varint(n)
                for item in val:
                    if etype == _CT_STRUCT:
                        self.struct(item)
                    elif etype == _CT_I32:
                        self.zigzag(item)
                    else:
                        data = (item.encode()
                                if isinstance(item, str) else item)
                        self.varint(len(data))
                        self.out += data
            else:
                raise ParquetError(f"writer: unknown kind {kind}")
            last = fid
        self.out.append(_CT_STOP)


_WRITE_TYPES = {"int32": _T_INT32, "int64": _T_INT64, "double": _T_DOUBLE,
                "boolean": _T_BOOLEAN, "string": _T_BYTE_ARRAY,
                "binary": _T_BYTE_ARRAY}


def _plain_encode(ptype: int, vals: list) -> bytes:
    if ptype == _T_BOOLEAN:
        out = bytearray((len(vals) + 7) // 8)
        for i, v in enumerate(vals):
            if v:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)
    if ptype == _T_INT32:
        return struct.pack(f"<{len(vals)}i", *vals)
    if ptype == _T_INT64:
        return struct.pack(f"<{len(vals)}q", *vals)
    if ptype == _T_DOUBLE:
        return struct.pack(f"<{len(vals)}d", *vals)
    out = bytearray()
    for v in vals:
        data = v.encode() if isinstance(v, str) else v
        out += len(data).to_bytes(4, "little") + data
    return bytes(out)


def _def_levels(present: list[bool]) -> bytes:
    """Length-prefixed RLE/bit-packed hybrid, bit width 1."""
    n_groups = (len(present) + 7) // 8
    packed = bytearray(n_groups)
    for i, p in enumerate(present):
        if p:
            packed[i // 8] |= 1 << (i % 8)
    w = _TWrite()
    w.varint((n_groups << 1) | 1)   # bit-packed run header
    body = bytes(w.out) + bytes(packed)
    return len(body).to_bytes(4, "little") + body


def write_parquet(rows: list[dict], schema: list[tuple[str, str]],
                  codec: str = "UNCOMPRESSED") -> bytes:
    """rows -> a single-row-group parquet file. schema: [(name, type)] with
    type in int32|int64|double|boolean|string|binary; None values become
    NULLs (all columns OPTIONAL). codec: UNCOMPRESSED | GZIP."""
    codec_id = {"UNCOMPRESSED": 0, "GZIP": 2}[codec.upper()]
    out = bytearray(b"PAR1")
    col_metas = []
    for name, tname in schema:
        ptype = _WRITE_TYPES[tname]
        col_vals = [r.get(name) for r in rows]
        present = [v is not None for v in col_vals]
        payload = _def_levels(present) + _plain_encode(
            ptype, [v for v in col_vals if v is not None])
        unc_size = len(payload)
        body = payload
        if codec_id == 2:  # gzip framing
            c = zlib.compressobj(9, zlib.DEFLATED, 16 + zlib.MAX_WBITS)
            body = c.compress(payload) + c.flush()
        hdr = _TWrite()
        hdr.struct([
            (1, "i32", 0),                       # DATA_PAGE
            (2, "i32", unc_size),
            (3, "i32", len(body)),
            (5, "struct", [(1, "i32", len(rows)),
                           (2, "i32", _ENC_PLAIN),
                           (3, "i32", _ENC_RLE),
                           (4, "i32", _ENC_RLE)]),
        ])
        offset = len(out)
        out += bytes(hdr.out) + body
        col_metas.append((name, ptype, offset,
                          len(bytes(hdr.out)) + len(body), unc_size))
    # footer
    schema_elems = [[(4, "binary", "schema"), (5, "i32", len(schema))]]
    for name, tname in schema:
        schema_elems.append([
            (1, "i32", _WRITE_TYPES[tname]),
            (3, "i32", 1),                       # OPTIONAL
            (4, "binary", name),
        ] + ([(6, "i32", 0)] if tname == "string" else []))
    chunks = []
    for name, ptype, offset, total, unc in col_metas:
        chunks.append([
            (2, "i64", offset),
            (3, "struct", [
                (1, "i32", ptype),
                (2, "list_i32", [_ENC_PLAIN, _ENC_RLE]),
                (3, "list_binary", [name]),
                (4, "i32", codec_id),
                (5, "i64", len(rows)),
                (6, "i64", unc),
                (7, "i64", total),
                (9, "i64", offset),
            ]),
        ])
    row_group = [(1, "list_struct", chunks),
                 (2, "i64", sum(c[3] for c in col_metas)),
                 (3, "i64", len(rows))]
    footer = _TWrite()
    footer.struct([
        (1, "i32", 1),
        (2, "list_struct", schema_elems),
        (3, "i64", len(rows)),
        (4, "list_struct", [row_group]),
    ])
    fbytes = bytes(footer.out)
    out += fbytes
    out += len(fbytes).to_bytes(4, "little") + b"PAR1"
    return bytes(out)
