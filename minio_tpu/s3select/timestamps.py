"""S3 Select timestamp values (pkg/s3select/sql/timestampfuncs.go role).

The dialect's timestamp literal grammar is a fixed ladder of layouts
(year → nanosecond, reference timestampfuncs.go:23-40); values parse to
timezone-aware datetimes and format back to the *shortest* layout that
preserves the value (FormatSQLTimestamp, timestampfuncs.go:52-77).
EXTRACT / DATE_ADD / DATE_DIFF mirror the reference's part semantics,
including Go's truncating integer division for timezone parts and the
calendar-normalising AddDate overflow behavior.

Beyond the reference: TO_TIMESTAMP / TO_STRING actually evaluate here
(funceval.go:140-142 leaves them errNotImplemented); TO_STRING uses the
Ion-style pattern tokens AWS documents for S3 Select.
"""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone

from minio_tpu.s3select.sql import SelectError, _aware as _as_aware

_UTC = timezone.utc

# One regex per reference layout, tried in the reference's order.
_ZONE = r"(?P<zone>Z|[+-]\d{2}:\d{2})"
_LAYOUTS = [
    re.compile(r"^(?P<y>\d{4})T$"),
    re.compile(r"^(?P<y>\d{4})-(?P<mo>\d{2})T$"),
    re.compile(r"^(?P<y>\d{4})-(?P<mo>\d{2})-(?P<d>\d{2})T$"),
    re.compile(r"^(?P<y>\d{4})-(?P<mo>\d{2})-(?P<d>\d{2})T"
               r"(?P<h>\d{2}):(?P<mi>\d{2})" + _ZONE + "$"),
    re.compile(r"^(?P<y>\d{4})-(?P<mo>\d{2})-(?P<d>\d{2})T"
               r"(?P<h>\d{2}):(?P<mi>\d{2}):(?P<s>\d{2})" + _ZONE + "$"),
    re.compile(r"^(?P<y>\d{4})-(?P<mo>\d{2})-(?P<d>\d{2})T"
               r"(?P<h>\d{2}):(?P<mi>\d{2}):(?P<s>\d{2})"
               r"\.(?P<frac>\d{1,9})" + _ZONE + "$"),
]


def _parse_zone(z: str | None) -> timezone:
    if not z or z == "Z":
        return _UTC
    sign = -1 if z[0] == "-" else 1
    hh, mm = int(z[1:3]), int(z[4:6])
    return timezone(sign * timedelta(hours=hh, minutes=mm))


def parse_sql_timestamp(s: str) -> datetime | None:
    """The reference's parseSQLTimestamp ladder; None when no layout fits."""
    for rx in _LAYOUTS:
        m = rx.match(s)
        if not m:
            continue
        g = m.groupdict()
        frac = g.get("frac") or ""
        # Go keeps nanoseconds; datetime holds microseconds. Truncate —
        # sub-microsecond digits are beyond what we can represent.
        micro = int((frac + "000000")[:6]) if frac else 0
        try:
            return datetime(int(g["y"]), int(g.get("mo") or 1),
                            int(g.get("d") or 1), int(g.get("h") or 0),
                            int(g.get("mi") or 0), int(g.get("s") or 0),
                            micro, _parse_zone(g.get("zone")))
        except ValueError:
            return None
    return None


def _zone_suffix(dt: datetime) -> str:
    off = dt.utcoffset() or timedelta(0)
    if not off:
        return "Z"
    total = int(off.total_seconds())
    sign = "+" if total >= 0 else "-"
    total = abs(total)
    return f"{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"


def format_sql_timestamp(dt: datetime) -> str:
    """Shortest-layout display (FormatSQLTimestamp,
    timestampfuncs.go:52-77)."""
    off = dt.utcoffset()
    has_zone = off is not None and off != timedelta(0)
    has_frac = dt.microsecond != 0
    has_second = dt.second != 0
    has_time = dt.hour != 0 or dt.minute != 0
    base = f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}T"
    if has_frac:
        frac = f"{dt.microsecond:06d}".rstrip("0")
        return (base + f"{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}"
                f".{frac}" + _zone_suffix(dt))
    if has_second:
        return (base + f"{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}"
                + _zone_suffix(dt))
    if has_time or has_zone:
        return base + f"{dt.hour:02d}:{dt.minute:02d}" + _zone_suffix(dt)
    if dt.day != 1:
        return base
    if dt.month != 1:
        return f"{dt.year:04d}-{dt.month:02d}T"
    return f"{dt.year:04d}T"


def _trunc_div(a: int, b: int) -> int:
    """Go's integer division truncates toward zero; Python's floors."""
    q = abs(a) // b
    return -q if a < 0 else q


def extract_part(part: str, dt: datetime):
    """EXTRACT(part FROM ts) — timestampfuncs.go:91-115."""
    if part == "YEAR":
        return dt.year
    if part == "MONTH":
        return dt.month
    if part == "DAY":
        return dt.day
    if part == "HOUR":
        return dt.hour
    if part == "MINUTE":
        return dt.minute
    if part == "SECOND":
        return dt.second
    off = int((dt.utcoffset() or timedelta(0)).total_seconds())
    if part == "TIMEZONE_HOUR":
        return _trunc_div(off, 3600)
    if part == "TIMEZONE_MINUTE":
        return _trunc_div(off - _trunc_div(off, 3600) * 3600, 60)
    raise SelectError(f"EXTRACT: unknown time part {part}")


def date_add(part: str, qty: float, dt: datetime) -> datetime:
    """DATE_ADD — timestampfuncs.go:117-135.  YEAR/MONTH/DAY follow Go's
    AddDate: month overflow normalises forward (Jan 31 + 1 MONTH →
    Mar 2/3), it does not clamp."""
    try:
        n = int(qty)  # Go truncates the quantity to an integer count
        if part == "YEAR":
            return _add_date(dt, n, 0, 0)
        if part == "MONTH":
            return _add_date(dt, 0, n, 0)
        if part == "DAY":
            return _add_date(dt, 0, 0, n)
        if part == "HOUR":
            return dt + timedelta(hours=n)
        if part == "MINUTE":
            return dt + timedelta(minutes=n)
        if part == "SECOND":
            return dt + timedelta(seconds=n)
    except (ValueError, OverflowError):
        # datetime's range is years 1–9999 (and qty may be inf/nan);
        # anything past it must die as a clean Select error, not an
        # unhandled 500 mid-stream.
        raise SelectError(
            f"DATE_ADD result out of range ({part} {qty})") from None
    raise SelectError(f"DATE_ADD: unknown time part {part}")


def _add_date(dt: datetime, years: int, months: int, days: int) -> datetime:
    """Go time.AddDate: add to the calendar fields, then normalise
    overflow forward (day 31 in a 30-day month spills into the next)."""
    y = dt.year + years
    m = dt.month - 1 + months
    y += m // 12
    m = m % 12 + 1
    base = datetime(y, m, 1, dt.hour, dt.minute, dt.second,
                    dt.microsecond, dt.tzinfo)
    return base + timedelta(days=dt.day - 1 + days)


def date_diff(part: str, t1: datetime, t2: datetime) -> int:
    """DATE_DIFF — timestampfuncs.go:141-183 (sign via swap+negate)."""
    if _as_aware(t2) < _as_aware(t1):
        return -date_diff(part, t2, t1)
    a, b = _as_aware(t1), _as_aware(t2)
    dur = b - a
    if part == "YEAR":
        dy = t2.year - t1.year
        if (t2.month, t2.day) >= (t1.month, t1.day):
            return dy
        return dy - 1
    if part == "MONTH":
        return (t2.year * 12 + t2.month) - (t1.year * 12 + t1.month)
    secs = int(dur.total_seconds())
    if part == "DAY":
        return secs // 86400
    if part == "HOUR":
        return secs // 3600
    if part == "MINUTE":
        return secs // 60
    if part == "SECOND":
        return secs
    raise SelectError(f"DATE_DIFF: unknown time part {part}")


_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]

_TOSTRING_TOKEN = re.compile(r"'(?:[^']|'')*'|y{1,4}|M{1,4}|d{1,2}|a"
                             r"|h{1,2}|H{1,2}|m{1,2}|s{1,2}|S{1,6}|n"
                             r"|X{1,5}|x{1,5}|.")


def to_string(dt: datetime, pattern: str) -> str:
    """TO_STRING(ts, pattern) with the Ion/AWS token set: y yyyy M MM MMM
    MMMM d dd a h hh H HH m mm s ss S.. n X.. x.. and 'quoted' literals."""
    out: list[str] = []
    off = int((dt.utcoffset() or timedelta(0)).total_seconds())
    hour12 = dt.hour % 12 or 12
    for tok in _TOSTRING_TOKEN.findall(pattern):
        if tok.startswith("'"):
            out.append(tok[1:-1].replace("''", "'"))
        elif tok in ("y", "yyy"):
            out.append(str(dt.year))
        elif tok == "yy":
            out.append(f"{dt.year % 100:02d}")
        elif tok == "yyyy":
            out.append(f"{dt.year:04d}")
        elif tok == "M":
            out.append(str(dt.month))
        elif tok == "MM":
            out.append(f"{dt.month:02d}")
        elif tok == "MMM":
            out.append(_MONTHS[dt.month - 1][:3])
        elif tok == "MMMM":
            out.append(_MONTHS[dt.month - 1])
        elif tok == "d":
            out.append(str(dt.day))
        elif tok == "dd":
            out.append(f"{dt.day:02d}")
        elif tok == "a":
            out.append("AM" if dt.hour < 12 else "PM")
        elif tok == "h":
            out.append(str(hour12))
        elif tok == "hh":
            out.append(f"{hour12:02d}")
        elif tok == "H":
            out.append(str(dt.hour))
        elif tok == "HH":
            out.append(f"{dt.hour:02d}")
        elif tok == "m":
            out.append(str(dt.minute))
        elif tok == "mm":
            out.append(f"{dt.minute:02d}")
        elif tok == "s":
            out.append(str(dt.second))
        elif tok == "ss":
            out.append(f"{dt.second:02d}")
        elif tok[0] == "S":
            digits = len(tok)
            out.append(f"{dt.microsecond:06d}"[:digits].ljust(digits, "0"))
        elif tok == "n":
            out.append(str(dt.microsecond * 1000))
        elif tok[0] in ("X", "x"):
            if off == 0 and tok[0] == "X":
                out.append("Z")
            else:
                sign = "+" if off >= 0 else "-"
                ao = abs(off)
                if len(tok) == 1:
                    out.append(f"{sign}{ao // 3600:02d}")
                elif len(tok) in (2, 4):
                    out.append(f"{sign}{ao // 3600:02d}"
                               f"{(ao % 3600) // 60:02d}")
                else:
                    out.append(f"{sign}{ao // 3600:02d}:"
                               f"{(ao % 3600) // 60:02d}")
        else:
            out.append(tok)
    return "".join(out)
