"""S3 Select — SQL over CSV/JSON objects.

Role-equivalent of pkg/s3select (22k LoC in the reference: sql parser +
evaluator, csv/json/parquet readers, RecordBatch responses). This build
covers the working core: the S3 Select SQL dialect over CSV (headers,
custom delimiters, gzip/bz2) and JSON (LINES/DOCUMENT), streamed back in
the AWS event-stream framing real SDKs parse. Parquet needs an arrow
reader this image doesn't ship — the reader interface is the seam.
"""

from minio_tpu.s3select.engine import S3SelectRequest, run_select

__all__ = ["S3SelectRequest", "run_select"]
