"""AWS event-stream framing for the SelectObjectContent response.

The wire format S3 SDKs parse (pkg/s3select/message.go role): each
message is

    [4B total length][4B headers length][4B prelude CRC32]
    [headers][payload][4B message CRC32]

headers are (1B name-len, name, 1B type=7 string, 2B value-len, value).
The response stream is Records* Stats End (Progress/Cont omitted — they
are optional keep-alives).
"""

from __future__ import annotations

import struct
import zlib


def _headers(pairs: dict[str, str]) -> bytes:
    out = bytearray()
    for name, value in pairs.items():
        nb = name.encode()
        vb = value.encode()
        out += bytes([len(nb)]) + nb + b"\x07" + struct.pack(">H", len(vb)) + vb
    return bytes(out)


def encode_message(headers: dict[str, str], payload: bytes) -> bytes:
    h = _headers(headers)
    total = 12 + len(h) + len(payload) + 4
    prelude = struct.pack(">II", total, len(h))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + h + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_message(payload: bytes) -> bytes:
    return encode_message({
        ":message-type": "event",
        ":event-type": "Records",
        ":content-type": "application/octet-stream",
    }, payload)


def stats_message(bytes_scanned: int, bytes_processed: int,
                  bytes_returned: int) -> bytes:
    xml = (f'<Stats xmlns=""><BytesScanned>{bytes_scanned}</BytesScanned>'
           f'<BytesProcessed>{bytes_processed}</BytesProcessed>'
           f'<BytesReturned>{bytes_returned}</BytesReturned></Stats>'
           ).encode()
    return encode_message({
        ":message-type": "event",
        ":event-type": "Stats",
        ":content-type": "text/xml",
    }, xml)


def end_message() -> bytes:
    return encode_message({
        ":message-type": "event",
        ":event-type": "End",
    }, b"")


# --- decoding (tests + any client tooling) ----------------------------------

def decode_stream(data: bytes) -> list[tuple[dict, bytes]]:
    """Parse a concatenated event stream into (headers, payload) pairs,
    verifying both CRCs."""
    out = []
    pos = 0
    while pos < len(data):
        total, hlen = struct.unpack_from(">II", data, pos)
        pcrc = struct.unpack_from(">I", data, pos + 8)[0]
        if zlib.crc32(data[pos:pos + 8]) != pcrc:
            raise ValueError("prelude CRC mismatch")
        msg = data[pos:pos + total]
        mcrc = struct.unpack_from(">I", msg, total - 4)[0]
        if zlib.crc32(msg[:total - 4]) != mcrc:
            raise ValueError("message CRC mismatch")
        hdr_raw = msg[12:12 + hlen]
        headers = {}
        i = 0
        while i < len(hdr_raw):
            nlen = hdr_raw[i]
            name = hdr_raw[i + 1:i + 1 + nlen].decode()
            i += 1 + nlen
            assert hdr_raw[i] == 7
            vlen = struct.unpack_from(">H", hdr_raw, i + 1)[0]
            value = hdr_raw[i + 3:i + 3 + vlen].decode()
            headers[name] = value
            i += 3 + vlen
        payload = msg[12 + hlen:total - 4]
        out.append((headers, payload))
        pos += total
    return out
