"""The S3 Select SQL dialect: tokenizer, recursive-descent parser,
evaluator (pkg/s3select/sql role).

Supported: SELECT <*|expr [AS alias], ...> FROM S3Object[.path] [alias]
[WHERE expr] [LIMIT n]; operators || * / % + - = != <> < <= > >= AND OR
NOT, LIKE [ESCAPE], IN (...), BETWEEN, IS [NOT] NULL/MISSING; aggregates
COUNT/SUM/AVG/MIN/MAX; scalar functions CAST, LOWER, UPPER, TRIM,
CHAR_LENGTH, CHARACTER_LENGTH, SUBSTRING, COALESCE, NULLIF.

Values are dynamically typed (MISSING ≠ NULL, matching the reference's
sql.Value); CSV fields arrive as strings and comparisons against numeric
operands coerce when the text parses as a number.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

MISSING = object()          # absent column (distinct from SQL NULL)


class SelectError(Exception):
    pass


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+\.\d*|\.\d+|\d+)
    | (?P<dqident>"(?:[^"]|"")*")
    | (?P<string>'(?:[^']|'')*')
    | (?P<op><>|!=|<=|>=|\|\||[=<>(),.*/%+\-])
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )""", re.VERBOSE)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "LIMIT", "AS", "AND", "OR", "NOT", "LIKE",
    "ESCAPE", "IN", "BETWEEN", "IS", "NULL", "MISSING", "TRUE", "FALSE",
    "CAST", "COUNT", "SUM", "AVG", "MIN", "MAX", "LOWER", "UPPER", "TRIM",
    "CHAR_LENGTH", "CHARACTER_LENGTH", "SUBSTRING", "COALESCE", "NULLIF",
    "INT", "INTEGER", "FLOAT", "DECIMAL", "NUMERIC", "STRING", "BOOL",
    "BOOLEAN", "VARCHAR", "FOR",
}


@dataclass
class Tok:
    kind: str      # number | string | ident | kw | op | eof
    text: str


def tokenize(src: str) -> list[Tok]:
    out: list[Tok] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            if src[pos:].strip() == "":
                break
            raise SelectError(f"bad token at {src[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup == "number":
            out.append(Tok("number", m.group("number")))
        elif m.lastgroup == "string":
            out.append(Tok("string",
                           m.group("string")[1:-1].replace("''", "'")))
        elif m.lastgroup == "dqident":
            out.append(Tok("ident",
                           m.group("dqident")[1:-1].replace('""', '"')))
        elif m.lastgroup == "op":
            out.append(Tok("op", m.group("op")))
        else:
            word = m.group("ident")
            up = word.upper()
            out.append(Tok("kw", up) if up in _KEYWORDS
                       else Tok("ident", word))
    out.append(Tok("eof", ""))
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Lit:
    value: Any


@dataclass
class Col:
    name: str          # "" means whole record; "_N" positional


@dataclass
class Unary:
    op: str
    e: Any


@dataclass
class Binary:
    op: str
    l: Any
    r: Any


@dataclass
class Like:
    e: Any
    pattern: Any
    escape: str | None
    negate: bool


@dataclass
class InList:
    e: Any
    items: list
    negate: bool


@dataclass
class Between:
    e: Any
    lo: Any
    hi: Any
    negate: bool


@dataclass
class IsNull:
    e: Any
    negate: bool
    missing: bool


@dataclass
class Func:
    name: str
    args: list
    star: bool = False          # COUNT(*)
    cast_type: str = ""         # CAST


@dataclass
class Projection:
    expr: Any                   # None == *
    alias: str


@dataclass
class Query:
    projections: list[Projection]
    alias: str
    where: Any
    limit: int | None
    aggregates: list = field(default_factory=list)   # Func nodes


_AGG = {"COUNT", "SUM", "AVG", "MIN", "MAX"}


class Parser:
    def __init__(self, toks: list[Tok], ):
        self.toks = toks
        self.i = 0
        self.aggs: list[Func] = []

    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, text: str | None = None) -> Tok | None:
        t = self.peek()
        if t.kind == kind and (text is None or t.text == text):
            return self.next()
        return None

    def expect(self, kind: str, text: str | None = None) -> Tok:
        t = self.accept(kind, text)
        if t is None:
            raise SelectError(
                f"expected {text or kind}, got {self.peek().text!r}")
        return t

    # -- grammar --

    def parse(self) -> Query:
        self.expect("kw", "SELECT")
        projections = [self.projection()]
        while self.accept("op", ","):
            projections.append(self.projection())
        self.expect("kw", "FROM")
        alias = self.from_clause()
        where = None
        if self.accept("kw", "WHERE"):
            where = self.expr()
        limit = None
        if self.accept("kw", "LIMIT"):
            limit = int(self.expect("number").text)
        self.expect("eof")
        return Query(projections, alias, where, limit, self.aggs)

    def projection(self) -> Projection:
        if self.accept("op", "*"):
            return Projection(None, "")
        e = self.expr()
        alias = ""
        if self.accept("kw", "AS"):
            alias = self.next().text
        elif self.peek().kind == "ident":
            alias = self.next().text
        return Projection(e, alias)

    def from_clause(self) -> str:
        t = self.next()
        if t.kind not in ("ident", "kw") or not t.text.upper().startswith(
                "S3OBJECT"):
            raise SelectError("FROM must reference S3Object")
        while self.accept("op", "."):
            self.next()  # S3Object.path — path is applied by the reader
        if self.peek().kind == "ident":
            return self.next().text
        return ""

    # precedence: OR < AND < NOT < comparison < additive < multiplicative
    def expr(self):
        e = self.and_expr()
        while self.accept("kw", "OR"):
            e = Binary("OR", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.accept("kw", "AND"):
            e = Binary("AND", e, self.not_expr())
        return e

    def not_expr(self):
        if self.accept("kw", "NOT"):
            return Unary("NOT", self.not_expr())
        return self.comparison()

    def comparison(self):
        e = self.additive()
        negate = bool(self.accept("kw", "NOT"))
        t = self.peek()
        if t.kind == "op" and t.text in ("=", "!=", "<>", "<", "<=", ">", ">="):
            if negate:
                raise SelectError("NOT before comparison operator")
            op = self.next().text
            return Binary("<>" if op == "!=" else op, e, self.additive())
        if self.accept("kw", "LIKE"):
            pat = self.additive()
            esc = None
            if self.accept("kw", "ESCAPE"):
                esc = self.expect("string").text
            return Like(e, pat, esc, negate)
        if self.accept("kw", "IN"):
            self.expect("op", "(")
            items = [self.expr()]
            while self.accept("op", ","):
                items.append(self.expr())
            self.expect("op", ")")
            return InList(e, items, negate)
        if self.accept("kw", "BETWEEN"):
            lo = self.additive()
            self.expect("kw", "AND")
            return Between(e, lo, self.additive(), negate)
        if self.accept("kw", "IS"):
            neg2 = bool(self.accept("kw", "NOT"))
            if self.accept("kw", "MISSING"):
                return IsNull(e, neg2, missing=True)
            self.expect("kw", "NULL")
            return IsNull(e, neg2, missing=False)
        if negate:
            raise SelectError("dangling NOT")
        return e

    def additive(self):
        e = self.multiplicative()
        while True:
            if self.accept("op", "+"):
                e = Binary("+", e, self.multiplicative())
            elif self.accept("op", "-"):
                e = Binary("-", e, self.multiplicative())
            elif self.accept("op", "||"):
                e = Binary("||", e, self.multiplicative())
            else:
                return e

    def multiplicative(self):
        e = self.unary()
        while True:
            if self.accept("op", "*"):
                e = Binary("*", e, self.unary())
            elif self.accept("op", "/"):
                e = Binary("/", e, self.unary())
            elif self.accept("op", "%"):
                e = Binary("%", e, self.unary())
            else:
                return e

    def unary(self):
        if self.accept("op", "-"):
            return Unary("-", self.unary())
        if self.accept("op", "+"):
            return self.unary()
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            txt = t.text
            return Lit(float(txt) if "." in txt else int(txt))
        if t.kind == "string":
            self.next()
            return Lit(t.text)
        if t.kind == "kw" and t.text in ("TRUE", "FALSE"):
            self.next()
            return Lit(t.text == "TRUE")
        if t.kind == "kw" and t.text == "NULL":
            self.next()
            return Lit(None)
        if self.accept("op", "("):
            e = self.expr()
            self.expect("op", ")")
            return e
        if t.kind == "kw" and (t.text in _AGG or t.text in (
                "CAST", "LOWER", "UPPER", "TRIM", "CHAR_LENGTH",
                "CHARACTER_LENGTH", "SUBSTRING", "COALESCE", "NULLIF")):
            return self.func()
        if t.kind in ("ident",):
            return self.column()
        raise SelectError(f"unexpected {t.text!r}")

    def func(self):
        name = self.next().text
        self.expect("op", "(")
        if name == "CAST":
            e = self.expr()
            self.expect("kw", "AS")
            ty = self.next().text.upper()
            self.expect("op", ")")
            return Func("CAST", [e], cast_type=ty)
        if name == "COUNT" and self.accept("op", "*"):
            self.expect("op", ")")
            f = Func("COUNT", [], star=True)
            self.aggs.append(f)
            return f
        if name == "SUBSTRING":
            args = [self.expr()]
            if self.accept("op", ","):
                args.append(self.expr())
                if self.accept("op", ","):
                    args.append(self.expr())
            elif self.accept("kw", "FROM"):
                args.append(self.expr())
                if self.accept("kw", "FOR"):
                    args.append(self.expr())
            else:
                raise SelectError("SUBSTRING needs FROM or comma arguments")
            self.expect("op", ")")
            return Func("SUBSTRING", args)
        args = []
        if not self.accept("op", ")"):
            args.append(self.expr())
            while self.accept("op", ","):
                args.append(self.expr())
            self.expect("op", ")")
        f = Func(name, args)
        if name in _AGG:
            self.aggs.append(f)
        return f

    def column(self):
        parts = [self.next().text]
        while self.accept("op", "."):
            parts.append(self.next().text)
        return Col(".".join(parts))


def parse(sql: str) -> Query:
    return Parser(tokenize(sql)).parse()


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

def _num(v):
    """Coerce to number when possible (CSV fields are text)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                return float(v)
            except ValueError:
                return None
    return None


def _cmp_pair(a, b):
    """Comparison operands: numeric compare when both sides look numeric,
    else string compare."""
    na, nb = _num(a), _num(b)
    if na is not None and nb is not None:
        return na, nb
    return str(a), str(b)


def _like_to_re(pattern: str, escape: str | None) -> re.Pattern:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.S)


class Evaluator:
    def __init__(self, query: Query):
        self.q = query
        self._like_cache: dict[tuple, re.Pattern] = {}
        # aggregate states, parallel to query.aggregates
        self.agg_state = [{"count": 0, "sum": 0.0, "min": None, "max": None}
                          for _ in query.aggregates]
        self.is_aggregate = bool(query.aggregates)

    # -- row evaluation --

    def eval(self, node, row: dict):
        if isinstance(node, Lit):
            return node.value
        if isinstance(node, Col):
            v = row.get(node.name, MISSING)
            if v is MISSING and "." in node.name:
                # First segment may be the table alias (s.age): drop it;
                # a remaining dotted path addresses nested JSON fields.
                rest = node.name.split(".", 1)[1]
                v = row.get(rest, MISSING)
                if v is MISSING:
                    v = row.get(node.name.rsplit(".", 1)[-1], MISSING)
            return v
        if isinstance(node, Unary):
            v = self.eval(node.e, row)
            if node.op == "NOT":
                return (not _truthy(v)) if v not in (None, MISSING) else None
            n = _num(v)
            return -n if n is not None else None
        if isinstance(node, Binary):
            return self._binary(node, row)
        if isinstance(node, Like):
            v = self.eval(node.e, row)
            pat = self.eval(node.pattern, row)
            if v in (None, MISSING) or pat in (None, MISSING):
                return None
            key = (pat, node.escape)
            rx = self._like_cache.get(key)
            if rx is None:
                rx = self._like_cache[key] = _like_to_re(str(pat), node.escape)
            hit = rx.match(str(v)) is not None
            return hit != node.negate
        if isinstance(node, InList):
            v = self.eval(node.e, row)
            if v in (None, MISSING):
                return None
            hit = False
            for item in node.items:
                a, b = _cmp_pair(v, self.eval(item, row))
                if a == b:
                    hit = True
                    break
            return hit != node.negate
        if isinstance(node, Between):
            v = self.eval(node.e, row)
            lo = self.eval(node.lo, row)
            hi = self.eval(node.hi, row)
            if v in (None, MISSING):
                return None
            a, l = _cmp_pair(v, lo)
            a2, h = _cmp_pair(v, hi)
            hit = l <= a and a2 <= h
            return hit != node.negate
        if isinstance(node, IsNull):
            v = self.eval(node.e, row)
            if node.missing:
                hit = v is MISSING
            else:
                hit = v is None or v is MISSING
            return hit != node.negate
        if isinstance(node, Func):
            return self._func(node, row)
        raise SelectError(f"cannot evaluate {node!r}")

    def _binary(self, node: Binary, row: dict):
        op = node.op
        if op in ("AND", "OR"):
            lv = self.eval(node.l, row)
            lt = _truthy(lv) if lv not in (None, MISSING) else None
            if op == "AND":
                if lt is False:
                    return False
                rv = self.eval(node.r, row)
                rt = _truthy(rv) if rv not in (None, MISSING) else None
                return rt if lt is True else (False if rt is False else None)
            if lt is True:
                return True
            rv = self.eval(node.r, row)
            rt = _truthy(rv) if rv not in (None, MISSING) else None
            return rt if lt is False else (True if rt is True else None)

        lv = self.eval(node.l, row)
        rv = self.eval(node.r, row)
        if lv in (None, MISSING) or rv in (None, MISSING):
            return None
        if op == "||":
            return str(lv) + str(rv)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            a, b = _cmp_pair(lv, rv)
            return {"=": a == b, "<>": a != b, "<": a < b,
                    "<=": a <= b, ">": a > b, ">=": a >= b}[op]
        a, b = _num(lv), _num(rv)
        if a is None or b is None:
            return None
        try:
            if op == "+":
                return a + b
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b
            if op == "%":
                return a % b
        except ZeroDivisionError:
            raise SelectError("division by zero") from None
        raise SelectError(f"bad operator {op}")

    def _func(self, node: Func, row: dict):
        name = node.name
        if name in _AGG:
            # During accumulation aggregates return their *index marker*;
            # final projection reads the state.
            idx = self.q.aggregates.index(node)
            return ("__agg__", idx)
        args = [self.eval(a, row) for a in node.args]
        if name == "CAST":
            return _cast(args[0], node.cast_type)
        if any(a is MISSING for a in args) and name != "COALESCE":
            return None
        if name == "LOWER":
            return None if args[0] is None else str(args[0]).lower()
        if name == "UPPER":
            return None if args[0] is None else str(args[0]).upper()
        if name == "TRIM":
            return None if args[0] is None else str(args[0]).strip()
        if name in ("CHAR_LENGTH", "CHARACTER_LENGTH"):
            return None if args[0] is None else len(str(args[0]))
        if name == "SUBSTRING":
            if args[0] is None:
                return None
            s = str(args[0])
            start = int(_num(args[1]) or 1)
            begin = max(start - 1, 0)
            if len(args) > 2:
                ln = int(_num(args[2]) or 0)
                return s[begin:begin + ln]
            return s[begin:]
        if name == "COALESCE":
            for a in args:
                if a not in (None, MISSING):
                    return a
            return None
        if name == "NULLIF":
            a, b = _cmp_pair(args[0], args[1])
            return None if a == b else args[0]
        raise SelectError(f"unknown function {name}")

    # -- aggregation --

    def accumulate(self, row: dict) -> None:
        for f, st in zip(self.q.aggregates, self.agg_state):
            if f.star:
                st["count"] += 1
                continue
            v = self.eval(f.args[0], row)
            if v in (None, MISSING):
                continue
            st["count"] += 1
            n = _num(v)
            if n is not None:
                st["sum"] += n
                st["min"] = n if st["min"] is None else min(st["min"], n)
                st["max"] = n if st["max"] is None else max(st["max"], n)

    def agg_value(self, f: Func) -> Any:
        st = self.agg_state[self.q.aggregates.index(f)]
        if f.name == "COUNT":
            return st["count"]
        if st["count"] == 0:
            return None
        if f.name == "SUM":
            return st["sum"]
        if f.name == "AVG":
            return st["sum"] / st["count"]
        if f.name == "MIN":
            return st["min"]
        return st["max"]

    # -- projection --

    def project(self, row: dict) -> dict:
        out: dict[str, Any] = {}
        for i, p in enumerate(self.q.projections):
            if p.expr is None:                       # SELECT *
                out.update(row)
                continue
            v = self.eval(p.expr, row)
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__agg__":
                v = self.agg_value(self.q.aggregates[v[1]])
            name = p.alias or _auto_name(p.expr, i)
            out[name] = v
        return out

    def where_matches(self, row: dict) -> bool:
        if self.q.where is None:
            return True
        return self.eval(self.q.where, row) is True


def _truthy(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.lower() == "true"
    return bool(v)


def _auto_name(expr, i: int) -> str:
    if isinstance(expr, Col):
        return expr.name
    return f"_{i + 1}"


def _cast(v, ty: str):
    if v in (None, MISSING):
        return None
    try:
        if ty in ("INT", "INTEGER"):
            return int(float(v)) if not isinstance(v, str) or "." in v \
                else int(v)
        if ty in ("FLOAT", "DECIMAL", "NUMERIC"):
            return float(v)
        if ty in ("STRING", "VARCHAR"):
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        if ty in ("BOOL", "BOOLEAN"):
            if isinstance(v, str):
                return v.lower() == "true"
            return bool(v)
    except (ValueError, TypeError):
        raise SelectError(f"cannot CAST {v!r} to {ty}") from None
    raise SelectError(f"unknown CAST type {ty}")
